"""Batched GF(2^255-19) field arithmetic in JAX (uint32 limbs).

TPU-first design notes
----------------------
- A field element is `uint32[20, ...batch]`: limbs on the LEADING axis so the
  batch axis maps onto TPU vector lanes; every op is elementwise across batch.
- Mixed-radix limbs (donna-style): limb i holds bits [s_i, s_{i+1}) of the
  value with s_i = ceil(12.75*i), widths alternating 13/13/13/12. The 20 limbs
  cover exactly 255 bits, so the wrap factor at limb 20 is exactly
  2^255 ≡ 19 (mod p) — no awkward 2^260-style folds.
- Schoolbook products: position s_i + s_j differs from s_{i+j} by 0 or 1 bits
  (superadditivity of ceil), absorbed by a static {1,2} multiplier matrix M.
  Accumulation bound: sum of ≤20 terms of 2·(2^13+ε)^2 < 2^32 — fits uint32
  with no wide accumulator, which TPUs don't have.
- All public ops return "carried" limbs: limb i < 2^{w_i} + 38 (loose bound;
  value ≡ correct mod p, value < 2^255 + small). `freeze` produces the unique
  canonical representative for byte encoding / comparison.

This replaces the per-signature scalar curve arithmetic the reference does in
Go (reference: crypto/ed25519/ed25519.go:148 via golang.org/x/crypto) with a
validator-axis-parallel implementation.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

P = 2**255 - 19
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

NLIMBS = 20
# Bit positions s_i = ceil(51*i/4) for i in 0..39 (covers product limbs too).
S = [math.ceil(51 * i / 4) for i in range(2 * NLIMBS + 1)]
assert S[NLIMBS] == 255
W = [S[i + 1] - S[i] for i in range(2 * NLIMBS)]  # limb widths (13 or 12)
for _k in range(NLIMBS, 2 * NLIMBS):
    assert S[_k] - S[_k - NLIMBS] == 255  # high limbs wrap with factor exactly 19

# M[i, j] = 2^(s_i + s_j - s_{i+j}) in {1, 2}
_M = np.zeros((NLIMBS, NLIMBS), dtype=np.uint32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        delta = S[_i] + S[_j] - S[_i + _j]
        assert delta in (0, 1)
        _M[_i, _j] = 1 << delta
M = jnp.asarray(_M)

# Anti-diagonal term lists split by M factor: prod_k = Σ_{M=1} a_i·b_j +
# 2·Σ_{M=2} a_i·b_j. Splitting turns the 400 per-element M-multiplies into 39
# shift-adds — the schoolbook product is the hottest loop in the framework.
_DIAG1 = [[] for _ in range(2 * NLIMBS - 1)]
_DIAG2 = [[] for _ in range(2 * NLIMBS - 1)]
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        (_DIAG1 if _M[_i, _j] == 1 else _DIAG2)[_i + _j].append((_i, _j))

_MASKS = np.array([(1 << w) - 1 for w in W], dtype=np.uint32)


def from_int(x: int) -> np.ndarray:
    """Host-side: python int -> canonical limbs, shape (20,)."""
    x %= P
    out = np.zeros(NLIMBS, dtype=np.uint32)
    for i in range(NLIMBS):
        out[i] = (x >> S[i]) & ((1 << W[i]) - 1)
    return out


def to_int(limbs) -> int:
    """Host-side: limbs -> python int (limbs need not be canonical)."""
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(arr[i]) << S[i] for i in range(arr.shape[0])) % P


def zeros_like_batch(batch_shape) -> jnp.ndarray:
    return jnp.zeros((NLIMBS, *batch_shape), dtype=jnp.uint32)


def const_fe(x: int, batch_shape=()) -> jnp.ndarray:
    """Broadcast a constant field element across a batch shape."""
    limbs = jnp.asarray(from_int(x))
    return jnp.broadcast_to(
        limbs.reshape((NLIMBS,) + (1,) * len(batch_shape)), (NLIMBS, *batch_shape)
    ).astype(jnp.uint32)


def _carry_pass(limbs_list, widths):
    """One sequential carry pass. limbs_list: python list of uint32 arrays.
    Returns (list of in-range limbs, final carry array)."""
    out = []
    carry = jnp.zeros_like(limbs_list[0])
    for k, x in enumerate(limbs_list):
        x = x + carry
        carry = x >> widths[k]
        out.append(x & jnp.uint32((1 << widths[k]) - 1))
    return out, carry


@jax.jit
def carry(x: jnp.ndarray) -> jnp.ndarray:
    """Two carry passes + wrap; output limbs < 2^{w_i} except limb0 < 2^13+38."""
    limbs = [x[i] for i in range(NLIMBS)]
    limbs, c = _carry_pass(limbs, W)
    limbs[0] = limbs[0] + jnp.uint32(19) * c  # 2^255 ≡ 19
    limbs, c = _carry_pass(limbs, W)
    limbs[0] = limbs[0] + jnp.uint32(19) * c  # c ∈ {0,1,2} here; limb0 stays < 2^13+38
    return jnp.stack(limbs)


@jax.jit
def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


# Limbs of 2p (non-canonical: limbs exceed their widths) with per-limb headroom
# >= 2^{w_i}+38 so (a + SUB2P - b) is non-negative limb-wise for any carried
# a, b (loose limb0 <= 2^13+37 included). Greedy top-down decomposition, then
# each limb borrows 2^{w_i} from the limb above (net zero).
_SUB2P = np.zeros(NLIMBS, dtype=np.uint32)
_rem = 2 * P
for _i in reversed(range(NLIMBS)):
    _SUB2P[_i] = _rem >> S[_i]
    _rem -= int(_SUB2P[_i]) << S[_i]
assert _rem == 0
for _i in range(NLIMBS - 1, 0, -1):
    _SUB2P[_i] -= 1
    _SUB2P[_i - 1] += 1 << W[_i - 1]
assert sum(int(_SUB2P[i]) << S[i] for i in range(NLIMBS)) == 2 * P
assert all(int(_SUB2P[i]) >= (1 << W[i]) + 38 for i in range(NLIMBS))
SUB2P = jnp.asarray(_SUB2P)


@jax.jit
def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (mod p). Inputs must be carried (limb_i < 2^{w_i}+38)."""
    shim = SUB2P.reshape((NLIMBS,) + (1,) * (a.ndim - 1))
    return carry(a + shim - b)


@jax.jit
def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


@jax.jit
def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply. Inputs carried; output carried."""
    # prod[k][...] = sum_{i+j=k} M[i,j] * a_i * b_j   (fits uint32, see header)
    t = a[:, None] * b[None, :, ...]  # (20, 20, ...batch)
    batch_shape = a.shape[1:]
    zero = jnp.zeros(batch_shape, dtype=jnp.uint32)
    prod = []
    for k in range(2 * NLIMBS - 1):
        s1 = zero
        for i, j in _DIAG1[k]:
            s1 = s1 + t[i, j]
        s2 = zero
        for i, j in _DIAG2[k]:
            s2 = s2 + t[i, j]
        prod.append(s1 + (s2 << jnp.uint32(1)))
    # Carry the 39-limb product, then fold high limbs down with factor 19.
    prod, c = _carry_pass(prod, W[: 2 * NLIMBS - 1])
    # carry c sits at position 39: s_39 = s_19 + 255 => folds to limb 19 x19
    prod[NLIMBS - 1] = prod[NLIMBS - 1] + jnp.uint32(19) * c
    lo = prod[:NLIMBS]
    for k in range(NLIMBS, 2 * NLIMBS - 1):
        lo[k - NLIMBS] = lo[k - NLIMBS] + jnp.uint32(19) * prod[k]
    return carry(jnp.stack(lo))


@jax.jit
def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant k < 2^18."""
    assert 0 < k < (1 << 18)
    return carry(a * jnp.uint32(k))


@jax.jit
def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical representative in [0, p). Input carried."""
    limbs = [a[i] for i in range(NLIMBS)]
    limbs, c = _carry_pass(limbs, W)
    limbs[0] = limbs[0] + jnp.uint32(19) * c
    limbs, c = _carry_pass(limbs, W)
    limbs[0] = limbs[0] + jnp.uint32(19) * c  # now value < 2^255 + 38
    limbs, c = _carry_pass(limbs, W)
    limbs[0] = limbs[0] + jnp.uint32(19) * c  # c<=1 and then limb0 < 57: no ripple
    # Conditional subtract p: y = x + 19; if y carries out of bit 255, x >= p
    # and the folded y (with the carry dropped) equals x - p.
    ylimbs = list(limbs)
    ylimbs[0] = ylimbs[0] + jnp.uint32(19)
    ylimbs, yc = _carry_pass(ylimbs, W)
    x = jnp.stack(limbs)
    y = jnp.stack(ylimbs)
    return jnp.where(yc[None] > 0, y, x)


@jax.jit
def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise field equality -> bool[...batch]."""
    return jnp.all(freeze(a) == freeze(b), axis=0)


@jax.jit
def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == 0, axis=0)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b with cond shaped like the batch."""
    return jnp.where(cond[None], a, b)


def bit(a: jnp.ndarray, i: int) -> jnp.ndarray:
    """Extract bit i of the canonical value. Input must be frozen."""
    k = 0
    while S[k + 1] <= i:
        k += 1
    return (a[k] >> jnp.uint32(i - S[k])) & jnp.uint32(1)


def from_bytes(b: jnp.ndarray, mask_high_bit: bool = True) -> jnp.ndarray:
    """Little-endian bytes uint8[32, ...batch] -> limbs (not reduced mod p).

    mask_high_bit drops bit 255 (the ed25519 sign bit)."""
    b = jnp.asarray(b).astype(jnp.uint32)
    if mask_high_bit:
        b = b.at[31].set(b[31] & jnp.uint32(0x7F))
    bits = jnp.stack(
        [(b[i // 8] >> jnp.uint32(i % 8)) & jnp.uint32(1) for i in range(256)]
    )  # (256, ...batch)
    limbs = []
    for i in range(NLIMBS):
        acc = jnp.zeros_like(bits[0])
        for j in range(W[i]):
            acc = acc + (bits[S[i] + j] << jnp.uint32(j))
        limbs.append(acc)
    # bit 255 (if unmasked) would be position 255 ≡ *19 — only reachable when
    # mask_high_bit=False; fold it.
    if not mask_high_bit:
        limbs[0] = limbs[0] + jnp.uint32(19) * bits[255]
    return carry(jnp.stack(limbs))


@jax.jit
def to_bytes(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical little-endian encoding uint8[32, ...batch]."""
    f = freeze(a)
    bits = []
    for i in range(NLIMBS):
        for j in range(W[i]):
            bits.append((f[i] >> jnp.uint32(j)) & jnp.uint32(1))
    bits.append(jnp.zeros_like(bits[0]))  # bit 255 = 0 in canonical form
    out = []
    for byte_i in range(32):
        acc = jnp.zeros_like(bits[0])
        for j in range(8):
            acc = acc + (bits[8 * byte_i + j] << jnp.uint32(j))
        out.append(acc)
    return jnp.stack(out).astype(jnp.uint8)


@jax.jit
def is_canonical_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """True iff the 255-bit value encoded (sign bit ignored) is < p."""
    v = from_bytes(b, mask_high_bit=True)
    limbs = [v[i] for i in range(NLIMBS)]
    limbs[0] = limbs[0] + jnp.uint32(19)
    _, c = _carry_pass(limbs, W)
    return c == 0


def _pow2k(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a^(2^k) via k squarings (fori_loop keeps the traced graph small)."""
    if k <= 2:
        for _ in range(k):
            a = square(a)
        return a
    return jax.lax.fori_loop(0, k, lambda _, x: square(x), a)


def _z250(a: jnp.ndarray):
    """Shared ladder: returns (x^(2^250 - 1), x^11, x^9). Classic 25519 chain."""
    z2 = square(a)
    z8 = _pow2k(z2, 2)
    z9 = mul(a, z8)
    z11 = mul(z2, z9)
    z22 = square(z11)
    z_5_0 = mul(z9, z22)  # x^(2^5 - 1)
    z_10_5 = _pow2k(z_5_0, 5)
    z_10_0 = mul(z_10_5, z_5_0)
    z_20_10 = _pow2k(z_10_0, 10)
    z_20_0 = mul(z_20_10, z_10_0)
    z_40_20 = _pow2k(z_20_0, 20)
    z_40_0 = mul(z_40_20, z_20_0)
    z_50_40 = _pow2k(z_40_0, 10)
    z_50_0 = mul(z_50_40, z_10_0)
    z_100_50 = _pow2k(z_50_0, 50)
    z_100_0 = mul(z_100_50, z_50_0)
    z_200_100 = _pow2k(z_100_0, 100)
    z_200_0 = mul(z_200_100, z_100_0)
    z_250_200 = _pow2k(z_200_0, 50)
    z_250_0 = mul(z_250_200, z_50_0)
    return z_250_0, z11, z9


@jax.jit
def inv(a: jnp.ndarray) -> jnp.ndarray:
    """x^(p-2) = x^(2^255 - 21). inv(0) = 0."""
    z_250_0, z11, _ = _z250(a)
    z_255_5 = _pow2k(z_250_0, 5)
    return mul(z_255_5, z11)


@jax.jit
def pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """x^((p-5)/8) = x^(2^252 - 3)."""
    z_250_0, _, _ = _z250(a)
    z_252_2 = _pow2k(z_250_0, 2)
    return mul(z_252_2, a)
