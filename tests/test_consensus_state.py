"""Consensus state machine tests: locking/POL rules against the real
ConsensusState with validator stubs — no network.

These are the spec scenarios from the reference's consensus/state_test.go
(:343 LockNoPOL, :529 POLRelock, POLUnlock, :844 POLSafety, timeouts, commit).
The fixture is the analog of consensus/common_test.go: validatorStub (:81)
signs real votes; we drive cs by enqueueing peer messages and awaiting
event-bus events."""

import asyncio
import time

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import test_config
from tendermint_tpu.consensus.cs_state import ConsensusState
from tendermint_tpu.consensus.messages import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.consensus.round_state import RoundStepType
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.proxy.multi import AppConns, local_client_creator
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.sm_state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.blockstore import BlockStore
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.event_bus import (
    EVENT_NEW_ROUND_STEP,
    EventBus,
    query_for_event,
)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote


class ValidatorStub:
    """Signs real votes for injection as peer messages
    (reference: consensus/common_test.go:81 validatorStub)."""

    def __init__(self, priv: FilePV, index: int, chain_id: str):
        self.priv = priv
        self.index = index
        self.chain_id = chain_id
        self.address = priv.get_pub_key().address()

    def sign_vote(self, type_, height, round_, block_id: BlockID, raw: bool = False) -> Vote:
        vote = Vote(
            type=type_,
            height=height,
            round=round_,
            block_id=block_id,
            timestamp_ns=time.time_ns(),
            validator_address=self.address,
            validator_index=self.index,
        )
        if raw:
            # byzantine signing: bypass the double-sign guard
            import dataclasses

            sig = self.priv.priv_key.sign(vote.sign_bytes(self.chain_id))
            return dataclasses.replace(vote, signature=sig)
        return self.priv.sign_vote(self.chain_id, vote)


class Fixture:
    def __init__(self, n_vals: int, tmp_path, chain_id="cs-test-chain"):
        self.chain_id = chain_id
        privs = [FilePV(gen_ed25519(bytes([50 + i]) * 32)) for i in range(n_vals)]
        gen = GenesisDoc(
            chain_id=chain_id,
            validators=[GenesisValidator(p.get_pub_key(), 10) for p in privs],
        )
        gen.validate_and_complete()
        state = state_from_genesis(gen)
        # sort stubs to match validator-set order
        valset = state.validators
        by_addr = {p.get_pub_key().address(): p for p in privs}
        self.privs = [by_addr[v.address] for v in valset.validators]
        self.stubs = [
            ValidatorStub(p, i, chain_id) for i, p in enumerate(self.privs)
        ]

        app = KVStoreApplication()
        self.proxy = AppConns(local_client_creator(app))
        self.block_store = BlockStore(MemDB())
        self.state_store = StateStore(MemDB())
        self.state_store.save(state)
        self.event_bus = EventBus()
        self.mempool = Mempool(self.proxy.mempool)
        self.evpool = EvidencePool(MemDB(), self.state_store, self.block_store)
        self.evpool.set_state(state)
        self.block_exec = BlockExecutor(
            self.state_store, self.proxy.consensus, self.mempool, self.evpool,
            event_bus=self.event_bus, block_store=self.block_store,
        )
        cfg = test_config().consensus
        cfg.wal_path = str(tmp_path / "wal")
        # init chain through the app so app state matches height 0
        from tendermint_tpu.consensus.replay import Handshaker

        state = Handshaker(self.state_store, state, self.block_store, gen, self.event_bus).handshake(self.proxy)
        self.cs = ConsensusState(
            cfg, state, self.block_exec, self.block_store, self.mempool,
            self.evpool, WAL(str(tmp_path / "wal")), event_bus=self.event_bus,
            priv_validator=self.privs[0],  # we are validator 0
        )
        self.steps = self.event_bus.subscribe("test", query_for_event(EVENT_NEW_ROUND_STEP), 500)

    async def start(self):
        await self.cs.start()

    async def stop(self):
        await self.cs.stop()

    # -- helpers -----------------------------------------------------------

    async def wait_step(self, step: RoundStepType, height=None, round_=None, timeout=5.0):
        """Wait until cs publishes a NewRoundStep matching the criteria."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"waiting for {step.name} h={height} r={round_}; at "
                    f"{self.cs.rs.height}/{self.cs.rs.round}/{self.cs.rs.step.name}"
                )
            try:
                msg = await asyncio.wait_for(self.steps.next(), remaining)
            except asyncio.TimeoutError:
                continue
            d = msg.data
            if d.step != step.name:
                continue
            if height is not None and d.height != height:
                continue
            if round_ is not None and d.round != round_:
                continue
            return

    async def add_votes(self, type_, height, round_, block_id: BlockID, idxs):
        for i in idxs:
            vote = self.stubs[i].sign_vote(type_, height, round_, block_id)
            await self.cs.add_peer_message(VoteMessage(vote), f"stub-{i}")
        await self.drain()

    async def drain(self, t=0.08):
        await asyncio.sleep(t)

    def make_block(self, height: int, proposer_idx: int = 1, txs=()):
        """Build a valid proposal block signed state (block + parts)."""
        from tendermint_tpu.types.block import Commit as CommitT

        state = self.cs.state
        if height == state.initial_height:
            commit = CommitT(0, 0, BlockID(), ())
        else:
            commit = self.cs.rs.last_commit.make_commit()
        proposer = self.cs.rs.validators.validators[proposer_idx]
        block = self.block_exec.create_proposal_block(
            height, state, commit, proposer.address, time.time_ns()
        )
        parts = PartSet.from_data(block.encode())
        return block, parts

    async def inject_proposal(self, block, parts, round_: int, proposer_idx: int, pol_round=-1):
        bid = BlockID(block.hash(), parts.header)
        prop = Proposal(
            height=block.header.height, round=round_, pol_round=pol_round,
            block_id=bid, timestamp_ns=time.time_ns(),
        )
        prop = self.privs[proposer_idx].sign_proposal(self.chain_id, prop)
        await self.cs.add_peer_message(ProposalMessage(prop), f"stub-{proposer_idx}")
        for i in range(parts.total):
            await self.cs.add_peer_message(
                BlockPartMessage(block.header.height, round_, parts.get_part(i)),
                f"stub-{proposer_idx}",
            )
        await self.drain()


NIL = BlockID()


def run_async(coro):
    asyncio.run(coro)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def test_full_round_commits(tmp_path):
    """All validators vote for the proposal -> commit (state_test.go
    TestStateFullRound2 analog)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            # we are validator 0; proposer for h1/r0 may be any validator.
            if rs.proposal_block is None:
                # inject a proposal from the actual proposer
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            assert rs.proposal_block is not None
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid, [1, 2, 3])
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            commit = fx.block_store.load_seen_commit(1)
            assert sum(0 if s.absent() else 1 for s in commit.signatures) >= 3
        finally:
            await fx.stop()

    run_async(main())


def test_lock_no_pol_prevotes_locked_block(tmp_path):
    """Once locked, without a new POL we keep prevoting the locked block in
    later rounds and precommit nil elsewhere (state_test.go:343 LockNoPOL)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)

            # polka at round 0 -> we lock
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_block is not None
            assert fx.cs.rs.locked_round == 0

            # +2/3 precommit nil -> move to round 1, still locked
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)  # our internal prevote flows through the queue
            assert fx.cs.rs.locked_block is not None
            # our round-1 prevote must be for the LOCKED block
            prevotes = fx.cs.rs.votes.prevotes(1)
            our = prevotes.get_by_index(0)
            assert our is not None and our.block_id.hash == bid.hash

            # two nil prevotes (NO nil polka: 20/40) -> 2/3-any triggers
            # prevote-wait; on timeout we precommit nil but REMAIN locked
            # (unlock requires an actual nil polka, covered by
            # test_pol_unlock_on_nil_polka)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, NIL, [1, 2])
            await fx.drain(1.0)  # prevote-wait timeout (0.2s+delta) fires
            precommits = fx.cs.rs.votes.precommits(1)
            ourpc = precommits.get_by_index(0)
            assert ourpc is not None and ourpc.block_id.is_zero()
            assert fx.cs.rs.locked_block is not None  # still locked
        finally:
            await fx.stop()

    run_async(main())


def test_pol_relock_on_same_block(tmp_path):
    """A new polka for the SAME locked block in a later round relocks
    (state_test.go:529 POLRelock-ish)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            block, parts = rs.proposal_block, rs.proposal_block_parts
            bid = BlockID(block.hash(), parts.header)

            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_round == 0

            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)

            # polka for the same block at round 1
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_round == 1  # relocked
            precommits = fx.cs.rs.votes.precommits(1)
            ourpc = precommits.get_by_index(0)
            assert ourpc is not None and ourpc.block_id.hash == bid.hash
        finally:
            await fx.stop()

    run_async(main())


def test_pol_unlock_on_nil_polka(tmp_path):
    """+2/3 prevote nil in a later round unlocks (state_test.go POLUnlock)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)

            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_block is not None

            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)

            # nil polka in round 1 -> unlock, precommit nil
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, NIL, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_block is None
            assert fx.cs.rs.locked_round == -1
        finally:
            await fx.stop()

    run_async(main())


def test_pol_safety_no_prevote_for_unlocked_new_block(tmp_path):
    """Locked on block A; a DIFFERENT block polka'd in a round we didn't see
    as a POL must not get our prevote; but a polka we DO see for block B in a
    later round unlocks us and (without B) we precommit nil
    (state_test.go:844 POLSafety shape)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid_a = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)

            # lock on A
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_a, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_block is not None

            # round 1: others claim polka for unknown block B (we never get B's
            # parts) -> we unlock (saw the polka) and precommit nil
            fake_psh = PartSetHeader(total=1, hash=b"\x99" * 32)
            bid_b = BlockID(b"\x88" * 32, fake_psh)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)
            # our prevote in round 1 is for LOCKED A (we saw no POL for B yet)
            our = fx.cs.rs.votes.prevotes(1).get_by_index(0)
            assert our is not None and our.block_id.hash == bid_a.hash

            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid_b, [1, 2, 3])
            await fx.drain(0.4)
            # polka for B seen -> unlock; we don't have B -> precommit nil
            assert fx.cs.rs.locked_block is None
            ourpc = fx.cs.rs.votes.precommits(1).get_by_index(0)
            assert ourpc is not None and ourpc.block_id.is_zero()
        finally:
            await fx.stop()

    run_async(main())


def test_propose_timeout_leads_to_nil_prevote(tmp_path):
    """No proposal arrives -> propose timeout -> prevote nil."""

    async def main():
        fx = Fixture(4, tmp_path)
        # make sure we aren't the round-0 proposer: if we are, the test is
        # trivially different; force by picking a fixture where proposer != 0
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PREVOTE, height=1, timeout=10)
            rs = fx.cs.rs
            our = rs.votes.prevotes(rs.round).get_by_index(0)
            proposer_is_us = rs.validators.get_proposer().address == fx.stubs[0].address
            if not proposer_is_us:
                assert our is not None and our.block_id.is_zero()
        finally:
            await fx.stop()

    run_async(main())


def test_round_skip_on_future_round_votes(tmp_path):
    """+2/3 prevotes at a future round move us to that round
    (state_test.go round-skip behavior)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 3, NIL, [1, 2, 3])
            await fx.drain(0.5)
            assert fx.cs.rs.round == 3
        finally:
            await fx.stop()

    run_async(main())


def test_late_precommit_for_previous_height(tmp_path):
    """A precommit for height-1 arriving during NEW_HEIGHT is added to
    last_commit (addVote :1880 first branch)."""

    async def main():
        fx = Fixture(4, tmp_path)
        # slow down round0 so we stay in NEW_HEIGHT after a commit
        fx.cs.config.timeout_commit = 2.0
        fx.cs.config.skip_timeout_commit = False
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid, [1, 2])
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            # now at height 2, NEW_HEIGHT (commit timeout 2s); send the late precommit
            assert fx.cs.rs.height == 2
            before = sum(1 for s in fx.cs.rs.last_commit.bit_array() if s)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid, [3])
            await fx.drain(0.3)
            after = sum(1 for s in fx.cs.rs.last_commit.bit_array() if s)
            assert after == before + 1
        finally:
            await fx.stop()

    run_async(main())


def test_conflicting_votes_produce_evidence(tmp_path):
    """Equivocating prevotes from a stub produce DuplicateVoteEvidence in the
    pool (byzantine detection at the VoteSet level)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.2)
            psh = PartSetHeader(total=1, hash=b"\x11" * 32)
            bid1 = BlockID(b"\x22" * 32, psh)
            bid2 = BlockID(b"\x33" * 32, psh)
            v1 = fx.stubs[2].sign_vote(SignedMsgType.PREVOTE, 1, 0, bid1, raw=True)
            v2 = fx.stubs[2].sign_vote(SignedMsgType.PREVOTE, 1, 0, bid2, raw=True)
            await fx.cs.add_peer_message(VoteMessage(v1), "stub-2")
            await fx.cs.add_peer_message(VoteMessage(v2), "stub-2")
            await fx.drain(0.3)
            pend = fx.evpool.pending_evidence(-1)
            assert len(pend) == 1
            ev = pend[0]
            assert ev.vote_a.validator_address == fx.stubs[2].address
        finally:
            await fx.stop()

    run_async(main())


def test_unlock_then_commit_different_block_round1(tmp_path):
    """After unlocking, a polka + precommits for a new block B in round 1
    commits B (liveness after unlock)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            block_a = rs.proposal_block
            parts_a = rs.proposal_block_parts
            bid_a = BlockID(block_a.hash(), parts_a.header)

            # lock on A, then nil precommits move to round 1
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_a, [1, 2, 3])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)

            # commit A in round 1: polka + precommits for A (it's the locked block)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid_a, [1, 2, 3])
            await fx.drain(0.3)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 1, bid_a, [1, 2, 3])
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            saved = fx.block_store.load_block(1)
            assert saved.hash() == block_a.hash()
        finally:
            await fx.stop()

    run_async(main())
