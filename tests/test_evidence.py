"""Evidence pool unit tests: add/check/pending/update/expiry/committed dedup
(reference test model: evidence/pool_test.go, evidence/verify_test.go)."""

import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.crypto import gen_ed25519, tmhash
from tendermint_tpu.evidence.pool import EvidenceError, EvidencePool
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.state.sm_state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.types.basic import NANOS, BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.vote import Vote

CHAIN = "ev-chain"


def make_env():
    priv = gen_ed25519(b"\x31" * 32)
    gen = GenesisDoc(chain_id=CHAIN, validators=[GenesisValidator(priv.pub_key(), 10)])
    gen.validate_and_complete()
    state = state_from_genesis(gen)
    state_store = StateStore(MemDB())
    state_store.save(state)  # persists the valset at heights 1, 2
    pool = EvidencePool(MemDB(), state_store, block_store=None)
    return priv, state, state_store, pool


def make_equivocation(priv, height=1, total_power=10, val_power=10, ts=None):
    ts = ts if ts is not None else 1_700_000_000 * NANOS

    def vote(tag):
        bh = tmhash.sum256(tag)
        v = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=height,
            round=0,
            block_id=BlockID(bh, PartSetHeader(1, tmhash.sum256(bh))),
            timestamp_ns=ts,
            validator_address=priv.pub_key().address(),
            validator_index=0,
        )
        return v.with_signature(priv.sign(v.sign_bytes(CHAIN)))

    return DuplicateVoteEvidence.from_votes(
        vote(b"block-A"), vote(b"block-B"), ts, total_power, val_power
    )


def test_add_check_and_pending_lifecycle():
    priv, state, _, pool = make_env()
    import dataclasses

    state = dataclasses.replace(
        state, last_block_height=1, last_block_time_ns=1_700_000_100 * NANOS
    )
    pool.set_state(state)
    ev = make_equivocation(priv)

    pool.add_evidence(ev)
    assert pool.is_pending(ev)
    assert not pool.is_committed(ev)
    assert pool.pending_evidence(1 << 20) != []

    # idempotent re-add
    pool.add_evidence(ev)
    assert len(pool.pending_evidence(1 << 20)) == 1

    # commit: moves pending -> committed; re-add becomes a no-op
    pool.update(state, [ev])
    assert pool.is_committed(ev)
    assert not pool.is_pending(ev)
    assert pool.pending_evidence(1 << 20) == []
    with pytest.raises(EvidenceError):
        pool.check_evidence(state, ev)  # committed evidence is rejected


def test_bad_evidence_rejected():
    priv, state, _, pool = make_env()
    import dataclasses

    state = dataclasses.replace(
        state, last_block_height=1, last_block_time_ns=1_700_000_100 * NANOS
    )
    pool.set_state(state)

    # wrong validator power claimed
    with pytest.raises(EvidenceError):
        pool.add_evidence(make_equivocation(priv, val_power=99))
    # total power mismatch
    with pytest.raises(EvidenceError):
        pool.add_evidence(make_equivocation(priv, total_power=99))
    # validator not in the set
    outsider = gen_ed25519(b"\x32" * 32)
    with pytest.raises(EvidenceError):
        pool.add_evidence(make_equivocation(outsider))
    # forged signature: evidence verify fails
    ev = make_equivocation(priv)
    import dataclasses as dc

    forged = dc.replace(ev, vote_b=dc.replace(ev.vote_b, signature=b"\x00" * 64))
    with pytest.raises(Exception):
        pool.add_evidence(forged)


def test_add_from_consensus_validates_and_dedups():
    """Satellite: add_evidence_from_consensus stored with ZERO validation —
    now it must run basic checks, verify both signatures against the
    conflict's validator set, and suppress duplicates."""
    priv, state, _, pool = make_env()
    import dataclasses as dc

    state = dc.replace(
        state, last_block_height=1, last_block_time_ns=1_700_000_100 * NANOS
    )
    pool.set_state(state)
    ev = make_equivocation(priv)

    pool.add_evidence_from_consensus(ev, ev.timestamp_ns, state.validators)
    assert pool.is_pending(ev)
    # duplicate suppression: second add is a no-op, not a second row
    pool.add_evidence_from_consensus(ev, ev.timestamp_ns, state.validators)
    assert len(pool.pending_evidence(-1)) == 1

    # forged signature: rejected (this is the last gate before gossip)
    forged = dc.replace(ev, vote_b=dc.replace(ev.vote_b, signature=b"\x01" * 64))
    with pytest.raises(Exception):
        pool.add_evidence_from_consensus(forged, ev.timestamp_ns, state.validators)
    assert not pool.is_pending(forged)

    # wrong order (fails validate_basic)
    swapped = dc.replace(ev, vote_a=ev.vote_b, vote_b=ev.vote_a)
    with pytest.raises(ValueError):
        pool.add_evidence_from_consensus(swapped, ev.timestamp_ns, state.validators)

    # validator outside the provided set
    outsider = gen_ed25519(b"\x33" * 32)
    with pytest.raises(EvidenceError):
        pool.add_evidence_from_consensus(
            make_equivocation(outsider), ev.timestamp_ns, state.validators
        )

    # expired at discovery time
    params = state.consensus_params
    future = dataclasses_replace_expired(state, params)
    pool.set_state(future)
    old = make_equivocation(priv, ts=1_000_000_000 * NANOS)
    with pytest.raises(EvidenceError):
        pool.add_evidence_from_consensus(old, old.timestamp_ns, state.validators)


def dataclasses_replace_expired(state, params):
    import dataclasses

    return dataclasses.replace(
        state,
        last_block_height=1 + params.evidence.max_age_num_blocks + 1,
        last_block_time_ns=1_000_000_000 * NANOS
        + params.evidence.max_age_duration_ns
        + NANOS,
    )


def test_pending_evidence_max_bytes_cap():
    """Satellite: the max_bytes cap must bound what a proposal pulls — the
    first evidence that would cross the cap is excluded, -1 is unbounded."""
    priv, state, _, pool = make_env()
    import dataclasses

    state = dataclasses.replace(
        state, last_block_height=1, last_block_time_ns=1_700_000_100 * NANOS
    )
    pool.set_state(state)
    evs = [make_equivocation(priv, height=h) for h in (1, 2, 3)]
    for ev in evs:
        pool.add_evidence_from_consensus(ev, ev.timestamp_ns, state.validators)

    allp = pool.pending_evidence(-1)
    assert len(allp) == 3
    # iteration order is key order (height ascending)
    assert [e.height for e in allp] == [1, 2, 3]
    first_len = len(allp[0].encode())
    only_first = pool.pending_evidence(first_len)
    assert [e.height for e in only_first] == [1]
    assert pool.pending_evidence(0) == []


def test_expired_evidence_rejected_and_pruned():
    priv, state, _, pool = make_env()
    import dataclasses

    params = state.consensus_params
    old_ts = 1_000_000_000 * NANOS
    ev = make_equivocation(priv, ts=old_ts)

    # state far in the future: exceed BOTH age bounds
    future = dataclasses.replace(
        state,
        last_block_height=1 + params.evidence.max_age_num_blocks + 1,
        last_block_time_ns=old_ts + params.evidence.max_age_duration_ns + NANOS,
    )
    pool.set_state(future)
    with pytest.raises(EvidenceError):
        pool.add_evidence(ev)

    # pending evidence that expires later gets pruned by update()
    fresh = dataclasses.replace(
        state, last_block_height=1, last_block_time_ns=old_ts + NANOS
    )
    pool.set_state(fresh)
    pool.add_evidence(make_equivocation(priv, ts=old_ts))
    assert pool.pending_evidence(1 << 20)
    pool.update(future, [])
    assert pool.pending_evidence(1 << 20) == []
