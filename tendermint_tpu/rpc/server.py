"""JSON-RPC/HTTP/WebSocket server (reference: rpc/jsonrpc/server + rpc/core/routes.go:10-47).

Serves POST JSON-RPC, GET URI style, and /websocket subscriptions against the
node's internals (the reference's rpc/core Environment role)."""

from __future__ import annotations

import asyncio
import heapq
import json
import logging
import time
from typing import Any, Dict, Optional

from aiohttp import web, WSMsgType

from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs.pubsub import Query
from tendermint_tpu.light.service import (
    ErrBadRequest,
    ErrLightDisabled,
    ErrLightOverloaded,
    LightServiceError,
)
from tendermint_tpu.mempool.mempool import MempoolError
from tendermint_tpu.types.event_bus import EVENT_TX, TX_HASH_KEY, query_for_event
from tendermint_tpu.types.light import (
    block_id_to_json,
    commit_to_json,
    header_to_json,
    validator_to_json,
)

logger = logging.getLogger("tendermint_tpu.rpc")


def _b64(b: bytes) -> str:
    import base64

    return base64.b64encode(b).decode()


def _result(id_, result) -> dict:
    return {"jsonrpc": "2.0", "id": id_, "result": result}


def _error(id_, code, message, data="") -> dict:
    return {"jsonrpc": "2.0", "id": id_, "error": {"code": code, "message": message, "data": data}}


class RPCShedError(Exception):
    """Raised by the load gate when a sheddable request is refused; the
    transport layers translate it to HTTP 429 + Retry-After (JSON-RPC
    error -32005)."""


# JSON-RPC error codes (implementation-defined range)
ERR_SHED = -32005  # server overloaded, retry later
ERR_MEMPOOL = -32001  # mempool rejected the tx (data carries the reason)

# Methods the gate may refuse under load. Everything else — health, status,
# consensus introspection, net_info, the debug/unsafe routes — bypasses the
# gate: an operator must be able to see INTO an overloaded node, and
# consensus-critical paths are never shed.
SHEDDABLE_METHODS = frozenset({
    "broadcast_tx_async", "broadcast_tx_sync", "broadcast_tx_commit",
    "check_tx", "abci_query", "abci_info",
    "tx", "tx_status", "tx_search", "block_search",
    "block", "blockchain", "block_results", "block_by_hash", "commit",
    "unconfirmed_txs",
    # light-client serving (light/service.py): per-client admission rides
    # this gate (429 + Retry-After) so a light-verification flood can never
    # starve the live vote path; light_status bypasses like status
    "light_verify", "light_block",
})
# Under overload pressure (node/overload.py flips rpc_shed_writes before
# rpc_shed_reads), write-path methods shed first.
WRITE_METHODS = frozenset(
    {"broadcast_tx_async", "broadcast_tx_sync", "broadcast_tx_commit"}
)


class LoadGate:
    """Bounded-concurrency admission gate for sheddable RPC methods
    ([rpc] max_inflight_requests). Refusal is immediate (no queueing): an
    overloaded serving stack must fail fast with Retry-After, not build an
    unbounded backlog. The overload controller may additionally force-shed
    writes (shed_writes) or all sheddable methods (shed_reads)."""

    def __init__(self, max_inflight: int, metrics=None):
        self.max_inflight = max_inflight
        self.metrics = metrics  # RPCMetrics or None
        self.inflight = 0
        self.shed_total = 0
        self.shed_writes = False  # flipped by the overload controller
        self.shed_reads = False

    def admits(self, method: str) -> bool:
        if method not in SHEDDABLE_METHODS:
            return True
        if self.shed_reads:
            return False
        if self.shed_writes and method in WRITE_METHODS:
            return False
        return self.max_inflight <= 0 or self.inflight < self.max_inflight

    def record_shed(self, method: str) -> None:
        self.shed_total += 1
        if self.metrics is not None:
            self.metrics.shed_requests.labels(method).inc()

    def enter(self) -> None:
        self.inflight += 1
        if self.metrics is not None:
            self.metrics.inflight_requests.set(self.inflight)

    def exit(self) -> None:
        self.inflight -= 1
        if self.metrics is not None:
            self.metrics.inflight_requests.set(self.inflight)


class SlowRequestRing:
    """Bounded top-N-by-duration request ring (ISSUE 10): the structured
    annotations an operator reads at GET /debug/rpc to answer "why was my
    request slow" — method, wall duration, outcome, error detail, and the
    gate pressure (inflight count + shed switches) the request saw at
    dispatch. A min-heap keyed on duration keeps exactly the N slowest;
    offering a faster-than-the-floor request is O(1)."""

    def __init__(self, cap: int = 32):
        self.cap = max(1, int(cap))
        self._heap: list = []  # (duration_s, seq, entry)
        self._seq = 0

    def offer(self, duration_s: float, entry: dict) -> None:
        if len(self._heap) >= self.cap and duration_s <= self._heap[0][0]:
            return
        self._seq += 1
        heapq.heappush(self._heap, (duration_s, self._seq, entry))
        while len(self._heap) > self.cap:
            heapq.heappop(self._heap)

    def snapshot(self) -> list:
        """Slowest first."""
        return [e for _, _, e in sorted(self._heap, key=lambda t: -t[0])]


class RPCServer:
    def __init__(self, node):
        self.node = node
        addr = node.config.rpc.laddr.replace("tcp://", "")
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port) if port else 0  # 0: handler-only (LocalClient)
        self.app = web.Application(client_max_size=node.config.rpc.max_body_bytes)
        self.app.router.add_post("/", self._handle_jsonrpc)
        self.app.router.add_get("/metrics", self._handle_metrics)
        self.app.router.add_get("/websocket", self._handle_websocket)
        # flight-recorder dumps (libs/trace.py); two path segments, so they
        # need explicit routes ahead of the generic /{method} catch-all
        self.app.router.add_get("/debug", self._handle_debug_index)
        self.app.router.add_get("/debug/trace", self._handle_debug_trace)
        self.app.router.add_get("/debug/verify_stats", self._handle_debug_verify_stats)
        self.app.router.add_get(
            "/debug/consensus_timeline", self._handle_debug_consensus_timeline
        )
        self.app.router.add_get("/debug/overload", self._handle_debug_overload)
        self.app.router.add_get("/debug/mesh", self._handle_debug_mesh)
        self.app.router.add_get("/debug/slo", self._handle_debug_slo)
        self.app.router.add_get("/debug/light", self._handle_debug_light)
        self.app.router.add_get("/debug/tx_trace", self._handle_debug_tx_trace)
        self.app.router.add_get("/debug/rpc", self._handle_debug_rpc)
        self.app.router.add_get(
            "/debug/device_profile", self._handle_debug_device_profile
        )
        self.app.router.add_get("/{method}", self._handle_uri)
        self.runner: Optional[web.AppRunner] = None
        # load-shedding gate ([rpc] max_inflight_requests); the overload
        # controller (node/overload.py) reads inflight and flips the
        # shed_writes/shed_reads switches
        rpc_metrics = getattr(getattr(node, "metrics", None), "rpc", None)
        self.gate = LoadGate(
            getattr(node.config.rpc, "max_inflight_requests", 0),
            metrics=rpc_metrics,
        )
        self._routes = {
            "health": self._health,
            "status": self._status,
            "broadcast_tx_async": self._broadcast_tx_async,
            "broadcast_tx_sync": self._broadcast_tx_sync,
            "broadcast_tx_commit": self._broadcast_tx_commit,
            "abci_query": self._abci_query,
            "abci_info": self._abci_info,
            "block": self._block,
            "blockchain": self._blockchain,
            "commit": self._commit,
            "validators": self._validators,
            "genesis": self._genesis,
            "tx": self._tx,
            "unconfirmed_txs": self._unconfirmed_txs,
            "num_unconfirmed_txs": self._num_unconfirmed_txs,
            "consensus_state": self._consensus_state,
            "dump_consensus_state": self._dump_consensus_state,
            "consensus_params": self._consensus_params,
            "net_info": self._net_info,
            "tx_search": self._tx_search,
            "block_search": self._block_search,
            "block_results": self._block_results,
            "block_by_hash": self._block_by_hash,
            "broadcast_evidence": self._broadcast_evidence,
            "check_tx": self._check_tx,
            "dial_peers": self._dial_peers,
            "dial_seeds": self._dial_seeds,
            "unsafe_flush_mempool": self._unsafe_flush_mempool,
            "unsafe_dump_stacks": self._unsafe_dump_stacks,
            "unsafe_dump_heap": self._unsafe_dump_heap,
            "debug_trace": self._debug_trace,
            "debug_verify_stats": self._debug_verify_stats,
            "consensus_timeline": self._consensus_timeline,
            "debug_overload": self._debug_overload,
            "debug_mesh": self._debug_mesh,
            "debug_slo": self._debug_slo,
            "debug_index": self._debug_index,
            "debug_device_profile": self._debug_device_profile,
            # light-client-as-a-service (light/service.py)
            "light_verify": self._light_verify,
            "light_block": self._light_block,
            "light_status": self._light_status,
            "debug_light": self._debug_light,
            # transaction & request observatory (libs/txtrace.py, ISSUE 10)
            "tx_status": self._tx_status,
            "debug_tx_trace": self._debug_tx_trace,
            "debug_rpc": self._debug_rpc,
        }
        # per-method request telemetry (ISSUE 10): every transport routes
        # through _dispatch, which observes duration + outcome per method
        # (label cardinality bounded to this route table; unknown methods
        # fold into "_other") and feeds the slowest requests into a bounded
        # top-N ring served at GET /debug/rpc
        self.slow_ring = SlowRequestRing(cap=32)
        self._method_agg: Dict[str, dict] = {}

    # -- load shedding -------------------------------------------------------

    async def _dispatch(self, method: str, handler, params):
        """All transports (JSON-RPC POST, URI GET, websocket; LocalClient
        too) route through the gate here; a refused request raises
        RPCShedError for the transport to translate (HTTP 429 +
        Retry-After). Every dispatched request — admitted or shed — is
        observed once: per-method duration histogram + outcome counter
        (tendermint_rpc_request_*), the rpc_request_p99 SLO budget, and the
        slow-request ring behind GET /debug/rpc."""
        t0 = time.perf_counter()
        inflight0 = self.gate.inflight
        if not self.gate.admits(method):
            self.gate.record_shed(method)
            self._observe_request(
                method, time.perf_counter() - t0, "shed", inflight0,
                error="gate refused (429)",
            )
            raise RPCShedError(method)
        entered = method in SHEDDABLE_METHODS
        if entered:
            self.gate.enter()
        outcome, error = "ok", None
        try:
            return await handler(params)
        except asyncio.CancelledError:
            # client disconnect / shutdown, not a request outcome — don't
            # mint error series or slow-ring entries for aborts
            outcome = None
            raise
        except ErrLightOverloaded as e:
            outcome, error = "shed", f"{e.code}: light overloaded"
            raise
        except MempoolError as e:
            # structured admission refusals are the serving path WORKING,
            # not erroring — attribute them separately from 500s
            outcome, error = "reject", f"mempool {getattr(e, 'reason', '?')}"
            raise
        except LightServiceError as e:
            outcome, error = "reject", f"{e.code}: {type(e).__name__}"
            raise
        except BaseException as e:
            outcome, error = "error", type(e).__name__
            raise
        finally:
            if entered:
                self.gate.exit()
            if outcome is not None:
                self._observe_request(
                    method, time.perf_counter() - t0, outcome, inflight0, error
                )

    def _method_label(self, method: str) -> str:
        """Bound the per-method label space to the declared route table —
        a client probing made-up method names must not mint unbounded
        metric series (they fold into `_other`)."""
        return method if method in self._routes else "_other"

    SLOW_RING_MIN_S = 0.001  # sub-ms requests never displace real evidence

    def _observe_request(
        self,
        method: str,
        seconds: float,
        outcome: str,
        inflight0: int,
        error: Optional[str] = None,
    ) -> None:
        label = self._method_label(method)
        served = outcome != "shed"
        m = self.gate.metrics  # RPCMetrics or None
        if m is not None:
            if served:
                # sheds refuse in microseconds: feeding them into the
                # latency histogram (or the p99 SLO below) would collapse
                # the per-method p99 toward zero exactly while the node is
                # refusing traffic — shed visibility is requests_total
                # {outcome="shed"} + shed_requests_total, never latency
                m.request_duration.labels(label).observe(seconds)
            m.requests.labels(label, outcome).inc()
        slo = getattr(self.node, "slo", None)
        if slo is not None and served:
            slo.observe("rpc_request_p99", seconds)
        agg = self._method_agg.get(label)
        if agg is None:
            agg = self._method_agg[label] = {
                "count": 0, "ok": 0, "shed": 0, "reject": 0, "error": 0,
                "total_s": 0.0, "max_ms": 0.0,
            }
        agg["count"] += 1
        agg[outcome] = agg.get(outcome, 0) + 1
        if served:
            agg["total_s"] += seconds
            if seconds * 1e3 > agg["max_ms"]:
                agg["max_ms"] = round(seconds * 1e3, 3)
        if seconds >= self.SLOW_RING_MIN_S:
            self.slow_ring.offer(
                seconds,
                {
                    "method": label,
                    "duration_ms": round(seconds * 1e3, 3),
                    "ts": round(time.time(), 3),
                    "outcome": outcome,
                    "error": error,
                    # gate pressure at dispatch: admission is immediate (no
                    # queue wait), so congestion shows as inflight depth and
                    # flipped shed switches rather than waiting time
                    "inflight_at_dispatch": inflight0,
                    "shed_writes": self.gate.shed_writes,
                    "shed_reads": self.gate.shed_reads,
                },
            )

    def _shed_response(self, id_, method: str) -> web.Response:
        retry_after = getattr(self.node.config.rpc, "shed_retry_after", 1.0)
        return web.json_response(
            _error(
                id_, ERR_SHED, "server overloaded",
                {"method": method, "retry_after": retry_after},
            ),
            status=429,
            headers={"Retry-After": f"{retry_after:g}"},
        )

    @staticmethod
    def _mempool_reject(id_, e) -> dict:
        """Structured JSON-RPC error for a mempool admission rejection —
        the reject reason (full/evicted/cache/quota/too_large) is data, not
        a 500 with a bare traceback."""
        return _error(
            id_, ERR_MEMPOOL, "mempool rejected tx",
            {"reason": getattr(e, "reason", "rejected"), "detail": str(e)},
        )

    async def start(self) -> None:
        self.runner = web.AppRunner(self.app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, self.host, self.port)
        await site.start()
        logger.info("RPC server listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self.runner:
            await self.runner.cleanup()

    # -- transport ----------------------------------------------------------

    async def _handle_jsonrpc(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(_error(None, -32700, "parse error"))
        id_ = body.get("id")
        method = body.get("method", "")
        params = body.get("params", {}) or {}
        handler = self._routes.get(method)
        if handler is None:
            return web.json_response(_error(id_, -32601, f"method {method} not found"))
        try:
            result = await self._dispatch(method, handler, params)
            return web.json_response(_result(id_, result))
        except RPCShedError:
            return self._shed_response(id_, method)
        except ErrLightOverloaded:
            return self._shed_response(id_, method)
        except MempoolError as e:
            return web.json_response(self._mempool_reject(id_, e))
        except LightServiceError as e:
            return web.json_response(_error(id_, e.code, str(e), e.data))
        except Exception as e:
            logger.exception("rpc error in %s", method)
            return web.json_response(_error(id_, -32603, "internal error", str(e)))

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition (reference: the :26660 /metrics
        endpoint, node/node.go:861; served on the RPC listener here)."""
        if not self.node.config.instrumentation.prometheus:
            return web.Response(status=404, text="instrumentation disabled")
        return web.Response(
            text=self.node.metrics.expose(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def _handle_debug_trace(self, request: web.Request) -> web.Response:
        params = {k: v for k, v in request.query.items()}
        try:
            return web.json_response(_result(None, await self._debug_trace(params)))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_verify_stats(self, request: web.Request) -> web.Response:
        try:
            return web.json_response(_result(None, await self._debug_verify_stats({})))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_consensus_timeline(self, request: web.Request) -> web.Response:
        params = {k: v for k, v in request.query.items()}
        try:
            return web.json_response(
                _result(None, await self._consensus_timeline(params))
            )
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_overload(self, request: web.Request) -> web.Response:
        try:
            return web.json_response(_result(None, await self._debug_overload({})))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_mesh(self, request: web.Request) -> web.Response:
        try:
            return web.json_response(_result(None, await self._debug_mesh({})))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_slo(self, request: web.Request) -> web.Response:
        try:
            return web.json_response(_result(None, await self._debug_slo({})))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_index(self, request: web.Request) -> web.Response:
        try:
            return web.json_response(_result(None, await self._debug_index({})))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_light(self, request: web.Request) -> web.Response:
        try:
            return web.json_response(_result(None, await self._debug_light({})))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_tx_trace(self, request: web.Request) -> web.Response:
        params = {k: v for k, v in request.query.items()}
        try:
            return web.json_response(
                _result(None, await self._debug_tx_trace(params))
            )
        except LightServiceError as e:  # ErrBadRequest: malformed hash
            return web.json_response(_error(None, e.code, str(e), e.data))
        except ValueError as e:
            return web.json_response(_error(None, -32602, "bad request", str(e)))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_rpc(self, request: web.Request) -> web.Response:
        try:
            return web.json_response(_result(None, await self._debug_rpc({})))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_debug_device_profile(self, request: web.Request) -> web.Response:
        params = {k: v for k, v in request.query.items()}
        try:
            return web.json_response(
                _result(None, await self._debug_device_profile(params))
            )
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_uri(self, request: web.Request) -> web.Response:
        method = request.match_info["method"]
        handler = self._routes.get(method)
        if handler is None:
            return web.json_response(_error(None, -32601, f"method {method} not found"))
        params = {k: v.strip('"') for k, v in request.query.items()}
        try:
            result = await self._dispatch(method, handler, params)
            return web.json_response(_result(None, result))
        except RPCShedError:
            return self._shed_response(None, method)
        except ErrLightOverloaded:
            return self._shed_response(None, method)
        except MempoolError as e:
            return web.json_response(self._mempool_reject(None, e))
        except LightServiceError as e:
            return web.json_response(_error(None, e.code, str(e), e.data))
        except Exception as e:
            return web.json_response(_error(None, -32603, "internal error", str(e)))

    async def _handle_websocket(self, request: web.Request):
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        subscriber = f"ws-{id(ws)}"
        tasks = []
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    body = json.loads(msg.data)
                except json.JSONDecodeError:
                    await ws.send_json(_error(None, -32700, "parse error"))
                    continue
                id_ = body.get("id")
                method = body.get("method", "")
                params = body.get("params", {}) or {}
                if method == "subscribe":
                    try:
                        q = Query(params.get("query", ""))
                        sub = self.node.event_bus.subscribe(subscriber, q)
                    except Exception as e:
                        await ws.send_json(_error(id_, -32603, "subscribe failed", str(e)))
                        continue
                    await ws.send_json(_result(id_, {}))

                    async def pump(sub=sub, q=q, id_=id_):
                        try:
                            while True:
                                m = await sub.next()
                                await ws.send_json(
                                    _result(
                                        id_,
                                        {
                                            "query": str(q),
                                            "data": {"type": m.events.get("tm.event", [""])[0]},
                                            "events": m.events,
                                        },
                                    )
                                )
                        except Exception:
                            pass

                    tasks.append(asyncio.create_task(pump()))
                elif method == "unsubscribe":
                    # by query, mirroring the reference's /unsubscribe route
                    # (reference: rpc/core/events.go Unsubscribe)
                    try:
                        q = Query(params.get("query", ""))
                        self.node.event_bus.unsubscribe(subscriber, q)
                        await ws.send_json(_result(id_, {}))
                    except Exception as e:
                        await ws.send_json(_error(id_, -32603, "unsubscribe failed", str(e)))
                elif method == "unsubscribe_all":
                    self.node.event_bus.unsubscribe_all(subscriber)
                    await ws.send_json(_result(id_, {}))
                else:
                    handler = self._routes.get(method)
                    if handler is None:
                        await ws.send_json(_error(id_, -32601, f"method {method} not found"))
                    else:
                        try:
                            await ws.send_json(
                                _result(id_, await self._dispatch(method, handler, params))
                            )
                        except (RPCShedError, ErrLightOverloaded):
                            await ws.send_json(
                                _error(id_, ERR_SHED, "server overloaded", {"method": method})
                            )
                        except MempoolError as e:
                            await ws.send_json(self._mempool_reject(id_, e))
                        except LightServiceError as e:
                            await ws.send_json(_error(id_, e.code, str(e), e.data))
                        except Exception as e:
                            await ws.send_json(_error(id_, -32603, "internal error", str(e)))
        finally:
            for t in tasks:
                t.cancel()
            try:
                self.node.event_bus.unsubscribe_all(subscriber)
            except Exception:
                pass
        return ws

    # -- handlers (reference: rpc/core/*.go) --------------------------------

    async def _health(self, params) -> dict:
        return {}

    async def _status(self, params) -> dict:
        node = self.node
        latest_height = node.block_store.height
        latest_block = node.block_store.load_block(latest_height) if latest_height else None
        pub = node.priv_validator.get_pub_key() if node.priv_validator else None
        return {
            "node_info": {
                "network": node.genesis.chain_id,
                "moniker": node.config.base.moniker,
                "version": "0.1.0",
            },
            "sync_info": {
                "latest_block_height": str(latest_height),
                "latest_block_hash": latest_block.hash().hex().upper() if latest_block else "",
                "latest_app_hash": node.state.app_hash.hex().upper() if node.state else "",
                "catching_up": False,
            },
            "validator_info": {
                "address": pub.address().hex().upper() if pub else "",
                "pub_key": {"type": pub.type_name(), "value": _b64(pub.bytes())} if pub else None,
                "voting_power": "0",
            },
        }

    def _decode_tx_param(self, params) -> bytes:
        import base64

        tx = params.get("tx", "")
        if isinstance(tx, str):
            if tx.startswith("0x"):
                return bytes.fromhex(tx[2:])
            try:
                return base64.b64decode(tx)
            except Exception:
                return tx.encode()
        return bytes(tx)

    def _track_received(self, tx_hash: bytes) -> None:
        """Stamp the journey's `received` at the RPC edge — BEFORE the
        executor hop into mempool.check_tx, so the waterfall's first stage
        includes executor queueing (the mempool re-stamp dedupes)."""
        tt = getattr(self.node, "tx_tracker", None)
        if tt is not None and tt.enabled:
            tt.record(tx_hash, "received", via="rpc")

    async def _broadcast_tx_async(self, params) -> dict:
        tx = self._decode_tx_param(params)
        tx_hash = tmhash.sum256(tx)
        self._track_received(tx_hash)
        asyncio.get_event_loop().run_in_executor(None, self.node.mempool.check_tx, tx)
        return {"code": 0, "data": "", "log": "", "hash": tx_hash.hex().upper()}

    async def _broadcast_tx_sync(self, params) -> dict:
        tx = self._decode_tx_param(params)
        self._track_received(tmhash.sum256(tx))
        res = await asyncio.get_event_loop().run_in_executor(None, self.node.mempool.check_tx, tx)
        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "hash": tmhash.sum256(tx).hex().upper(),
        }

    async def _check_tx(self, params) -> dict:
        """Run CheckTx against the app WITHOUT adding the tx to the mempool
        (reference: rpc/core/mempool.go CheckTx, routes.go:26)."""
        tx = self._decode_tx_param(params)
        res = await asyncio.get_event_loop().run_in_executor(
            None, self.node.proxy_app.mempool.check_tx, abci.RequestCheckTx(tx=tx)
        )
        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "gas_wanted": str(res.gas_wanted),
            "gas_used": str(res.gas_used),
        }

    async def _broadcast_tx_commit(self, params) -> dict:
        """CheckTx → wait for DeliverTx event (reference: rpc/core/mempool.go)."""
        tx = self._decode_tx_param(params)
        tx_hash = tmhash.sum256(tx)
        self._track_received(tx_hash)
        q = Query(f"{TX_HASH_KEY} = '{tx_hash.hex().upper()}'")
        subscriber = f"btc-{tx_hash.hex()[:16]}"
        sub = self.node.event_bus.subscribe(subscriber, q)
        try:
            check = await asyncio.get_event_loop().run_in_executor(
                None, self.node.mempool.check_tx, tx
            )
            if check.code != abci.CODE_TYPE_OK:
                return {
                    "check_tx": {"code": check.code, "log": check.log},
                    "deliver_tx": {},
                    "hash": tx_hash.hex().upper(),
                    "height": "0",
                }
            timeout = self.node.config.rpc.timeout_broadcast_tx_commit
            msg = await asyncio.wait_for(sub.next(), timeout=timeout)
            data = msg.data
            return {
                "check_tx": {"code": check.code, "log": check.log},
                "deliver_tx": {
                    "code": data.result.code,
                    "data": _b64(data.result.data),
                    "log": data.result.log,
                },
                "hash": tx_hash.hex().upper(),
                "height": str(data.height),
            }
        finally:
            try:
                self.node.event_bus.unsubscribe_all(subscriber)
            except Exception:
                pass

    async def _abci_query(self, params) -> dict:
        data = params.get("data", "")
        if isinstance(data, str):
            data = bytes.fromhex(data[2:] if data.startswith("0x") else data)
        res = self.node.proxy_app.query.query(
            abci.RequestQuery(
                data=data,
                path=params.get("path", ""),
                height=int(params.get("height", 0)),
                prove=bool(params.get("prove", False)),
            )
        )
        out = {
            "code": res.code,
            "log": res.log,
            "key": _b64(res.key),
            "value": _b64(res.value),
            "height": str(res.height),
        }
        if res.proof_ops:
            out["proofOps"] = {
                "ops": [
                    {"type": op.type, "key": _b64(op.key), "data": _b64(op.data)}
                    for op in res.proof_ops
                ]
            }
        return {"response": out}

    async def _abci_info(self, params) -> dict:
        res = self.node.proxy_app.query.info(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "app_version": str(res.app_version),
                "last_block_height": str(res.last_block_height),
                "last_block_app_hash": _b64(res.last_block_app_hash),
            }
        }

    def _block_to_json(self, block, block_id) -> dict:
        return {
            "block_id": block_id_to_json(block_id),
            "block": {
                "header": header_to_json(block.header),
                "data": {"txs": [_b64(tx) for tx in block.txs]},
                "last_commit": commit_to_json(block.last_commit),
            },
        }

    async def _block(self, params) -> dict:
        height = int(params.get("height") or self.node.block_store.height)
        block = self.node.block_store.load_block(height)
        if block is None:
            raise ValueError(f"block at height {height} not found")
        meta = self.node.block_store.load_block_meta(height)
        return self._block_to_json(block, meta[0])

    async def _blockchain(self, params) -> dict:
        store = self.node.block_store
        max_h = int(params.get("maxHeight") or store.height)
        min_h = int(params.get("minHeight") or max(store.base, max_h - 19))
        metas = []
        for h in range(max_h, min_h - 1, -1):
            meta = store.load_block_meta(h)
            if meta is None:
                continue
            block = store.load_block(h)
            metas.append(
                {
                    "block_id": {"hash": meta[0].hash.hex().upper()},
                    "header": {"height": str(h), "chain_id": block.header.chain_id},
                    "num_txs": str(len(block.txs)),
                }
            )
        return {"last_height": str(store.height), "block_metas": metas}

    async def _commit(self, params) -> dict:
        """Full signed header — backs the light client's HTTPProvider
        (reference: rpc/core/blocks.go Commit). canonical=True when the commit
        comes from the next block's LastCommit, else the seen commit."""
        height = int(params.get("height") or self.node.block_store.height)
        block = self.node.block_store.load_block(height)
        if block is None:
            raise ValueError(f"block at height {height} not found")
        canonical = False
        commit = None
        nxt = self.node.block_store.load_block(height + 1)
        if nxt is not None and nxt.last_commit.height == height:
            commit, canonical = nxt.last_commit, True
        else:
            commit = self.node.block_store.load_seen_commit(height)
        if commit is None:
            raise ValueError(f"commit at height {height} not found")
        return {
            "signed_header": {
                "header": header_to_json(block.header),
                "commit": commit_to_json(commit),
            },
            "canonical": canonical,
        }

    async def _validators(self, params) -> dict:
        height = int(params.get("height") or (self.node.state.last_block_height + 1))
        vals = self.node.state_store.load_validators(height)
        if vals is None:
            raise ValueError(f"no validator set at height {height}")
        return {
            "block_height": str(height),
            "validators": [validator_to_json(v) for v in vals.validators],
            "count": str(len(vals.validators)),
            "total": str(len(vals.validators)),
        }

    async def _genesis(self, params) -> dict:
        return {"genesis": json.loads(self.node.genesis.to_json())}

    async def _tx(self, params) -> dict:
        h = params.get("hash", "")
        if isinstance(h, str):
            tx_hash = bytes.fromhex(h[2:] if h.startswith("0x") else h)
        else:
            tx_hash = bytes(h)
        res = self.node.tx_indexer.get(tx_hash)
        if res is None:
            raise ValueError(f"tx {tx_hash.hex()} not found")
        return {
            "hash": tx_hash.hex().upper(),
            "height": str(res.height),
            "index": res.index,
            "tx_result": {"code": res.code, "data": _b64(res.data), "log": res.log},
            "tx": _b64(res.tx),
        }

    async def _unconfirmed_txs(self, params) -> dict:
        limit = int(params.get("limit", 30))
        txs = self.node.mempool.reap_max_txs(limit)
        return {
            "n_txs": str(len(txs)),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.txs_bytes()),
            "txs": [_b64(tx) for tx in txs],
        }

    async def _num_unconfirmed_txs(self, params) -> dict:
        return {
            "n_txs": str(self.node.mempool.size()),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.txs_bytes()),
        }

    async def _consensus_state(self, params) -> dict:
        return {"round_state": self.node.consensus.rs.round_state_summary()}

    async def _dump_consensus_state(self, params) -> dict:
        """(reference: rpc/core/consensus.go DumpConsensusState)"""
        rs = self.node.consensus.rs
        votes = []
        if rs.votes is not None:
            for r in range(rs.round + 1):
                pv, pc = rs.votes.prevotes(r), rs.votes.precommits(r)
                votes.append(
                    {
                        "round": r,
                        "prevotes": pv.bit_array() if pv else [],
                        "prevotes_power": str(pv.sum_power()) if pv else "0",
                        "precommits": pc.bit_array() if pc else [],
                        "precommits_power": str(pc.sum_power()) if pc else "0",
                    }
                )
        peers = []
        if self.node.switch is not None:
            for p in self.node.switch.peers.list():
                ps = p.get("cs_peer_state")
                peers.append(
                    {
                        "node_address": p.id,
                        "peer_state": {
                            "height": str(ps.height),
                            "round": ps.round,
                            "step": int(ps.step),
                        }
                        if ps
                        else None,
                    }
                )
        return {
            "round_state": {
                "height": str(rs.height),
                "round": rs.round,
                "step": int(rs.step),
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
                "proposal": rs.proposal is not None,
                "proposal_block": rs.proposal_block.hash().hex().upper() if rs.proposal_block else "",
                "height_vote_set": votes,
            },
            "peers": peers,
        }

    async def _consensus_params(self, params) -> dict:
        height = int(params.get("height") or (self.node.state.last_block_height + 1))
        cp = self.node.state.consensus_params
        return {
            "block_height": str(height),
            "consensus_params": {
                "block": {"max_bytes": str(cp.block.max_bytes), "max_gas": str(cp.block.max_gas)},
                "evidence": {
                    "max_age_num_blocks": str(cp.evidence.max_age_num_blocks),
                    "max_age_duration": str(cp.evidence.max_age_duration_ns),
                },
            },
        }

    async def _tx_search(self, params) -> dict:
        """query like "tm.event.key='v'" or "app.creator='x'"; supports
        key=value equality terms (reference: rpc/core/tx.go TxSearch over the
        kv indexer state/txindex/kv/kv.go)."""
        query = params.get("query", "")
        terms = [t.strip() for t in query.split(" AND ") if t.strip()]
        results = None
        for term in terms:
            if "=" not in term:
                raise ValueError(f"bad query term {term!r}")
            key, _, val = term.partition("=")
            key = key.strip()
            val = val.strip().strip("'\"")
            if key == "tx.height":
                found = self.node.tx_indexer.by_height(int(val))
            else:
                found = self.node.tx_indexer.search(key, val)
            keys = {tmhash.sum256(r.tx) for r in found}
            if results is None:
                results = {tmhash.sum256(r.tx): r for r in found}
            else:
                results = {k: v for k, v in results.items() if k in keys}
        results = list((results or {}).values())
        page = int(params.get("page", 1))
        per_page = min(int(params.get("per_page", 30)), 100)
        start = (page - 1) * per_page
        out = results[start : start + per_page]
        return {
            "txs": [
                {
                    "hash": tmhash.sum256(r.tx).hex().upper(),
                    "height": str(r.height),
                    "index": r.index,
                    "tx_result": {"code": r.code, "data": _b64(r.data), "log": r.log},
                    "tx": _b64(r.tx),
                }
                for r in out
            ],
            "total_count": str(len(results)),
        }

    async def _block_search(self, params) -> dict:
        """Search blocks by height range terms, e.g.
        "block.height > 5 AND block.height <= 10"
        (reference: rpc/core/blocks.go BlockSearch)."""
        query = params.get("query", "")
        store = self.node.block_store
        lo, hi = store.base, store.height
        for term in (t.strip() for t in query.split(" AND ") if t.strip()):
            for op in (">=", "<=", ">", "<", "="):
                if op in term:
                    key, _, val = term.partition(op)
                    if key.strip() != "block.height":
                        raise ValueError(f"unsupported block_search key {key.strip()!r}")
                    v = int(val.strip().strip("'\""))
                    if op == ">=":
                        lo = max(lo, v)
                    elif op == ">":
                        lo = max(lo, v + 1)
                    elif op == "<=":
                        hi = min(hi, v)
                    elif op == "<":
                        hi = min(hi, v - 1)
                    else:
                        lo = hi = v
                    break
            else:
                raise ValueError(f"bad query term {term!r}")
        blocks = []
        for h in range(lo, hi + 1):
            block = store.load_block(h)
            meta = store.load_block_meta(h)
            if block is not None and meta is not None:
                blocks.append(self._block_to_json(block, meta[0]))
        page = int(params.get("page", 1))
        per_page = min(int(params.get("per_page", 30)), 100)
        start = (page - 1) * per_page
        return {"blocks": blocks[start : start + per_page], "total_count": str(len(blocks))}

    async def _block_results(self, params) -> dict:
        height = int(params.get("height") or self.node.block_store.height)
        resp = self.node.state_store.load_abci_responses(height)
        if resp is None:
            raise ValueError(f"no ABCI results for height {height}")
        return {
            "height": str(height),
            "txs_results": [
                {"code": r.code, "data": _b64(r.data), "log": r.log, "gas_used": str(r.gas_used)}
                for r in resp.deliver_txs
            ],
            "validator_updates": [
                {"pub_key": {"type": u.pub_key_type, "value": _b64(u.pub_key_bytes)}, "power": str(u.power)}
                for u in (resp.end_block.validator_updates if resp.end_block else [])
            ],
        }

    async def _block_by_hash(self, params) -> dict:
        h = params.get("hash", "")
        block_hash = bytes.fromhex(h[2:] if h.startswith("0x") else h) if isinstance(h, str) else bytes(h)
        block = self.node.block_store.load_block_by_hash(block_hash)
        if block is None:
            raise ValueError(f"block {block_hash.hex()} not found")
        meta = self.node.block_store.load_block_meta(block.header.height)
        return self._block_to_json(block, meta[0])

    async def _broadcast_evidence(self, params) -> dict:
        """(reference: rpc/core/evidence.go)"""
        from tendermint_tpu.types.evidence import decode_evidence

        raw = params.get("evidence", "")
        data = bytes.fromhex(raw[2:] if raw.startswith("0x") else raw) if isinstance(raw, str) else bytes(raw)
        ev = decode_evidence(data)
        self.node.evidence_pool.add_evidence(ev)
        return {"hash": ev.hash().hex().upper()}

    def _require_unsafe(self) -> None:
        if not self.node.config.rpc.unsafe:
            raise ValueError("unsafe RPC routes are disabled (set rpc.unsafe = true)")

    async def _dial_seeds(self, params) -> dict:
        """unsafe route (reference: rpc/core/net.go UnsafeDialSeeds)."""
        self._require_unsafe()
        seeds = params.get("seeds") or []
        if self.node.switch is None:
            raise ValueError("p2p is not enabled")
        await self.node.switch.dial_peers_async(list(seeds), persistent=False)
        return {"log": f"dialing seeds: {seeds}"}

    async def _unsafe_flush_mempool(self, params) -> dict:
        """unsafe route (reference: rpc/core/mempool.go UnsafeFlushMempool)."""
        self._require_unsafe()
        self.node.mempool.flush()
        return {}

    async def _unsafe_dump_stacks(self, params) -> dict:
        """Stack profile: every thread's Python stack plus every asyncio
        task's coroutine stack — the goroutine-profile analog the reference
        debug dump captures (cmd/tendermint/commands/debug/dump.go:117
        dumpProfile("goroutine"))."""
        self._require_unsafe()
        import sys
        import traceback

        threads = {}
        for tid, frame in sys._current_frames().items():
            threads[str(tid)] = "".join(traceback.format_stack(frame))
        tasks = {}
        for i, task in enumerate(asyncio.all_tasks()):
            stack = task.get_stack(limit=16)
            tasks[f"{i}:{task.get_name()}"] = "".join(
                "".join(traceback.format_stack(f)) for f in stack
            ) or repr(task)
        return {"threads": threads, "tasks": tasks}

    async def _unsafe_dump_heap(self, params) -> dict:
        """Heap profile via tracemalloc — the heap-pprof analog
        (cmd/tendermint/commands/debug/dump.go:121 dumpProfile("heap")).
        First call starts tracing and returns a baseline marker; subsequent
        calls return the top allocation sites."""
        self._require_unsafe()
        import tracemalloc

        top_n = int(params.get("top", 50))
        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return {"tracing_started": True, "top": []}
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")[:top_n]
        cur, peak = tracemalloc.get_traced_memory()
        return {
            "tracing_started": False,
            "traced_current_bytes": cur,
            "traced_peak_bytes": peak,
            "top": [
                {
                    "file": str(s.traceback[0].filename),
                    "line": s.traceback[0].lineno,
                    "size_bytes": s.size,
                    "count": s.count,
                }
                for s in stats
            ],
        }

    async def _debug_trace(self, params) -> dict:
        """Flight-recorder ring dump (libs/trace.py): the batch-verify
        pipeline's span tree as JSON, newest-last. ?limit=N returns the most
        recent N events. Read-only, served regardless of rpc.unsafe (like
        consensus_state); see docs/OBSERVABILITY.md for the span taxonomy."""
        from tendermint_tpu.libs import trace

        limit = params.get("limit")
        events = trace.tracer.dump(int(limit) if limit is not None else None)
        return {
            "enabled": trace.tracer.enabled,
            "ring_size": trace.tracer.ring_size,
            "count": len(events),
            "events": events,
        }

    async def _debug_verify_stats(self, params) -> dict:
        """Aggregated batch-verify telemetry + device health
        (libs/trace.verify_stats): per-(backend, path) flush totals, the
        per-stage time split, the last flush's breakdown, and the
        device_up/init/last-call-age gauges node liveness reads."""
        from tendermint_tpu.libs import trace

        out = trace.verify_stats()
        svc = getattr(self.node, "light_service", None)
        if svc is not None:
            # the serving subsystem's consumption of the pipeline above —
            # one stats read covers the device AND who it verified for
            out["light"] = svc.stats()
        sched = getattr(self.node, "scheduler", None)
        if sched is not None:
            # THIS node's scheduler, not the process-global default another
            # in-process node may have registered last
            out["scheduler"] = sched.stats()
        return out

    async def _consensus_timeline(self, params) -> dict:
        """Per-height/round consensus timeline ring
        (consensus/timeline.py): time-ordered step entries with derived
        durations, round escalations, proposal/vote arrival and commit per
        height. ?limit=N returns the most recent N heights. Degrades
        gracefully: with tracing disabled (or no timeline wired) it reports
        enabled=false and whatever records exist (none if tracing was never
        on). Read-only; same taxonomy as `wal-inspect`'s offline report."""
        from tendermint_tpu.libs import trace

        tl = getattr(self.node.consensus, "timeline", None)
        limit = params.get("limit")
        heights = tl.dump(int(limit) if limit is not None else None) if tl else []
        return {
            "enabled": bool(tl is not None and trace.tracer.enabled),
            "max_heights": tl.max_heights if tl is not None else 0,
            "count": len(heights),
            "heights": heights,
            # cross-height per-origin hop-latency aggregates (the per-peer
            # lag ranking the chain observatory merges across the fleet)
            "propagation_peers": tl.peer_stats() if tl is not None else {},
            "node_id": (
                self.node.node_key.id
                if getattr(self.node, "node_key", None) is not None
                else None
            ),
        }

    async def _debug_overload(self, params) -> dict:
        """Overload-protection snapshot (node/overload.py + the RPC gate +
        mempool admission + per-peer shed counters): the one page an
        operator reads when the node is under pressure. Read-only, served
        regardless of rpc.unsafe (like /debug/verify_stats)."""
        out = {
            "rpc": {
                "max_inflight_requests": self.gate.max_inflight,
                "inflight": self.gate.inflight,
                "shed_total": self.gate.shed_total,
                "shed_writes": self.gate.shed_writes,
                "shed_reads": self.gate.shed_reads,
            }
        }
        ctl = getattr(self.node, "overload", None)
        out["controller"] = ctl.snapshot() if ctl is not None else None
        mp = getattr(self.node, "mempool", None)
        if mp is not None:
            out["mempool"] = {
                "size": mp.size(),
                "max_txs": mp.max_txs,
                "bytes": mp.txs_bytes(),
                "max_bytes": mp.max_txs_bytes,
                "full": mp.is_full(0),
                "evicted_total": getattr(mp, "evicted_total", 0),
                "expired_total": getattr(mp, "expired_total", 0),
            }
        sw = getattr(self.node, "switch", None)
        if sw is not None:
            out["p2p"] = {
                "peers": sw.num_peers(),
                "shed_by_peer": {
                    p.id[:10]: {
                        "shed_msgs_total": p.mconn.shed_msgs,
                        "by_channel": {
                            f"{cid:#x}": n
                            for cid, n in p.mconn.shed_by_channel.items()
                        },
                    }
                    for p in sw.peers.list()
                    if p.mconn.shed_msgs
                },
            }
        return out

    async def _debug_mesh(self, params) -> dict:
        """Multi-chip mesh telemetry snapshot (parallel/telemetry.py): the
        active mesh, per-shard lane layout, pad waste, submit/finish wall
        totals, all_gather traffic, and AOT artifact-cache outcomes — the
        page a MULTICHIP round's post-mortem starts from. Read-only, served
        regardless of rpc.unsafe (like /debug/verify_stats); on a
        single-device node it reports mesh: null with zeroed totals."""
        from tendermint_tpu.parallel import telemetry as mesh_tm

        return mesh_tm.mesh_stats()

    # one-line description per debug surface — served by GET /debug so the
    # ~10 endpoints are discoverable from the node itself, not only the docs
    DEBUG_ENDPOINTS = (
        ("/debug", "this index: every debug endpoint with a description", False),
        ("/debug/trace", "flight-recorder ring dump (batch-verify spans + "
         "consensus/breaker/forensics events); ?limit=N", False),
        ("/debug/verify_stats", "aggregated batch-verify telemetry, last "
         "flush breakdown, slope samples, device health", False),
        ("/debug/consensus_timeline", "per-height/round timeline: steps, "
         "proposals, vote arrivals, cross-node propagation; ?limit=N", False),
        ("/debug/overload", "overload-protection snapshot: RPC gate, "
         "pressure controller, mempool admission, per-peer sheds", False),
        ("/debug/mesh", "multi-chip mesh telemetry: shard lanes, pad waste, "
         "all_gather traffic, AOT cache outcomes", False),
        ("/debug/slo", "declared latency budgets, per-window burn rates and "
         "guard trips ([slo] config)", False),
        ("/debug/light", "light-client-as-a-service snapshot: trusted span, "
         "cache/single-flight counters, coalesced flushes, sheds, "
         "conflicting-header detections", False),
        ("/debug/tx_trace", "tx lifecycle observatory: ?hash= returns the "
         "full received→delivered waterfall with per-stage durations; "
         "without, ring stats + per-stage latency percentiles", False),
        ("/debug/rpc", "per-method RPC latency attribution: gate state, "
         "per-method outcome counts + mean/max, top-N slowest requests "
         "with structured annotations", False),
        ("/debug/device_profile", "on-demand jax profiler capture; "
         "?action=start|stop|status (start/stop need rpc.unsafe)", True),
        ("/metrics", "Prometheus exposition (needs instrumentation."
         "prometheus)", False),
    )

    async def _debug_index(self, params) -> dict:
        """GET /debug: machine- and operator-readable catalog of every debug
        endpoint (they number ~10 and were only discoverable via docs)."""
        return {
            "endpoints": [
                {"path": path, "description": desc, "unsafe": unsafe}
                for path, desc, unsafe in self.DEBUG_ENDPOINTS
            ]
        }

    async def _debug_slo(self, params) -> dict:
        """SLO burn-rate snapshot (libs/slo.py): declared budgets, good/
        breach totals, fast+slow window burn rates, tripped guards and
        verdicts per objective. Read-only, served regardless of rpc.unsafe
        (like /debug/verify_stats); enabled=false when the engine is off."""
        eng = getattr(self.node, "slo", None)
        if eng is None:
            return {"enabled": False, "objectives": {}}
        return eng.snapshot()

    # -- light-client-as-a-service (light/service.py) -----------------------

    def _light_service(self):
        svc = getattr(self.node, "light_service", None)
        if svc is None:
            # structured refusal: a deliberately disabled service must not
            # produce -32603 + a stack trace per request
            raise ErrLightDisabled(
                "light service is disabled (set light_service.enabled = true)"
            )
        return svc

    @staticmethod
    def _decode_hash_param(params) -> Optional[bytes]:
        h = params.get("hash", "")
        if not h:
            return None
        try:
            if isinstance(h, str):
                out = bytes.fromhex(h[2:] if h.startswith("0x") else h)
            elif isinstance(h, (bytes, bytearray, list)):
                out = bytes(h)
            else:
                raise TypeError(f"unsupported type {type(h).__name__}")
        except (ValueError, TypeError) as e:
            raise ErrBadRequest(f"invalid hash parameter: {e}") from e
        if len(out) != 32:
            # a short/garbage hash must be a bad request, never a
            # conflicting-header "attack" detection
            raise ErrBadRequest(
                f"invalid hash parameter: want 32 bytes, got {len(out)}"
            )
        return out

    @staticmethod
    def _decode_height_param(params) -> int:
        try:
            return int(params.get("height") or 0)
        except (ValueError, TypeError) as e:
            raise ErrBadRequest(f"invalid height parameter: {e}") from e

    async def _light_verified_result(self, params) -> tuple:
        """Shared body of light_verify/light_block: parse params, verify
        through the service, build the base response. Returns (result,
        light_block) so light_block can append the validator set."""
        svc = self._light_service()
        height = self._decode_height_param(params)
        lb, source = await svc.verify_height(
            height, expected_hash=self._decode_hash_param(params)
        )
        return {
            "height": str(lb.height),
            "hash": lb.hash().hex().upper(),
            "source": source,
            "signed_header": {
                "header": header_to_json(lb.header),
                "commit": commit_to_json(lb.signed_header.commit),
            },
            "light_client_verified": True,
        }, lb

    async def _light_verify(self, params) -> dict:
        """Server-side skipping verification (the light-client-as-a-service
        fast path): verify the commit at `height` against the service's
        trusted span — answered from the verified-header cache, a shared
        coalesced device flush, or the bisection fallback. Optional `hash`
        is the client's expected header hash; a mismatch is a structured
        conflicting-header error (code -32010), not a 500. Sheddable under
        the LoadGate (429 + Retry-After) so a light flood never starves
        consensus."""
        result, _lb = await self._light_verified_result(params)
        return result

    async def _light_block(self, params) -> dict:
        """light_verify + the validator set: everything a downstream light
        client needs to extend its own trust from this height."""
        from tendermint_tpu.types.light import validator_set_to_json

        result, lb = await self._light_verified_result(params)
        result["validator_set"] = validator_set_to_json(lb.validator_set)
        return result

    async def _light_status(self, params) -> dict:
        """Service status: trusted span, cache occupancy, window policy,
        current pending load. Bypasses the gate like `status` — a client
        deciding whether to retry must always get an answer."""
        return self._light_service().status()

    async def _debug_light(self, params) -> dict:
        """GET /debug/light: the light service's full counter snapshot
        (requests by outcome, cache hits, single-flight waits, coalesced
        flushes + lanes, bisections, sheds, conflicting headers). Read-only,
        served regardless of rpc.unsafe (like /debug/verify_stats)."""
        svc = getattr(self.node, "light_service", None)
        if svc is None:
            return {"enabled": False}
        return svc.stats()

    # -- transaction & request observatory (libs/txtrace.py) ----------------

    async def _tx_status(self, params) -> dict:
        """Where is my transaction? The full lifecycle waterfall for one tx
        hash: received -> checked -> admitted -> first_gossiped ->
        proposed -> committed -> delivered (or the terminal reject/evict/
        expire), with wall timestamps and per-stage durations. Sheddable
        like `tx` — a status poll must never starve the vote path. A
        disabled tracker and an unknown hash are both structured answers,
        never -32603 + a stack trace per routine poll."""
        tt = getattr(self.node, "tx_tracker", None)
        if tt is None:
            return {
                "enabled": False,
                "found": False,
                "reason": "tx lifecycle tracking is disabled "
                          "(set instrumentation.txtrace_enabled = true)",
            }
        h = params.get("hash", "")
        try:
            if isinstance(h, str):
                tx_hash = bytes.fromhex(h[2:] if h.startswith("0x") else h)
            else:
                tx_hash = bytes(h)
        except (ValueError, TypeError) as e:
            # malformed input is a structured -32602 on every transport,
            # never a -32603 + stack trace
            raise ErrBadRequest(f"invalid hash parameter: {e}") from e
        wf = tt.waterfall(tx_hash)
        if wf is None:
            # the routine polling answer, not an error: clients poll this
            # route for hashes that may never have reached this node (or
            # whose journey aged out of the bounded ring)
            return {
                "hash": tx_hash.hex().upper(),
                "found": False,
                "reason": "not in the lifecycle ring (never received here, "
                          "or the journey aged out)",
                "ring_max_txs": tt.max_txs,
            }
        wf["found"] = True
        # a committed journey gains the indexer's final word when available
        indexer = getattr(self.node, "tx_indexer", None)
        if indexer is not None and wf.get("terminal") == "delivered":
            try:
                res = indexer.get(tx_hash)
            except Exception:
                res = None
            if res is not None:
                wf["indexed"] = {
                    "height": str(res.height),
                    "index": res.index,
                    "code": res.code,
                }
        return wf

    async def _debug_tx_trace(self, params) -> dict:
        """GET /debug/tx_trace: with ?hash= the same waterfall as
        `tx_status`; without, the tracker's ring stats — occupancy, lifetime
        stage counts, terminal outcomes, and per-stage latency percentiles
        (the document the chain observatory merges per node). Read-only,
        served regardless of rpc.unsafe (like /debug/verify_stats)."""
        tt = getattr(self.node, "tx_tracker", None)
        if tt is None:
            return {"enabled": False}
        if params.get("hash"):
            return await self._tx_status(params)
        return tt.stats()

    async def _debug_rpc(self, params) -> dict:
        """GET /debug/rpc: per-method request attribution — the gate state,
        per-method counts/outcomes/mean/max, and the bounded top-N
        slowest-request ring with structured annotations (outcome, error,
        gate pressure at dispatch). Read-only; the histogram form of the
        same data rides /metrics as tendermint_rpc_request_duration_seconds."""
        methods = {}
        for label, agg in sorted(self._method_agg.items()):
            served = agg["count"] - agg["shed"]  # latency covers served only
            methods[label] = {
                **agg,
                "total_s": round(agg["total_s"], 6),
                "mean_ms": round(agg["total_s"] / served * 1e3, 3)
                if served
                else 0.0,
            }
        return {
            "gate": {
                "max_inflight_requests": self.gate.max_inflight,
                "inflight": self.gate.inflight,
                "shed_total": self.gate.shed_total,
                "shed_writes": self.gate.shed_writes,
                "shed_reads": self.gate.shed_reads,
            },
            "methods": methods,
            "slow_ring_cap": self.slow_ring.cap,
            "slow_requests": self.slow_ring.snapshot(),
        }

    async def _debug_device_profile(self, params) -> dict:
        """On-demand device profiler capture (libs/profiler.py over
        jax.profiler): ?action=start begins a capture into a fresh run dir
        under [instrumentation] profile_dir, ?action=stop ends it and lists
        the artifacts (analyze offline with tools/profile_report.py),
        ?action=status (default) reports the session. One capture per
        process; start while active is an error, not a restart."""
        from tendermint_tpu.libs import profiler

        action = params.get("action", "status")
        loop = asyncio.get_running_loop()
        if action == "start":
            # start/stop mutate process-global profiler state and write tens
            # of MB per capture — unsafe-gated like every mutating route;
            # status stays open (read-only, like /debug/mesh)
            self._require_unsafe()
            base = (
                getattr(self.node.config.instrumentation, "profile_dir", "")
                or profiler.default_base_dir()
            )
            return await loop.run_in_executor(None, profiler.start, base)
        if action == "stop":
            self._require_unsafe()
            # stop_trace serializes the whole capture (tens of MB, seconds) —
            # off the event loop so consensus keeps stepping while it writes
            return await loop.run_in_executor(None, profiler.stop)
        if action == "status":
            return profiler.status()
        raise ValueError(
            f"unknown action {action!r} (want start|stop|status)"
        )

    async def _dial_peers(self, params) -> dict:
        """unsafe route (reference: rpc/core/net.go UnsafeDialPeers)."""
        self._require_unsafe()
        if self.node.switch is None:
            raise ValueError("p2p is not enabled")
        peers = params.get("peers", [])
        if isinstance(peers, str):
            peers = [p for p in peers.split(",") if p]
        persistent = bool(params.get("persistent", False))
        await self.node.switch.dial_peers_async(peers, persistent=persistent)
        return {"log": f"dialing {len(peers)} peers"}

    async def _net_info(self, params) -> dict:
        sw = self.node.switch
        if sw is None:
            return {"listening": False, "listeners": [], "n_peers": "0", "peers": []}
        return {
            "listening": True,
            "listeners": [sw.transport.listen_addr],
            "n_peers": str(sw.num_peers()),
            "peers": [
                {
                    "node_info": {
                        "id": p.id,
                        "moniker": p.node_info.moniker,
                        "network": p.node_info.network,
                    },
                    "is_outbound": p.outbound,
                    "remote_ip": p.socket_addr,
                    "trust_score": round(sw.reporter.score(p.id), 4),
                    # flowrate Monitors + send-queue depths (reference:
                    # p2p/peer.go Status → rpc/core/net.go NetInfo)
                    "connection_status": p.status(),
                }
                for p in sw.peers.list()
            ],
        }
