"""Crash-resumable snapshot-restore checkpoint (ISSUE 12).

The syncer records which chunk indices the app ACCEPTED while restoring a
snapshot. After a crash mid-restore, the restarted syncer re-offers the SAME
snapshot and marks the recorded chunks as already applied, so the restore
resumes where it died instead of re-fetching and re-applying the whole set.

The checkpoint only describes what the NODE observed; resuming assumes the
app's side of those applies also survived the crash (a socket app that kept
running, or an app whose chunk application is durable). When that assumption
is wrong the restore fails the final verify_app hash check, the snapshot is
rejected, the checkpoint cleared — and the next attempt starts fresh.

Format (JSON, atomic tmp+rename):

    {"v": 1,
     "snapshot": {"height": H, "format": F, "chunks": N, "hash": "<hex>"},
     "applied": [0, 1, 4, ...]}
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Optional, Set

logger = logging.getLogger("tendermint_tpu.statesync")


class RestoreCheckpoint:
    def __init__(self, path: Optional[str]):
        """path=None disables persistence: save/load/clear are no-ops."""
        self.path = path

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    def save(self, snapshot, applied: Set[int]) -> None:
        if not self.path:
            return
        payload = {
            "v": 1,
            "snapshot": {
                "height": int(snapshot.height),
                "format": int(snapshot.format),
                "chunks": int(snapshot.chunks),
                "hash": snapshot.hash.hex(),
            },
            "applied": sorted(int(i) for i in applied),
        }
        d = os.path.dirname(self.path) or "."
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".restore-")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            logger.exception("restore checkpoint write failed (continuing)")

    def load(self, snapshot) -> Set[int]:
        """Applied chunk indices recorded for exactly this snapshot, or the
        empty set (absent, unreadable, or a different snapshot)."""
        if not self.path:
            return set()
        try:
            with open(self.path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return set()
        try:
            if payload.get("v") != 1:
                return set()
            s = payload["snapshot"]
            if (
                int(s["height"]) != int(snapshot.height)
                or int(s["format"]) != int(snapshot.format)
                or int(s["chunks"]) != int(snapshot.chunks)
                or bytes.fromhex(s["hash"]) != snapshot.hash
            ):
                return set()
            applied = {
                int(i) for i in payload["applied"]
                if 0 <= int(i) < int(snapshot.chunks)
            }
        except Exception:
            logger.warning("restore checkpoint unreadable; discarding", exc_info=True)
            return set()
        return applied

    def clear(self) -> None:
        if not self.path:
            return
        try:
            os.unlink(self.path)
        except OSError:
            pass
