"""Generalized merkle proof operators.

The reference's crypto/merkle/proof_op.go + proof_value.go + proof_key_path.go:
a chain of proof operators each mapping a value (or sub-root) to the next
root, keyed by a /-separated key path, verified top-down against a trusted
root hash (the header's app_hash in the light client's abci_query path,
light/rpc/client.go:116).

Wire format follows the reference's protobuf shapes so proofs interop:
  ProofOp  { string type = 1; bytes key = 2; bytes data = 3; }
  ProofOps { repeated ProofOp ops = 1; }
  ValueOp.data = ValueOp { bytes key = 1; Proof proof = 2; }
  Proof    { int64 total = 1; int64 index = 2; bytes leaf_hash = 3;
             repeated bytes aunts = 4; }
"""

from __future__ import annotations

import hashlib
import urllib.parse
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from tendermint_tpu.crypto.merkle import Proof, leaf_hash, proofs_from_byte_slices
from tendermint_tpu.libs.protowire import Reader, Writer, encode_varint

PROOF_OP_VALUE = "simple:v"


# ---------------------------------------------------------------- key paths


KEY_ENCODING_URL = 0
KEY_ENCODING_HEX = 1


class KeyPath:
    """/-separated key path; hex-encoded segments use an "x:" prefix
    (reference: crypto/merkle/proof_key_path.go)."""

    def __init__(self) -> None:
        self._keys: List[tuple] = []

    def append_key(self, key: bytes, enc: int = KEY_ENCODING_URL) -> "KeyPath":
        self._keys.append((bytes(key), enc))
        return self

    def __str__(self) -> str:
        out = []
        for key, enc in self._keys:
            if enc == KEY_ENCODING_URL:
                # quote() on raw bytes percent-encodes each byte directly
                # (%FF for 0xFF), matching Go's url.PathEscape byte-wise
                # escaping; decoding via a str round-trip would re-encode
                # high bytes as UTF-8 (%C3%BF) and break interop.
                out.append("/" + urllib.parse.quote(key, safe=""))
            elif enc == KEY_ENCODING_HEX:
                out.append("/x:" + key.hex())
            else:
                raise ValueError(f"unknown key encoding {enc}")
        return "".join(out)


def key_path_to_keys(path: str) -> List[bytes]:
    """Decode a key path into raw key bytes, leftmost first."""
    if not path or path[0] != "/":
        raise ValueError("key path string must start with a forward slash '/'")
    parts = path[1:].split("/")
    keys = []
    for part in parts:
        if part.startswith("x:"):
            keys.append(bytes.fromhex(part[2:]))
        else:
            keys.append(urllib.parse.unquote_to_bytes(part))
    return keys


# ---------------------------------------------------------------- wire types


@dataclass
class ProofOp:
    type: str
    key: bytes
    data: bytes

    def encode(self) -> bytes:
        w = Writer()
        w.string_field(1, self.type)
        w.bytes_field(2, self.key)
        w.bytes_field(3, self.data)
        return w.bytes()

    @classmethod
    def decode(cls, raw: bytes) -> "ProofOp":
        type_, key, data = "", b"", b""
        for fnum, wt, val in Reader(raw):
            if fnum == 1:
                type_ = val.decode()
            elif fnum == 2:
                key = val
            elif fnum == 3:
                data = val
        return cls(type_, key, data)


def encode_proof(p: Proof) -> bytes:
    w = Writer()
    w.varint_field(1, p.total)
    w.varint_field(2, p.index, emit_zero=False)
    w.bytes_field(3, p.leaf_hash)
    for a in p.aunts:
        w.bytes_field(4, a, emit_empty=True)
    return w.bytes()


def decode_proof(raw: bytes) -> Proof:
    total = index = 0
    lh = b""
    aunts: List[bytes] = []
    for fnum, wt, val in Reader(raw):
        if fnum == 1:
            total = int(val)
        elif fnum == 2:
            index = int(val)
        elif fnum == 3:
            lh = val
        elif fnum == 4:
            aunts.append(val)
    return Proof(total=total, index=index, leaf_hash=lh, aunts=aunts)


def encode_proof_ops(ops: Sequence[ProofOp]) -> bytes:
    w = Writer()
    for op in ops:
        w.message_field(1, op.encode())
    return w.bytes()


def decode_proof_ops(raw: bytes) -> List[ProofOp]:
    return [ProofOp.decode(val) for fnum, _, val in Reader(raw) if fnum == 1]


# ---------------------------------------------------------------- operators


def _encode_byte_slice(b: bytes) -> bytes:
    return encode_varint(len(b)) + b


class ValueOp:
    """Proves value-under-key inside a simple-merkle KV tree; leaf =
    leafHash(encode(key) || encode(sha256(value)))
    (reference: crypto/merkle/proof_value.go Run)."""

    def __init__(self, key: bytes, proof: Proof):
        self.key = bytes(key)
        self.proof = proof

    def get_key(self) -> bytes:
        return self.key

    def run(self, args: List[bytes]) -> List[bytes]:
        if len(args) != 1:
            raise ValueError(f"expected 1 arg, got {len(args)}")
        vhash = hashlib.sha256(args[0]).digest()
        kvbytes = _encode_byte_slice(self.key) + _encode_byte_slice(vhash)
        kvhash = leaf_hash(kvbytes)
        if kvhash != self.proof.leaf_hash:
            raise ValueError(
                f"leaf hash mismatch: want {self.proof.leaf_hash.hex()} "
                f"got {kvhash.hex()}"
            )
        root = self.proof.compute_root_hash()
        if root is None:
            raise ValueError("invalid proof shape")
        return [root]

    def proof_op(self) -> ProofOp:
        w = Writer()
        w.bytes_field(1, self.key)
        w.message_field(2, encode_proof(self.proof))
        return ProofOp(PROOF_OP_VALUE, self.key, w.bytes())

    @classmethod
    def from_proof_op(cls, pop: ProofOp) -> "ValueOp":
        if pop.type != PROOF_OP_VALUE:
            raise ValueError(f"unexpected ProofOp.type: {pop.type!r}")
        key, proof = b"", None
        for fnum, wt, val in Reader(pop.data):
            if fnum == 1:
                key = val
            elif fnum == 2:
                proof = decode_proof(val)
        if proof is None:
            raise ValueError("ValueOp.data missing proof")
        return cls(pop.key or key, proof)


# ---------------------------------------------------------------- runtime


class ProofRuntime:
    """Decoder registry + top-level verify (crypto/merkle/proof_op.go:80)."""

    def __init__(self) -> None:
        self._decoders: Dict[str, Callable[[ProofOp], object]] = {}

    def register_op_decoder(self, type_: str, dec: Callable[[ProofOp], object]) -> None:
        if type_ in self._decoders:
            raise ValueError(f"already registered for type {type_}")
        self._decoders[type_] = dec

    def decode(self, pop: ProofOp):
        dec = self._decoders.get(pop.type)
        if dec is None:
            raise ValueError(f"unrecognized proof type {pop.type!r}")
        return dec(pop)

    def verify_value(self, ops: Sequence[ProofOp], root: bytes, keypath: str,
                     value: bytes) -> None:
        self.verify(ops, root, keypath, [value])

    def verify_absence(self, ops: Sequence[ProofOp], root: bytes, keypath: str) -> None:
        self.verify(ops, root, keypath, [])

    def verify(self, ops: Sequence[ProofOp], root: bytes, keypath: str,
               args: List[bytes]) -> None:
        """Run operators bottom-up, consuming keypath right-to-left; the last
        output must equal the trusted root (proof_op.go:39 Verify)."""
        keys = key_path_to_keys(keypath)
        operators = [self.decode(pop) for pop in ops]
        for i, op in enumerate(operators):
            key = op.get_key()
            if key:
                if not keys:
                    raise ValueError(
                        f"key path has insufficient parts: expected no more "
                        f"keys but got {key!r}"
                    )
                if keys[-1] != key:
                    raise ValueError(
                        f"key mismatch on operation #{i}: expected "
                        f"{keys[-1]!r} but got {key!r}"
                    )
                keys = keys[:-1]
            args = op.run(args)
        if not args or args[0] != root:
            raise ValueError(
                f"calculated root hash is invalid: expected {root.hex()} "
                f"but got {args[0].hex() if args else None}"
            )
        if keys:
            raise ValueError("keypath not fully consumed")


def default_proof_runtime() -> ProofRuntime:
    prt = ProofRuntime()
    prt.register_op_decoder(PROOF_OP_VALUE, ValueOp.from_proof_op)
    return prt


# ------------------------------------------------------------- simple map


def simple_map_proofs(kv: Dict[bytes, bytes]):
    """Root hash + per-key ValueOp over a sorted KV map — the SimpleMap tree
    ValueOp verifies against (crypto/merkle/proof_value.go:14). Returns
    (root_hash, {key: ValueOp})."""
    keys = sorted(kv)
    leaves = [
        _encode_byte_slice(k) + _encode_byte_slice(hashlib.sha256(kv[k]).digest())
        for k in keys
    ]
    root, proofs = proofs_from_byte_slices(leaves)
    return root, {k: ValueOp(k, proofs[i]) for i, k in enumerate(keys)}
