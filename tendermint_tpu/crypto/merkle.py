"""RFC-6962-style Merkle trees over SHA-256.

Mirrors the reference's crypto/merkle (hash.go, tree.go, proof.go): leaf nodes
are H(0x00 || leaf), inner nodes H(0x01 || left || right), empty tree hashes to
H(""), and the split point for n leaves is the largest power of two strictly
less than n. Proofs carry (total, index, leaf_hash, aunts).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def empty_hash() -> bytes:
    return _sha256(b"")


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def split_point(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    if n < 2:
        raise ValueError("split_point requires n >= 2")
    return 1 << (n - 1).bit_length() - 1


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def compute_root_hash(self) -> Optional[bytes]:
        return _compute_hash_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root_hash: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        return self.compute_root_hash() == root_hash


def _compute_hash_from_aunts(
    index: int, total: int, lh: bytes, aunts: List[bytes]
) -> Optional[bytes]:
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        if aunts:
            return None
        return lh
    if not aunts:
        return None
    k = split_point(total)
    if index < k:
        left = _compute_hash_from_aunts(index, k, lh, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_hash_from_aunts(index - k, total - k, lh, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> tuple[bytes, List[Proof]]:
    """Root hash + a proof per item."""
    trails, root = _trails_from_byte_slices(list(items))
    root_hash = root.hash
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(
            Proof(total=len(items), index=i, leaf_hash=trail.hash, aunts=trail.flatten_aunts())
        )
    return root_hash, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent: Optional[_Node] = None
        self.left: Optional[_Node] = None  # left sibling (aunt chain)
        self.right: Optional[_Node] = None

    def flatten_aunts(self) -> List[bytes]:
        aunts: List[bytes] = []
        node: Optional[_Node] = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_byte_slices(items: List[bytes]) -> tuple[List[_Node], _Node]:
    n = len(items)
    if n == 0:
        return [], _Node(empty_hash())
    if n == 1:
        node = _Node(leaf_hash(items[0]))
        return [node], node
    k = split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root
