"""ISSUE 18 — cross-flush verified-row memo safety tests.

The memo (crypto/batch.VerifiedRowMemo) caches digests of rows that
verified OK so a commit assembled from deferred-verified live votes does
not re-pay device/host verification for the same rows. The safety
contract pinned here:

  - only verdict-True rows are ever inserted; a flush that raises inserts
    NOTHING (never-cache-on-failure);
  - a tampered byte anywhere in (key_type, pubkey, msg, sig) produces a
    different digest: the tampered row misses, re-verifies, and fails —
    the memo can never turn a False verdict into a True one;
  - the LRU eviction bound holds under a 10k-row flood;
  - capacity 0 disables the memo entirely (the test-suite default via
    tests/conftest.py);
  - integration: a commit built from a deferred-verified VoteSet resolves
    through the memo with ZERO re-verified rows.

The suite-wide conftest fixture swaps in a disabled memo per test; tests
here enable one explicitly through configure_verified_memo.
"""

import dataclasses

import numpy as np
import pytest

from tendermint_tpu.crypto import batch
from tendermint_tpu.crypto.keys import gen_ed25519
from tendermint_tpu.libs import trace as _trace


def _memo_on(rows=4096):
    batch.configure_verified_memo(rows)
    return batch._MEMO


def _signed(n, seed=b"\x31"):
    priv = gen_ed25519(seed * 32 if len(seed) == 1 else seed)
    pk = priv.pub_key().bytes()
    msgs = [b"memo-%05d" % i for i in range(n)]
    return [pk] * n, msgs, [priv.sign(m) for m in msgs]


def _last_flush():
    return _trace.verify_stats()["last_flush"]


# ---------------------------------------------------------------------------
# hit/miss semantics


def test_full_hit_short_circuits():
    memo = _memo_on()
    pks, msgs, sigs = _signed(60)
    assert batch.verify_batch(pks, msgs, sigs).all()
    assert len(memo) == 60
    assert memo.stats()["insertions"] == 60

    mask = batch.verify_batch(pks, msgs, sigs)
    assert mask.all() and len(mask) == 60
    st = memo.stats()
    assert st["hits"] == 60
    lf = _last_flush()
    assert lf["backend"] == "memo" and lf["path"] == "memo"
    assert lf["memo_hits"] == 60


def test_partial_hit_verifies_residue_only():
    memo = _memo_on()
    pks, msgs, sigs = _signed(60)
    assert batch.verify_batch(pks[:40], msgs[:40], sigs[:40]).all()
    hits0 = memo.stats()["hits"]

    mask = batch.verify_batch(pks, msgs, sigs)
    assert mask.all() and len(mask) == 60
    assert memo.stats()["hits"] == hits0 + 40
    # the residue flush (recorded after the memo flush) carried ONLY the
    # 20 unseen rows — and re-inserted them for next time
    assert _last_flush()["n"] == 20
    assert len(memo) == 60


def test_tampered_row_never_hits_memo():
    memo = _memo_on()
    pks, msgs, sigs = _signed(30, b"\x32")
    assert batch.verify_batch(pks, msgs, sigs).all()

    msgs = list(msgs)
    msgs[7] = msgs[7][:-1] + bytes([msgs[7][-1] ^ 1])
    mask = batch.verify_batch(pks, msgs, sigs)
    assert not mask[7]
    assert mask.sum() == 29

    # the tampered digest is not in the memo — and never got inserted
    d = memo.digest_rows([pks[7]], [msgs[7]], [sigs[7]])[0]
    assert d not in memo
    assert memo.stats()["insertions"] == 30
    # repeat: the verdict stays False (the memo cannot launder a failure)
    assert not batch.verify_batch(pks, msgs, sigs)[7]


def test_bad_rows_never_cached():
    memo = _memo_on()
    pks, msgs, sigs = _signed(20, b"\x33")
    sigs = list(sigs)
    sigs[4] = sigs[4][:32] + b"\xff" * 32  # non-canonical s: verdict False
    mask = batch.verify_batch(pks, msgs, sigs)
    assert not mask[4] and mask.sum() == 19
    assert len(memo) == 19
    d = memo.digest_rows([pks[4]], [msgs[4]], [sigs[4]])[0]
    assert d not in memo


def test_failed_flush_caches_nothing(monkeypatch):
    memo = _memo_on()
    pks, msgs, sigs = _signed(16, b"\x34")

    def boom(*a, **kw):
        raise RuntimeError("injected flush failure")

    monkeypatch.setattr(batch, "_verify_batch_routed", boom)
    with pytest.raises(RuntimeError, match="injected flush failure"):
        batch.verify_batch(pks, msgs, sigs)
    assert len(memo) == 0
    assert memo.stats()["insertions"] == 0


# ---------------------------------------------------------------------------
# bounds and disablement


def test_eviction_bound_under_10k_flood():
    memo = batch.VerifiedRowMemo(1000)
    rng = np.random.default_rng(7)
    digests = [rng.bytes(32) for _ in range(10_000)]
    ones = np.ones(1000, dtype=bool)
    for lo in range(0, 10_000, 1000):
        memo.insert(digests[lo : lo + 1000], ones)
    st = memo.stats()
    assert len(memo) == 1000
    assert st["insertions"] == 10_000
    assert st["evictions"] == 9_000
    # LRU: the newest 1000 survive, the oldest 9000 are gone
    assert memo.lookup(digests[-1000:]).all()
    assert not memo.lookup(digests[:1000]).any()


def test_capacity_zero_disables():
    memo = _memo_on(0)
    pks, msgs, sigs = _signed(12, b"\x35")
    assert batch.verify_batch(pks, msgs, sigs).all()
    assert batch.verify_batch(pks, msgs, sigs).all()  # re-verified, no memo
    st = memo.stats()
    assert st["capacity"] == 0
    assert st["hits"] == 0 and st["insertions"] == 0
    assert len(memo) == 0


def test_digest_framing_is_unambiguous():
    """pk||msg boundary shifts must produce different digests (the frame
    prevents "ab"+"c" aliasing "a"+"bc")."""
    memo = batch.VerifiedRowMemo(16)
    d1 = memo.digest_rows([b"ab"], [b"c"], [b"sig"])[0]
    d2 = memo.digest_rows([b"a"], [b"bc"], [b"sig"])[0]
    assert d1 != d2


def test_scheduler_stats_carry_memo_block():
    _memo_on(128)
    pks, msgs, sigs = _signed(8, b"\x36")
    assert batch.verify_batch(pks, msgs, sigs).all()
    assert batch.verified_memo_stats()["insertions"] == 8


# ---------------------------------------------------------------------------
# integration: deferred-verified votes -> commit verify through the memo


def test_deferred_commit_verifies_through_memo():
    """The consensus shape the memo exists for: precommits batch-verified
    by the deferred VoteSet flush populate the memo; the commit assembled
    from those SAME votes then verifies with zero re-verified rows."""
    from tendermint_tpu.types.block import BlockID, PartSetHeader
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.types.vote import Vote
    from tendermint_tpu.types.vote_set import VoteSet

    memo = _memo_on()
    rng = np.random.default_rng(42)
    privs = [
        gen_ed25519(rng.integers(0, 256, 32, dtype=np.uint8).tobytes())
        for _ in range(48)
    ]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    sorted_privs = [by_addr[v.address] for v in vals.validators]
    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))

    vs = VoteSet("memo-chain", 1, 0, 2, vals, defer_verification=True)
    for i, (val, priv) in enumerate(zip(vals.validators, sorted_privs)):
        v = Vote(type=2, height=1, round=0, block_id=bid, timestamp_ns=0,
                 validator_address=val.address, validator_index=i)
        v = dataclasses.replace(v, signature=priv.sign(v.sign_bytes("memo-chain")))
        assert vs.add_vote(v) == "pending"
    committed, failed = vs.flush()
    assert len(committed) == 48 and not failed
    assert len(memo) == 48  # the deferred flush populated the memo

    commit = vs.make_commit()
    misses0 = memo.stats()["misses"]
    vals.verify_commit("memo-chain", bid, 1, commit)  # must not raise

    st = memo.stats()
    assert st["misses"] == misses0  # ZERO re-verified rows
    assert st["hits"] == 48        # the commit's full memo hit
    lf = _last_flush()
    assert lf["backend"] == "memo" and lf["memo_hits"] == 48


# ---------------------------------------------------------------------------
# memo x quarantine (ISSUE 20): the adversarial flush defense must not
# change the memo's safety contract, and the memo must not blind the
# suspicion scorer.


@pytest.fixture
def scratch_scorer():
    from tendermint_tpu.crypto import provenance as prov

    scorer = prov.SuspicionScorer(fail_quarantine=3, parole_clean=30)
    prev = prov.set_default(scorer)
    yield scorer
    prov.set_default(prev)


def test_quarantined_clean_rows_may_enter_memo(scratch_scorer):
    """A quarantined source's rows that verify CLEAN are memo-eligible:
    quarantine is a scheduling demotion (slow lane), not a verdict — the
    memo caches verdicts, and a clean verdict is a clean verdict."""
    memo = _memo_on()
    pks, msgs, sigs = _signed(20, b"\x37")
    srcs = ["peer:mallory"] * 20

    # quarantine the source with a poisoned flush first
    bad = list(sigs)
    for i in (0, 1, 2):
        bad[i] = bad[i][:32] + (1).to_bytes(32, "little")
    mask = batch.verify_batch(pks, msgs, bad, sources=srcs)
    assert mask.sum() == 17
    assert scratch_scorer.is_quarantined("peer:mallory")

    # the 17 clean rows were memoized; the 3 failed rows were NOT
    assert len(memo) == 17
    for i in (0, 1, 2):
        d = memo.digest_rows([pks[i]], [msgs[i]], [bad[i]])[0]
        assert d not in memo

    # a fully-clean flush from the still-quarantined source memoizes too
    assert batch.verify_batch(pks, msgs, sigs, sources=srcs).all()
    assert len(memo) == 20


def test_memo_hits_count_toward_parole(scratch_scorer):
    """Memo-answered rows verified clean in an earlier flush still feed
    the scorer: a quarantined source whose repeats resolve through the
    memo must be able to earn parole, not be starved of clean credit."""
    _memo_on()
    pks, msgs, sigs = _signed(16, b"\x38")
    srcs = ["peer:flaky"] * 16

    bad = list(sigs)
    for i in (0, 1, 2):
        bad[i] = bad[i][:32] + (1).to_bytes(32, "little")
    batch.verify_batch(pks, msgs, bad, sources=srcs)
    assert scratch_scorer.is_quarantined("peer:flaky")

    # first clean flush verifies for real (16 clean), the second resolves
    # entirely through the memo — BOTH must advance the clean streak
    assert batch.verify_batch(pks, msgs, sigs, sources=srcs).all()
    assert scratch_scorer.is_quarantined("peer:flaky")  # 16 < 30
    assert batch.verify_batch(pks, msgs, sigs, sources=srcs).all()
    assert _last_flush()["backend"] == "memo"
    assert not scratch_scorer.is_quarantined("peer:flaky")  # 32 >= 30: parole
    assert scratch_scorer.stats()["paroles"] == 1


def test_memo_never_launders_a_poisoned_row_across_sources(scratch_scorer):
    """A poisoned row replayed by a DIFFERENT source still fails: the
    memo keys on row bytes, failed rows are never inserted, so a replay
    re-verifies, fails again, and indicts the replaying source too."""
    memo = _memo_on()
    pks, msgs, sigs = _signed(12, b"\x39")
    bad = list(sigs)
    bad[5] = bad[5][:32] + (1).to_bytes(32, "little")

    mask = batch.verify_batch(pks, msgs, bad, sources=["peer:a"] * 12)
    assert not mask[5] and len(memo) == 11

    # peer:b replays JUST the poisoned row, over and over: every replay
    # misses the memo, re-verifies, fails — and accumulates suspicion
    # (clean-row decay never sees a clean row to forgive with)
    for _ in range(3):
        mask = batch.verify_batch(
            [pks[5]], [msgs[5]], [bad[5]], sources=["peer:b"]
        )
        assert not mask[0]
    assert scratch_scorer.is_quarantined("peer:b")
