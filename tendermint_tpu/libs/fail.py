"""Fail-point crash injection (reference: libs/fail/fail.go).

Two injection mechanisms share the fail-point call sites:

1. Env-driven hard crash (the original matrix): set TMTPU_FAIL_INDEX=<n>;
   the n-th fail point hit in the process aborts it hard (os._exit),
   simulating a crash at that exact ordering point. Used by the
   crash-recovery test matrix around the commit/apply sequence
   (reference: state/execution.go:143-189, consensus/state.go:746,
   test/persist/test_failure_indices.sh).

2. Programmatic handlers (the chaos engine's in-process mode): `inject()`
   registers a callable for a NAMED fail point; when that point is hit the
   handler runs and may raise (e.g. SimulatedCrash) to crash the component
   without killing the test process — the multinode chaos harness pairs
   this with chaos.process.hard_kill to model crash/restart cycles
   deterministically (tendermint_tpu/chaos/).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

_counter = 0

# name -> handler; consulted BEFORE the env counter so a chaos schedule can
# target a specific ordering point by name instead of by global hit index.
_HANDLERS: Dict[str, Callable[[], None]] = {}


class SimulatedCrash(Exception):
    """Raised by injected fail-point handlers to crash a component in-process
    (the consensus receive loop treats any escaped exception as a consensus
    failure and halts — the in-process analog of os._exit)."""


def fail_index() -> int:
    try:
        return int(os.environ.get("TMTPU_FAIL_INDEX", "-1"))
    except ValueError:
        return -1


def reset() -> None:
    global _counter
    _counter = 0


def inject(name: str, handler: Optional[Callable[[], None]]) -> None:
    """Register (or, with None, remove) a handler for a named fail point."""
    if handler is None:
        _HANDLERS.pop(name, None)
    else:
        _HANDLERS[name] = handler


def clear_injections() -> None:
    _HANDLERS.clear()


def fail_point(name: str = "") -> None:
    global _counter
    handler = _HANDLERS.get(name)
    if handler is not None:
        handler()  # may raise (SimulatedCrash) back into the caller
    target = fail_index()
    if target < 0:
        return
    if _counter == target:
        os.write(2, f"FAIL_POINT {_counter} {name}: crashing\n".encode())
        os._exit(77)
    _counter += 1
