"""EventBus — typed wrapper over pubsub (reference: types/event_bus.go:33).

Composite keys follow the reference convention: `tm.event` for the event type,
`tx.hash`/`tx.height` for txs, and app-emitted `<event_type>.<attr_key>`."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs import hotstats as _hotstats
from tendermint_tpu.libs.pubsub import PubSubServer, Query, Subscription

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_POLKA = "Polka"
EVENT_LOCK = "Lock"
EVENT_RELOCK = "Relock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_VOTE = "Vote"
EVENT_VALID_BLOCK = "ValidBlock"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_TX = "Tx"

EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"


def query_for_event(event_type: str) -> Query:
    return Query(f"{EVENT_TYPE_KEY} = '{event_type}'")


@dataclass
class EventDataTx:
    height: int
    index: int
    tx: bytes
    result: object  # abci.ResponseDeliverTx


@dataclass
class EventDataNewBlock:
    block: object
    block_id: object
    result_begin_block: object
    result_end_block: object


@dataclass
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass
class EventDataVote:
    vote: object


class EventBus:
    def __init__(self):
        self.pubsub = PubSubServer()

    def subscribe(self, subscriber: str, query: Query, out_capacity: int = 100) -> Subscription:
        return self.pubsub.subscribe(subscriber, query, out_capacity)

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        self.pubsub.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self.pubsub.unsubscribe_all(subscriber)

    def _publish(self, event_type: str, data: object, extra: Optional[Dict[str, List[str]]] = None) -> None:
        hs = _hotstats.stats if _hotstats.stats.enabled else None
        t0 = _hotstats.perf_counter() if hs is not None else 0.0
        self._publish_untimed(event_type, data, extra)
        if hs is not None:
            hs.add("pubsub", _hotstats.perf_counter() - t0, n=0)

    def _publish_untimed(self, event_type: str, data: object, extra: Optional[Dict[str, List[str]]] = None) -> None:
        # Zero-subscriber fast path: consensus publishes events for every
        # vote/step whether or not anyone listens; skip the event-map build
        # and the query walk when nothing could match.
        if not self.pubsub.has_subscribers(event_type):
            return
        events = {EVENT_TYPE_KEY: [event_type]}
        if extra:
            for k, v in extra.items():
                events.setdefault(k, []).extend(v)
        self.pubsub.publish(data, events)

    @staticmethod
    def _abci_events_to_map(abci_events) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for ev in abci_events or []:
            for key, value, index in ev.attributes:
                if not index:
                    continue
                k = f"{ev.type}.{key.decode(errors='replace')}"
                out.setdefault(k, []).append(value.decode(errors="replace"))
        return out

    def publish_new_block(self, block, block_id, abci_responses) -> None:
        if not self.pubsub.has_subscribers(EVENT_NEW_BLOCK):
            return
        extra: Dict[str, List[str]] = {}
        if abci_responses.begin_block is not None:
            extra.update(self._abci_events_to_map(abci_responses.begin_block.events))
        if abci_responses.end_block is not None:
            extra.update(self._abci_events_to_map(abci_responses.end_block.events))
        self._publish(
            EVENT_NEW_BLOCK,
            EventDataNewBlock(block, block_id, abci_responses.begin_block, abci_responses.end_block),
            extra,
        )

    def publish_tx(self, height: int, index: int, tx: bytes, result) -> None:
        if not self.pubsub.has_subscribers(EVENT_TX):
            return
        extra = {
            TX_HASH_KEY: [tmhash.sum256(tx).hex().upper()],
            TX_HEIGHT_KEY: [str(height)],
        }
        extra.update(self._abci_events_to_map(getattr(result, "events", None)))
        self._publish(EVENT_TX, EventDataTx(height, index, tx, result), extra)

    def publish_validator_set_updates(self, updates) -> None:
        self._publish(EVENT_VALIDATOR_SET_UPDATES, updates)

    def publish_vote(self, vote) -> None:
        hs = _hotstats.stats if _hotstats.stats.enabled else None
        t0 = _hotstats.perf_counter() if hs is not None else 0.0
        # explicit check (not just _publish's) so the EventDataVote wrapper
        # is never allocated on the zero-subscriber path
        if self.pubsub.has_subscribers(EVENT_VOTE):
            self._publish_untimed(EVENT_VOTE, EventDataVote(vote))
        if hs is not None:
            hs.add("pubsub", _hotstats.perf_counter() - t0)

    def publish_votes(self, votes) -> None:
        """Batch publish for the deferred-vote drain: one subscriber-match
        pass for the whole flush (pubsub.publish_many)."""
        if not votes:
            return
        hs = _hotstats.stats if _hotstats.stats.enabled else None
        t0 = _hotstats.perf_counter() if hs is not None else 0.0
        if self.pubsub.has_subscribers(EVENT_VOTE):
            self.pubsub.publish_many(
                [EventDataVote(v) for v in votes], {EVENT_TYPE_KEY: [EVENT_VOTE]}
            )
        if hs is not None:
            hs.add("pubsub", _hotstats.perf_counter() - t0, n=len(votes))

    def publish_round_state(self, event_type: str, height: int, round_: int, step: str) -> None:
        self._publish(event_type, EventDataRoundState(height, round_, step))
