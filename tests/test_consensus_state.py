"""Consensus state machine tests: locking/POL rules against the real
ConsensusState with validator stubs — no network.

These are the spec scenarios from the reference's consensus/state_test.go
(:343 LockNoPOL, :529 POLRelock, POLUnlock, :844 POLSafety, timeouts, commit).
The fixture is the analog of consensus/common_test.go: validatorStub (:81)
signs real votes; we drive cs by enqueueing peer messages and awaiting
event-bus events."""

import asyncio
import time

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import test_config
from tendermint_tpu.consensus.cs_state import ConsensusState
from tendermint_tpu.consensus.messages import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.consensus.round_state import RoundStepType
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.proxy.multi import AppConns, local_client_creator
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.sm_state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.blockstore import BlockStore
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.event_bus import (
    EVENT_NEW_ROUND_STEP,
    EventBus,
    query_for_event,
)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote


class ValidatorStub:
    """Signs real votes for injection as peer messages
    (reference: consensus/common_test.go:81 validatorStub)."""

    def __init__(self, priv: FilePV, index: int, chain_id: str):
        self.priv = priv
        self.index = index
        self.chain_id = chain_id
        self.address = priv.get_pub_key().address()

    def sign_vote(self, type_, height, round_, block_id: BlockID, raw: bool = False) -> Vote:
        vote = Vote(
            type=type_,
            height=height,
            round=round_,
            block_id=block_id,
            timestamp_ns=time.time_ns(),
            validator_address=self.address,
            validator_index=self.index,
        )
        if raw:
            # byzantine signing: bypass the double-sign guard
            import dataclasses

            sig = self.priv.priv_key.sign(vote.sign_bytes(self.chain_id))
            return dataclasses.replace(vote, signature=sig)
        return self.priv.sign_vote(self.chain_id, vote)


class Fixture:
    def __init__(self, n_vals: int, tmp_path, chain_id="cs-test-chain"):
        self.chain_id = chain_id
        privs = [FilePV(gen_ed25519(bytes([50 + i]) * 32)) for i in range(n_vals)]
        gen = GenesisDoc(
            chain_id=chain_id,
            validators=[GenesisValidator(p.get_pub_key(), 10) for p in privs],
        )
        gen.validate_and_complete()
        state = state_from_genesis(gen)
        # sort stubs to match validator-set order
        valset = state.validators
        by_addr = {p.get_pub_key().address(): p for p in privs}
        self.privs = [by_addr[v.address] for v in valset.validators]
        self.stubs = [
            ValidatorStub(p, i, chain_id) for i, p in enumerate(self.privs)
        ]

        app = KVStoreApplication()
        self.proxy = AppConns(local_client_creator(app))
        self.block_store = BlockStore(MemDB())
        self.state_store = StateStore(MemDB())
        self.state_store.save(state)
        self.event_bus = EventBus()
        self.mempool = Mempool(self.proxy.mempool)
        self.evpool = EvidencePool(MemDB(), self.state_store, self.block_store)
        self.evpool.set_state(state)
        self.block_exec = BlockExecutor(
            self.state_store, self.proxy.consensus, self.mempool, self.evpool,
            event_bus=self.event_bus, block_store=self.block_store,
        )
        cfg = test_config().consensus
        cfg.wal_path = str(tmp_path / "wal")
        # init chain through the app so app state matches height 0
        from tendermint_tpu.consensus.replay import Handshaker

        state = Handshaker(self.state_store, state, self.block_store, gen, self.event_bus).handshake(self.proxy)
        self.cs = ConsensusState(
            cfg, state, self.block_exec, self.block_store, self.mempool,
            self.evpool, WAL(str(tmp_path / "wal")), event_bus=self.event_bus,
            priv_validator=self.privs[0],  # we are validator 0
        )
        self.steps = self.event_bus.subscribe("test", query_for_event(EVENT_NEW_ROUND_STEP), 500)

    async def start(self):
        await self.cs.start()

    async def stop(self):
        await self.cs.stop()

    # -- helpers -----------------------------------------------------------

    async def wait_step(self, step: RoundStepType, height=None, round_=None, timeout=5.0):
        """Wait until cs publishes a NewRoundStep matching the criteria."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"waiting for {step.name} h={height} r={round_}; at "
                    f"{self.cs.rs.height}/{self.cs.rs.round}/{self.cs.rs.step.name}"
                )
            try:
                msg = await asyncio.wait_for(self.steps.next(), remaining)
            except asyncio.TimeoutError:
                continue
            d = msg.data
            if d.step != step.name:
                continue
            if height is not None and d.height != height:
                continue
            if round_ is not None and d.round != round_:
                continue
            return

    async def add_votes(self, type_, height, round_, block_id: BlockID, idxs, raw=False):
        for i in idxs:
            vote = self.stubs[i].sign_vote(type_, height, round_, block_id, raw=raw)
            await self.cs.add_peer_message(VoteMessage(vote), f"stub-{i}")
        await self.drain()

    async def drain(self, t=0.08):
        await asyncio.sleep(t)

    def make_block(self, height: int, proposer_idx: int = 1, txs=()):
        """Build a valid proposal block signed state (block + parts)."""
        from tendermint_tpu.types.block import Commit as CommitT

        state = self.cs.state
        if height == state.initial_height:
            commit = CommitT(0, 0, BlockID(), ())
        else:
            commit = self.cs.rs.last_commit.make_commit()
        proposer = self.cs.rs.validators.validators[proposer_idx]
        block = self.block_exec.create_proposal_block(
            height, state, commit, proposer.address, time.time_ns()
        )
        parts = PartSet.from_data(block.encode())
        return block, parts

    def make_signed_proposal(self, block, parts, round_: int, proposer_idx: int, pol_round=-1):
        bid = BlockID(block.hash(), parts.header)
        prop = Proposal(
            height=block.header.height, round=round_, pol_round=pol_round,
            block_id=bid, timestamp_ns=time.time_ns(),
        )
        return self.privs[proposer_idx].sign_proposal(self.chain_id, prop)

    async def inject_proposal(self, block, parts, round_: int, proposer_idx: int,
                              pol_round=-1, prop=None):
        if prop is None:
            prop = self.make_signed_proposal(block, parts, round_, proposer_idx, pol_round)
        await self.cs.add_peer_message(ProposalMessage(prop), f"stub-{proposer_idx}")
        for i in range(parts.total):
            await self.cs.add_peer_message(
                BlockPartMessage(block.header.height, round_, parts.get_part(i)),
                f"stub-{proposer_idx}",
            )
        await self.drain()


NIL = BlockID()


def run_async(coro):
    asyncio.run(coro)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def test_full_round_commits(tmp_path):
    """All validators vote for the proposal -> commit (state_test.go
    TestStateFullRound2 analog)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            # we are validator 0; proposer for h1/r0 may be any validator.
            if rs.proposal_block is None:
                # inject a proposal from the actual proposer
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            assert rs.proposal_block is not None
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid, [1, 2, 3])
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            commit = fx.block_store.load_seen_commit(1)
            assert sum(0 if s.absent() else 1 for s in commit.signatures) >= 3
        finally:
            await fx.stop()

    run_async(main())


def test_lock_no_pol_prevotes_locked_block(tmp_path):
    """Once locked, without a new POL we keep prevoting the locked block in
    later rounds and precommit nil elsewhere (state_test.go:343 LockNoPOL)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)

            # polka at round 0 -> we lock
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_block is not None
            assert fx.cs.rs.locked_round == 0

            # +2/3 precommit nil -> move to round 1, still locked
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)  # our internal prevote flows through the queue
            assert fx.cs.rs.locked_block is not None
            # our round-1 prevote must be for the LOCKED block
            prevotes = fx.cs.rs.votes.prevotes(1)
            our = prevotes.get_by_index(0)
            assert our is not None and our.block_id.hash == bid.hash

            # two nil prevotes (NO nil polka: 20/40) -> 2/3-any triggers
            # prevote-wait; on timeout we precommit nil but REMAIN locked
            # (unlock requires an actual nil polka, covered by
            # test_pol_unlock_on_nil_polka)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, NIL, [1, 2])
            await fx.drain(1.0)  # prevote-wait timeout (0.2s+delta) fires
            precommits = fx.cs.rs.votes.precommits(1)
            ourpc = precommits.get_by_index(0)
            assert ourpc is not None and ourpc.block_id.is_zero()
            assert fx.cs.rs.locked_block is not None  # still locked
        finally:
            await fx.stop()

    run_async(main())


def test_pol_relock_on_same_block(tmp_path):
    """A new polka for the SAME locked block in a later round relocks
    (state_test.go:529 POLRelock-ish)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            block, parts = rs.proposal_block, rs.proposal_block_parts
            bid = BlockID(block.hash(), parts.header)

            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_round == 0

            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)

            # polka for the same block at round 1
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_round == 1  # relocked
            precommits = fx.cs.rs.votes.precommits(1)
            ourpc = precommits.get_by_index(0)
            assert ourpc is not None and ourpc.block_id.hash == bid.hash
        finally:
            await fx.stop()

    run_async(main())


def test_pol_unlock_on_nil_polka(tmp_path):
    """+2/3 prevote nil in a later round unlocks (state_test.go POLUnlock)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)

            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_block is not None

            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)

            # nil polka in round 1 -> unlock, precommit nil
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, NIL, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_block is None
            assert fx.cs.rs.locked_round == -1
        finally:
            await fx.stop()

    run_async(main())


def test_pol_safety_no_prevote_for_unlocked_new_block(tmp_path):
    """Locked on block A; a DIFFERENT block polka'd in a round we didn't see
    as a POL must not get our prevote; but a polka we DO see for block B in a
    later round unlocks us and (without B) we precommit nil
    (state_test.go:844 POLSafety shape)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid_a = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)

            # lock on A
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_a, [1, 2, 3])
            await fx.drain(0.3)
            assert fx.cs.rs.locked_block is not None

            # round 1: others claim polka for unknown block B (we never get B's
            # parts) -> we unlock (saw the polka) and precommit nil
            fake_psh = PartSetHeader(total=1, hash=b"\x99" * 32)
            bid_b = BlockID(b"\x88" * 32, fake_psh)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)
            # our prevote in round 1 is for LOCKED A (we saw no POL for B yet)
            our = fx.cs.rs.votes.prevotes(1).get_by_index(0)
            assert our is not None and our.block_id.hash == bid_a.hash

            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid_b, [1, 2, 3])
            await fx.drain(0.4)
            # polka for B seen -> unlock; we don't have B -> precommit nil
            assert fx.cs.rs.locked_block is None
            ourpc = fx.cs.rs.votes.precommits(1).get_by_index(0)
            assert ourpc is not None and ourpc.block_id.is_zero()
        finally:
            await fx.stop()

    run_async(main())


def test_propose_timeout_leads_to_nil_prevote(tmp_path):
    """No proposal arrives -> propose timeout -> prevote nil."""

    async def main():
        fx = Fixture(4, tmp_path)
        # make sure we aren't the round-0 proposer: if we are, the test is
        # trivially different; force by picking a fixture where proposer != 0
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PREVOTE, height=1, timeout=10)
            rs = fx.cs.rs
            our = rs.votes.prevotes(rs.round).get_by_index(0)
            proposer_is_us = rs.validators.get_proposer().address == fx.stubs[0].address
            if not proposer_is_us:
                assert our is not None and our.block_id.is_zero()
        finally:
            await fx.stop()

    run_async(main())


def test_round_skip_on_future_round_votes(tmp_path):
    """+2/3 prevotes at a future round move us to that round
    (state_test.go round-skip behavior)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 3, NIL, [1, 2, 3])
            await fx.drain(0.5)
            assert fx.cs.rs.round == 3
        finally:
            await fx.stop()

    run_async(main())


def test_late_precommit_for_previous_height(tmp_path):
    """A precommit for height-1 arriving during NEW_HEIGHT is added to
    last_commit (addVote :1880 first branch)."""

    async def main():
        fx = Fixture(4, tmp_path)
        # slow down round0 so we stay in NEW_HEIGHT after a commit
        fx.cs.config.timeout_commit = 2.0
        fx.cs.config.skip_timeout_commit = False
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            bid = BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid, [1, 2])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid, [1, 2])
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            # now at height 2, NEW_HEIGHT (commit timeout 2s); send the late precommit
            assert fx.cs.rs.height == 2
            before = sum(1 for s in fx.cs.rs.last_commit.bit_array() if s)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid, [3])
            await fx.drain(0.3)
            after = sum(1 for s in fx.cs.rs.last_commit.bit_array() if s)
            assert after == before + 1
        finally:
            await fx.stop()

    run_async(main())


def test_conflicting_votes_produce_evidence(tmp_path):
    """Equivocating prevotes from a stub produce DuplicateVoteEvidence in the
    pool (byzantine detection at the VoteSet level)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.2)
            psh = PartSetHeader(total=1, hash=b"\x11" * 32)
            bid1 = BlockID(b"\x22" * 32, psh)
            bid2 = BlockID(b"\x33" * 32, psh)
            v1 = fx.stubs[2].sign_vote(SignedMsgType.PREVOTE, 1, 0, bid1, raw=True)
            v2 = fx.stubs[2].sign_vote(SignedMsgType.PREVOTE, 1, 0, bid2, raw=True)
            await fx.cs.add_peer_message(VoteMessage(v1), "stub-2")
            await fx.cs.add_peer_message(VoteMessage(v2), "stub-2")
            await fx.drain(0.3)
            pend = fx.evpool.pending_evidence(-1)
            assert len(pend) == 1
            ev = pend[0]
            assert ev.vote_a.validator_address == fx.stubs[2].address
        finally:
            await fx.stop()

    run_async(main())


def test_unlock_then_commit_different_block_round1(tmp_path):
    """After unlocking, a polka + precommits for a new block B in round 1
    commits B (liveness after unlock)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.3)
            rs = fx.cs.rs
            if rs.proposal_block is None:
                proposer_idx = next(
                    i for i, v in enumerate(rs.validators.validators)
                    if v.address == rs.validators.get_proposer().address
                )
                block, parts = fx.make_block(1, proposer_idx)
                await fx.inject_proposal(block, parts, 0, proposer_idx)
            rs = fx.cs.rs
            block_a = rs.proposal_block
            parts_a = rs.proposal_block_parts
            bid_a = BlockID(block_a.hash(), parts_a.header)

            # lock on A, then nil precommits move to round 1
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_a, [1, 2, 3])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.3)

            # commit A in round 1: polka + precommits for A (it's the locked block)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid_a, [1, 2, 3])
            await fx.drain(0.3)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 1, bid_a, [1, 2, 3])
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            saved = fx.block_store.load_block(1)
            assert saved.hash() == block_a.hash()
        finally:
            await fx.stop()

    run_async(main())


# ---------------------------------------------------------------------------
# round-3 matrix: proposer selection, bad proposals, POL safety 1/2,
# valid-block rules, commit paths, slashing (state_test.go:57,183,844,963,
# 1060,1150,1212,1422,1633,1678)
# ---------------------------------------------------------------------------


def _cur_proposer_idx(fx) -> int:
    rs = fx.cs.rs
    addr = rs.validators.get_proposer().address
    return next(i for i, v in enumerate(rs.validators.validators) if v.address == addr)


async def _ensure_proposal(fx, height=1):
    """Complete proposal for the CURRENT round: cs's own if it proposed,
    otherwise injected from the actual proposer. Returns (block, parts, bid)."""
    await fx.drain(0.3)
    rs = fx.cs.rs
    if rs.proposal_block is None:
        idx = _cur_proposer_idx(fx)
        block, parts = fx.make_block(height, idx)
        await fx.inject_proposal(block, parts, rs.round, idx)
    rs = fx.cs.rs
    assert rs.proposal_block is not None
    return (
        rs.proposal_block,
        rs.proposal_block_parts,
        BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header),
    )


async def _advance_round_via_nil(fx, height, round_):
    """Drive a full nil round: +2/3 nil prevotes then nil precommits, wait
    for the next round's PROPOSE step."""
    await fx.add_votes(SignedMsgType.PREVOTE, height, round_, NIL, [1, 2, 3])
    await fx.add_votes(SignedMsgType.PRECOMMIT, height, round_, NIL, [1, 2, 3])
    await fx.wait_step(RoundStepType.PROPOSE, height=height, round_=round_ + 1, timeout=10)


def test_proposer_selection_rotates_across_rounds(tmp_path):
    """Equal-power validators take turns proposing round by round
    (state_test.go:57 ProposerSelection0 shape)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            seen = {_cur_proposer_idx(fx)}
            # NB: with all-zero genesis priorities rounds 0 and 1 elect the
            # SAME proposer (the decrement happens inside the increment call,
            # so round 1's +power leaves the tie unbroken) — matching the
            # reference's priority algorithm. 5 rounds cover the full cycle.
            for r in range(4):
                await _advance_round_via_nil(fx, 1, r)
                await fx.drain(0.2)
                seen.add(_cur_proposer_idx(fx))
            assert seen == {0, 1, 2, 3}
        finally:
            await fx.stop()

    run_async(main())


def test_enter_propose_as_proposer_creates_proposal(tmp_path):
    """When WE are the round's proposer, entering propose creates and signs a
    proposal without any network input (state_test.go:153)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            r = 0
            while _cur_proposer_idx(fx) != 0 and r < 5:
                await _advance_round_via_nil(fx, 1, r)
                await fx.drain(0.2)
                r += 1
            assert _cur_proposer_idx(fx) == 0
            await fx.drain(0.3)
            rs = fx.cs.rs
            assert rs.proposal is not None  # we proposed
            assert rs.proposal_block is not None
            # signed by us, for this height/round
            assert rs.proposal.height == 1 and rs.proposal.round == r
            pub = fx.privs[0].get_pub_key()
            assert pub.verify(
                rs.proposal.sign_bytes(fx.chain_id), rs.proposal.signature
            )
        finally:
            await fx.stop()

    run_async(main())


def test_bad_proposal_wrong_signer_rejected(tmp_path):
    """A proposal signed by a non-proposer is rejected and we prevote nil
    after the propose timeout (state_test.go:183 BadProposal shape)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.1)
            r = 0
            while _cur_proposer_idx(fx) == 0 and r < 5:
                await _advance_round_via_nil(fx, 1, r)
                await fx.drain(0.2)
                r += 1
            rs = fx.cs.rs
            if rs.proposal is None:
                proposer = _cur_proposer_idx(fx)
                wrong = next(i for i in range(1, 4) if i != proposer)
                block, parts = fx.make_block(1, proposer)
                await fx.inject_proposal(block, parts, rs.round, wrong)
                assert fx.cs.rs.proposal is None  # rejected: bad signature
            await fx.wait_step(RoundStepType.PREVOTE, height=1, timeout=10)
            await fx.drain(0.2)
            our = fx.cs.rs.votes.prevotes(fx.cs.rs.round).get_by_index(0)
            assert our is not None and our.block_id.is_zero()
        finally:
            await fx.stop()

    run_async(main())


def test_bad_proposal_invalid_block_prevotes_nil(tmp_path):
    """A correctly-signed proposal whose block fails validation (tampered
    app_hash) gets a nil prevote (state_test.go:183)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.1)
            r = 0
            while _cur_proposer_idx(fx) == 0 and r < 5:
                await _advance_round_via_nil(fx, 1, r)
                await fx.drain(0.2)
                r += 1
            rs = fx.cs.rs
            if rs.proposal_block is None:
                import dataclasses

                idx = _cur_proposer_idx(fx)
                block, _ = fx.make_block(1, idx)
                bad_header = dataclasses.replace(block.header, app_hash=b"\xde" * 32)
                bad_block = dataclasses.replace(block, header=bad_header)
                parts = PartSet.from_data(bad_block.encode())
                await fx.inject_proposal(bad_block, parts, rs.round, idx)
                await fx.wait_step(RoundStepType.PREVOTE, height=1, timeout=10)
                await fx.drain(0.2)
                our = fx.cs.rs.votes.prevotes(fx.cs.rs.round).get_by_index(0)
                assert our is not None and our.block_id.is_zero()
        finally:
            await fx.stop()

    run_async(main())


def test_full_round_nil_precommits_nil(tmp_path):
    """No proposal at all: prevote nil, nil polka, precommit nil
    (state_test.go:285 FullRoundNil)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PREVOTE, height=1, timeout=10)
            rs = fx.cs.rs
            if _cur_proposer_idx(fx) == 0:
                return  # we proposed; scenario needs a missing proposal
            await fx.add_votes(SignedMsgType.PREVOTE, 1, rs.round, NIL, [1, 2, 3])
            await fx.drain(0.4)
            ourpc = fx.cs.rs.votes.precommits(rs.round).get_by_index(0)
            assert ourpc is not None and ourpc.block_id.is_zero()
        finally:
            await fx.stop()

    run_async(main())


def test_pol_safety1_missed_polka_does_not_relock_old_block(tmp_path):
    """We miss round 0's polka for A, lock B in round 1; late round-0
    prevotes for A must not move us (state_test.go:844 POLSafety1)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            block_a, parts_a, bid_a = await _ensure_proposal(fx)
            # the others polka A but we never see the prevotes; we see only
            # nil precommits, carrying us to round 1
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PROPOSE, height=1, round_=1, timeout=10)
            await fx.drain(0.2)
            assert fx.cs.rs.locked_block is None

            # round 1: a NEW block B proposed (cs's own if we are the
            # round-1 proposer); we prevote it (not locked)
            block_b, parts_b, bid_b = await _ensure_proposal(fx)
            assert block_b.hash() != block_a.hash()
            await fx.drain(0.3)
            our = fx.cs.rs.votes.prevotes(1).get_by_index(0)
            assert our is not None and our.block_id.hash == bid_b.hash

            # polka for B -> lock B, precommit B
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid_b, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_round == 1
            assert fx.cs.rs.locked_block.hash() == block_b.hash()

            # nil precommits -> round 2; propose timeout -> prevote locked B
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 1, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=2, timeout=10)
            await fx.drain(0.3)
            our2 = fx.cs.rs.votes.prevotes(2).get_by_index(0)
            assert our2 is not None and our2.block_id.hash == bid_b.hash

            # NOW the round-0 polka for A shows up late (signed back in
            # round 0 -> raw, bypassing the stubs' forward-moving HRS guard)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_a, [1, 2, 3], raw=True)
            await fx.drain(0.4)
            # must not unlock or change rounds
            assert fx.cs.rs.locked_block.hash() == block_b.hash()
            assert fx.cs.rs.locked_round == 1
            assert fx.cs.rs.round == 2
        finally:
            await fx.stop()

    run_async(main())


def test_pol_safety2_old_pol_proposal_does_not_unlock(tmp_path):
    """Locked on B1 from round 1; round 2 re-proposes round-0's polka'd block
    B0 with pol_round=0 — we must keep prevoting B1
    (state_test.go:963 POLSafety2)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            # round-0 block B0 (built but its polka stays hidden for now)
            block_b0, parts_b0, bid_b0 = await _ensure_proposal(fx)

            # we move to round 1 on nil votes (never seeing B0's polka)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, NIL, [1, 2])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PROPOSE, height=1, round_=1, timeout=10)
            await fx.drain(0.2)

            # round 1: propose + polka B1 -> we lock B1
            idx1 = _cur_proposer_idx(fx)
            block_b1, parts_b1 = fx.make_block(1, idx1)
            bid_b1 = BlockID(block_b1.hash(), parts_b1.header)
            if fx.cs.rs.proposal_block is None:
                await fx.inject_proposal(block_b1, parts_b1, 1, idx1)
            else:
                block_b1 = fx.cs.rs.proposal_block
                parts_b1 = fx.cs.rs.proposal_block_parts
                bid_b1 = BlockID(block_b1.hash(), parts_b1.header)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, bid_b1, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_round == 1

            # nil precommits -> round 2
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 1, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PROPOSE, height=1, round_=2, timeout=10)
            await fx.drain(0.2)

            # round 2: B0 re-proposed with pol_round=0 plus its old polka
            idx2 = _cur_proposer_idx(fx)
            if idx2 != 0:
                await fx.inject_proposal(block_b0, parts_b0, 2, idx2, pol_round=0)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_b0, [1, 2, 3], raw=True)
            await fx.drain(0.4)

            # a POL from BEFORE our locked round must not unlock us
            assert fx.cs.rs.locked_block is not None
            assert fx.cs.rs.locked_block.hash() == block_b1.hash()
            our = fx.cs.rs.votes.prevotes(2).get_by_index(0)
            if our is not None:
                assert our.block_id.hash == bid_b1.hash
        finally:
            await fx.stop()

    run_async(main())


def test_propose_valid_block_in_later_round(tmp_path):
    """After unlock, valid_block survives; when we become proposer we
    re-propose it with pol_round = valid_round (state_test.go:1060)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            block_a, parts_a, bid_a = await _ensure_proposal(fx)

            # polka A -> lock A, valid_block = A (valid_round 0)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_a, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_round == 0
            assert fx.cs.rs.valid_round == 0

            # round 1 via nil precommits; nil polka unlocks but valid_block stays
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1, 2, 3])
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, NIL, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_block is None
            assert fx.cs.rs.valid_block is not None

            # advance rounds until WE propose; cs must re-propose A with POL 0
            r = 1
            while _cur_proposer_idx(fx) != 0 and r < 6:
                await fx.add_votes(SignedMsgType.PRECOMMIT, 1, r, NIL, [1, 2, 3])
                await fx.wait_step(RoundStepType.PROPOSE, height=1, round_=r + 1, timeout=10)
                await fx.drain(0.2)
                r += 1
                if _cur_proposer_idx(fx) == 0:
                    break
                await fx.add_votes(SignedMsgType.PREVOTE, 1, r, NIL, [1, 2, 3])
                await fx.drain(0.2)
            if _cur_proposer_idx(fx) == 0:
                await fx.drain(0.3)
                rs = fx.cs.rs
                assert rs.proposal is not None
                assert rs.proposal_block.hash() == block_a.hash()
                assert rs.proposal.pol_round == 0
        finally:
            await fx.stop()

    run_async(main())


def test_set_valid_block_on_delayed_prevote(tmp_path):
    """Prevote-wait times out (precommit nil, no lock); the late third
    prevote still sets valid_block (state_test.go:1150)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            block_a, parts_a, bid_a = await _ensure_proposal(fx)
            rnd = fx.cs.rs.round

            await fx.add_votes(SignedMsgType.PREVOTE, 1, rnd, bid_a, [1])
            await fx.add_votes(SignedMsgType.PREVOTE, 1, rnd, NIL, [2])
            await fx.drain(1.0)  # prevote-wait timeout -> precommit nil
            ourpc = fx.cs.rs.votes.precommits(rnd).get_by_index(0)
            assert ourpc is not None and ourpc.block_id.is_zero()
            assert fx.cs.rs.locked_block is None
            assert fx.cs.rs.valid_block is None

            # delayed prevote completes the polka -> valid_block, no lock
            await fx.add_votes(SignedMsgType.PREVOTE, 1, rnd, bid_a, [3])
            await fx.drain(0.3)
            assert fx.cs.rs.valid_block is not None
            assert fx.cs.rs.valid_block.hash() == block_a.hash()
            assert fx.cs.rs.valid_round == rnd
            assert fx.cs.rs.locked_block is None
        finally:
            await fx.stop()

    run_async(main())


def test_set_valid_block_on_delayed_proposal(tmp_path):
    """Polka for a block we haven't received; the late proposal+parts set
    valid_block on completion (state_test.go:1212)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.1)
            rnd = 0
            while _cur_proposer_idx(fx) == 0 and rnd < 5:
                await _advance_round_via_nil(fx, 1, rnd)
                await fx.drain(0.2)
                rnd += 1
            idx = _cur_proposer_idx(fx)
            block_b, parts_b = fx.make_block(1, idx)
            bid_b = BlockID(block_b.hash(), parts_b.header)
            # signed NOW (before the proposer stub's HRS advances past it)
            prop_b = fx.make_signed_proposal(block_b, parts_b, rnd, idx)

            # we prevote nil on propose timeout; others polka B
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=rnd, timeout=10)
            await fx.add_votes(SignedMsgType.PREVOTE, 1, rnd, bid_b, [1, 2, 3])
            await fx.drain(0.6)
            ourpc = fx.cs.rs.votes.precommits(rnd).get_by_index(0)
            assert ourpc is not None and ourpc.block_id.is_zero()

            # delayed proposal delivery -> valid_block = B
            await fx.inject_proposal(block_b, parts_b, rnd, idx, prop=prop_b)
            await fx.drain(0.3)
            assert fx.cs.rs.valid_block is not None
            assert fx.cs.rs.valid_block.hash() == block_b.hash()
            assert fx.cs.rs.valid_round == rnd
        finally:
            await fx.stop()

    run_async(main())


def test_commit_from_previous_round(tmp_path):
    """+2/3 precommits for round 0's block arriving in round 1 take us to
    COMMIT without the block; the late parts finalize it
    (state_test.go:1388,1422)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.2)
            r0_idx = _cur_proposer_idx(fx)
            got_own = fx.cs.rs.proposal_block is not None
            if got_own:
                block_a = fx.cs.rs.proposal_block
                parts_a = fx.cs.rs.proposal_block_parts
            else:
                block_a, parts_a = fx.make_block(1, r0_idx)
            bid_a = BlockID(block_a.hash(), parts_a.header)

            # skip to round 1 on future-round nil prevotes
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 1, NIL, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.round == 1

            # +2/3 precommits for A at round 0 arrive (signed in round 0)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid_a, [1, 2, 3], raw=True)
            await fx.drain(0.4)
            rs = fx.cs.rs
            if fx.block_store.height < 1:
                # block unknown (or a different round-1 proposal was loaded):
                # step COMMIT, waiting on A's parts
                assert rs.step == RoundStepType.COMMIT
                assert rs.commit_round == 0
                assert rs.proposal_block is None or rs.proposal_block.hash() != block_a.hash()
                for i in range(parts_a.total):
                    await fx.cs.add_peer_message(
                        BlockPartMessage(1, 0, parts_a.get_part(i)), "peer"
                    )
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            assert fx.block_store.load_block(1).hash() == block_a.hash()
        finally:
            await fx.stop()

    run_async(main())


def test_slashing_conflicting_precommits_produce_evidence(tmp_path):
    """Equivocating PRECOMMITS produce DuplicateVoteEvidence
    (state_test.go:1633 SlashingPrecommits)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            await fx.drain(0.2)
            psh = PartSetHeader(total=1, hash=b"\x44" * 32)
            bid1 = BlockID(b"\x55" * 32, psh)
            bid2 = BlockID(b"\x66" * 32, psh)
            v1 = fx.stubs[3].sign_vote(SignedMsgType.PRECOMMIT, 1, 0, bid1, raw=True)
            v2 = fx.stubs[3].sign_vote(SignedMsgType.PRECOMMIT, 1, 0, bid2, raw=True)
            await fx.cs.add_peer_message(VoteMessage(v1), "stub-3")
            await fx.cs.add_peer_message(VoteMessage(v2), "stub-3")
            await fx.drain(0.3)
            pend = fx.evpool.pending_evidence(-1)
            assert len(pend) == 1
            ev = pend[0]
            assert ev.vote_a.validator_address == fx.stubs[3].address
            assert ev.vote_a.type == SignedMsgType.PRECOMMIT
        finally:
            await fx.stop()

    run_async(main())


def test_halt_on_late_precommit_from_previous_round(tmp_path):
    """Locked on A; precommit-wait timed out into round 1; the last round-0
    precommit for A arrives late and commits A (state_test.go:1678 Halt1)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            block_a, parts_a, bid_a = await _ensure_proposal(fx)

            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, bid_a, [1, 2, 3])
            await fx.drain(0.4)
            assert fx.cs.rs.locked_round == 0

            # precommits: one nil, one for A; ours is for A -> no decision
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1])
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid_a, [2])
            # precommit-wait timeout moves us to round 1, still locked
            await fx.wait_step(RoundStepType.PREVOTE, height=1, round_=1, timeout=10)
            await fx.drain(0.2)
            our = fx.cs.rs.votes.prevotes(1).get_by_index(0)
            assert our is not None and our.block_id.hash == bid_a.hash

            # the missing round-0 precommit arrives -> straight to commit
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, bid_a, [3])
            for _ in range(100):
                if fx.block_store.height >= 1:
                    break
                await asyncio.sleep(0.05)
            assert fx.block_store.height >= 1
            assert fx.block_store.load_block(1).hash() == block_a.hash()
            assert fx.cs.rs.height == 2
        finally:
            await fx.stop()

    run_async(main())


def test_triggered_timeout_precommit_resets_each_round(tmp_path):
    """triggered_timeout_precommit clears on every new round
    (state_test.go:1475,1536)."""

    async def main():
        fx = Fixture(4, tmp_path)
        await fx.start()
        try:
            await fx.wait_step(RoundStepType.PROPOSE, height=1, timeout=10)
            # 2/3-any precommits (split) trigger the precommit timeout
            await fx.add_votes(SignedMsgType.PREVOTE, 1, 0, NIL, [1, 2, 3])
            await fx.drain(0.3)
            psh = PartSetHeader(total=1, hash=b"\x77" * 32)
            await fx.add_votes(SignedMsgType.PRECOMMIT, 1, 0, NIL, [1])
            await fx.add_votes(
                SignedMsgType.PRECOMMIT, 1, 0, BlockID(b"\x79" * 32, psh), [2]
            )
            await fx.drain(0.02)
            # 2/3-any (ours + 2 split) armed the precommit timeout
            assert fx.cs.rs.round == 0 and fx.cs.rs.triggered_timeout_precommit
            await fx.add_votes(
                SignedMsgType.PRECOMMIT, 1, 0, BlockID(b"\x78" * 32, psh), [3]
            )
            await fx.wait_step(RoundStepType.PROPOSE, height=1, round_=1, timeout=10)
            await fx.drain(0.1)
            assert not fx.cs.rs.triggered_timeout_precommit
        finally:
            await fx.stop()

    run_async(main())
