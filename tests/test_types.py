"""Types layer: canonical sign-bytes, votes, blocks, part sets, evidence."""

import pytest
from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from tendermint_tpu.crypto import gen_ed25519, tmhash
from tendermint_tpu.types import canonical
from tendermint_tpu.types.basic import BlockID, BlockIDFlag, PartSetHeader, SignedMsgType
from tendermint_tpu.types.block import Block, Commit, CommitSig, ConsensusVersion, Header
from tendermint_tpu.types.evidence import DuplicateVoteEvidence, decode_evidence
from tendermint_tpu.types.part_set import PartSet, BLOCK_PART_SIZE_BYTES
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

BID = BlockID(hash=b"\xaa" * 32, part_set_header=PartSetHeader(total=3, hash=b"\xbb" * 32))


def _canonical_vote_pb_cls():
    """Dynamic protobuf class for the real CanonicalVote schema."""
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "cv.proto"
    fdp.package = "cvpkg"
    fdp.syntax = "proto3"

    psh = fdp.message_type.add()
    psh.name = "CanonicalPartSetHeader"
    f = psh.field.add()
    f.name, f.number, f.type = "total", 1, descriptor_pb2.FieldDescriptorProto.TYPE_UINT32
    f = psh.field.add()
    f.name, f.number, f.type = "hash", 2, descriptor_pb2.FieldDescriptorProto.TYPE_BYTES

    bid = fdp.message_type.add()
    bid.name = "CanonicalBlockID"
    f = bid.field.add()
    f.name, f.number, f.type = "hash", 1, descriptor_pb2.FieldDescriptorProto.TYPE_BYTES
    f = bid.field.add()
    f.name, f.number, f.type = (
        "part_set_header",
        2,
        descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE,
    )
    f.type_name = ".cvpkg.CanonicalPartSetHeader"

    ts = fdp.message_type.add()
    ts.name = "Ts"
    f = ts.field.add()
    f.name, f.number, f.type = "seconds", 1, descriptor_pb2.FieldDescriptorProto.TYPE_INT64
    f = ts.field.add()
    f.name, f.number, f.type = "nanos", 2, descriptor_pb2.FieldDescriptorProto.TYPE_INT32

    cv = fdp.message_type.add()
    cv.name = "CanonicalVote"
    specs = [
        ("type", 1, descriptor_pb2.FieldDescriptorProto.TYPE_INT64, None),
        ("height", 2, descriptor_pb2.FieldDescriptorProto.TYPE_SFIXED64, None),
        ("round", 3, descriptor_pb2.FieldDescriptorProto.TYPE_SFIXED64, None),
        ("block_id", 4, descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE, ".cvpkg.CanonicalBlockID"),
        ("timestamp", 5, descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE, ".cvpkg.Ts"),
        ("chain_id", 6, descriptor_pb2.FieldDescriptorProto.TYPE_STRING, None),
    ]
    for name, num, typ, tn in specs:
        f = cv.field.add()
        f.name, f.number, f.type = name, num, typ
        if tn:
            f.type_name = tn

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    return message_factory.GetMessageClass(pool.FindMessageTypeByName("cvpkg.CanonicalVote"))


def test_canonical_vote_bytes_match_protobuf():
    CV = _canonical_vote_pb_cls()
    msg = CV()
    msg.type = int(SignedMsgType.PRECOMMIT)
    msg.height = 100
    msg.round = 3
    msg.block_id.hash = BID.hash
    msg.block_id.part_set_header.total = 3
    msg.block_id.part_set_header.hash = BID.part_set_header.hash
    msg.timestamp.seconds = 1700000000
    msg.timestamp.nanos = 42
    msg.chain_id = "test-chain"
    expected = msg.SerializeToString(deterministic=True)

    got = canonical.canonical_vote_bytes(
        SignedMsgType.PRECOMMIT, 100, 3, BID, 1700000000 * 10**9 + 42, "test-chain"
    )
    assert got == expected


def test_canonical_vote_nil_block_omits_blockid():
    CV = _canonical_vote_pb_cls()
    msg = CV()
    msg.type = int(SignedMsgType.PREVOTE)
    msg.height = 5
    msg.timestamp.seconds = 10
    msg.chain_id = "c"
    expected = msg.SerializeToString(deterministic=True)
    got = canonical.canonical_vote_bytes(
        SignedMsgType.PREVOTE, 5, 0, BlockID(), 10 * 10**9, "c"
    )
    assert got == expected


def test_vote_sign_bytes_are_length_prefixed():
    sb = canonical.vote_sign_bytes("c", SignedMsgType.PREVOTE, 1, 0, BID, 0)
    body = canonical.canonical_vote_bytes(SignedMsgType.PREVOTE, 1, 0, BID, 0, "c")
    assert sb.endswith(body) and len(sb) > len(body)


def _make_vote(priv, chain_id="test-chain", height=7, round_=0, block_id=BID, ts=123456789):
    pub = priv.pub_key()
    v = Vote(
        type=SignedMsgType.PRECOMMIT,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp_ns=ts,
        validator_address=pub.address(),
        validator_index=0,
    )
    return v.with_signature(priv.sign(v.sign_bytes(chain_id)))


def test_vote_sign_verify_roundtrip():
    priv = gen_ed25519(b"\x11" * 32)
    v = _make_vote(priv)
    assert v.verify("test-chain", priv.pub_key())
    assert not v.verify("other-chain", priv.pub_key())
    other = gen_ed25519(b"\x22" * 32)
    assert not v.verify("test-chain", other.pub_key())
    v.validate_basic()


def test_vote_encode_decode():
    priv = gen_ed25519(b"\x11" * 32)
    v = _make_vote(priv)
    assert Vote.decode(v.encode()) == v


def test_proposal_roundtrip_and_signbytes():
    p = Proposal(height=10, round=1, pol_round=-1, block_id=BID, timestamp_ns=55)
    priv = gen_ed25519(b"\x33" * 32)
    signed = p.with_signature(priv.sign(p.sign_bytes("chain")))
    signed.validate_basic()
    assert Proposal.decode(signed.encode()) == signed
    assert priv.pub_key().verify(p.sign_bytes("chain"), signed.signature)


def test_commit_hash_and_roundtrip():
    priv = gen_ed25519(b"\x44" * 32)
    cs = CommitSig(BlockIDFlag.COMMIT, priv.pub_key().address(), 99, b"\x01" * 64)
    commit = Commit(height=5, round=0, block_id=BID, signatures=(cs, CommitSig.absent_sig()))
    commit.validate_basic()
    assert len(commit.hash()) == 32
    assert Commit.decode(commit.encode()) == commit
    # vote reconstruction
    vote = commit.get_vote(0)
    assert vote.height == 5 and vote.block_id == BID
    # nil/absent sigs resolve to zero block id
    assert commit.get_vote(1).block_id.is_zero()


def test_header_hash_deterministic_and_sensitive():
    h = Header(
        version=ConsensusVersion(),
        chain_id="test",
        height=3,
        time_ns=1000,
        last_block_id=BID,
        last_commit_hash=b"\x01" * 32,
        data_hash=b"\x02" * 32,
        validators_hash=b"\x03" * 32,
        next_validators_hash=b"\x04" * 32,
        consensus_hash=b"\x05" * 32,
        app_hash=b"\x06" * 32,
        last_results_hash=b"\x07" * 32,
        evidence_hash=b"\x08" * 32,
        proposer_address=b"\x09" * 20,
    )
    h.validate_basic()
    h1 = h.hash()
    assert len(h1) == 32
    import dataclasses

    h2 = dataclasses.replace(h, height=4).hash()
    assert h1 != h2
    assert Header.decode(h.encode()) == h


def test_part_set_roundtrip():
    data = bytes(range(256)) * 1024  # 256 KiB -> 4 parts
    ps = PartSet.from_data(data)
    assert ps.total == 4 and ps.is_complete()
    header = ps.header
    # Reassemble from gossiped parts
    ps2 = PartSet(header)
    assert not ps2.is_complete()
    for i in range(ps.total):
        added = ps2.add_part(ps.get_part(i))
        assert added
    assert ps2.is_complete()
    assert ps2.assemble() == data


def test_part_set_rejects_bad_proof():
    data = b"x" * (BLOCK_PART_SIZE_BYTES + 10)
    ps = PartSet.from_data(data)
    ps2 = PartSet(ps.header)
    part = ps.get_part(0)
    from tendermint_tpu.types.part_set import Part

    bad = Part(part.index, b"tampered" + part.bytes_[8:], part.proof)
    with pytest.raises(ValueError, match="invalid proof"):
        ps2.add_part(bad)


def test_duplicate_vote_evidence():
    priv = gen_ed25519(b"\x55" * 32)
    v1 = _make_vote(priv, block_id=BID)
    bid2 = BlockID(hash=b"\xcc" * 32, part_set_header=PartSetHeader(total=1, hash=b"\xdd" * 32))
    v2 = _make_vote(priv, block_id=bid2)
    ev = DuplicateVoteEvidence.from_votes(v1, v2, block_time_ns=1, total_power=10, val_power=1)
    ev.validate_basic()
    ev.verify("test-chain", priv.pub_key())
    assert decode_evidence(ev.encode()) == ev
    # same-block "evidence" is invalid
    with pytest.raises(ValueError):
        ev_same = DuplicateVoteEvidence.from_votes(v1, v1, 1, 10, 1)
        ev_same.verify("test-chain", priv.pub_key())
    # wrong pubkey
    with pytest.raises(ValueError):
        ev.verify("test-chain", gen_ed25519(b"\x66" * 32).pub_key())


def test_block_validate_basic():
    txs = (b"tx1", b"tx2")
    priv = gen_ed25519(b"\x77" * 32)
    cs = CommitSig(BlockIDFlag.COMMIT, priv.pub_key().address(), 5, b"\x01" * 64)
    last_commit = Commit(height=2, round=0, block_id=BID, signatures=(cs,))
    from tendermint_tpu.types.block import txs_hash
    from tendermint_tpu.crypto.merkle import hash_from_byte_slices

    header = Header(
        version=ConsensusVersion(),
        chain_id="test",
        height=3,
        time_ns=1000,
        last_block_id=BID,
        last_commit_hash=last_commit.hash(),
        data_hash=txs_hash(txs),
        validators_hash=b"\x03" * 32,
        next_validators_hash=b"\x04" * 32,
        consensus_hash=b"\x05" * 32,
        app_hash=b"\x06" * 32,
        last_results_hash=b"\x07" * 32,
        evidence_hash=hash_from_byte_slices([]),
        proposer_address=b"\x09" * 20,
    )
    block = Block(header, txs, (), last_commit)
    block.validate_basic()
    assert Block.decode(block.encode()) == block
    # tampered data hash fails
    import dataclasses

    bad = Block(dataclasses.replace(header, data_hash=b"\x00" * 32), txs, (), last_commit)
    with pytest.raises(ValueError, match="DataHash"):
        bad.validate_basic()
