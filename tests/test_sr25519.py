"""sr25519 / ristretto255 / merlin tests.

Spec conformance: ristretto255 small-multiples test vectors (public
ristretto255 spec appendix) and the merlin transcript test vector (merlin
crate's transcript test) pin the from-scratch implementations to the public
specifications; the rest is behavioral."""

import numpy as np

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.crypto.ed25519_ref import BASE, IDENTITY, point_add, point_mul
from tendermint_tpu.crypto.merlin import Transcript
from tendermint_tpu.crypto.sr25519 import (
    Sr25519PubKey,
    gen_sr25519,
    ristretto_decode,
    ristretto_encode,
)

# ristretto255 spec: encodings of B*0 .. B*4
SMALL_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
]


def test_ristretto_small_multiples_match_spec():
    pt = IDENTITY
    for i, want in enumerate(SMALL_MULTIPLES):
        assert ristretto_encode(pt).hex() == want, f"B*{i}"
        pt = point_add(pt, BASE)


def test_ristretto_decode_encode_roundtrip():
    for i in range(1, 16):
        pt = point_mul(i, BASE)
        enc = ristretto_encode(pt)
        dec = ristretto_decode(enc)
        assert dec is not None
        assert ristretto_encode(dec) == enc


def test_ristretto_rejects_invalid():
    # non-canonical (>= p)
    from tendermint_tpu.crypto.ed25519_ref import P

    assert ristretto_decode(int.to_bytes(P + 1, 32, "little")) is None
    # negative encoding (odd)
    assert ristretto_decode(int.to_bytes(1, 32, "little")) is None


def test_merlin_transcript_vector():
    """merlin crate test_transcript_it_works equivalence."""
    t = Transcript(b"test protocol")
    t.append_message(b"some label", b"some data")
    cb = t.challenge_bytes(b"challenge", 32)
    assert cb.hex() == "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"


def test_sr25519_sign_verify_roundtrip():
    priv = gen_sr25519(b"\x01" * 32)
    pub = priv.pub_key()
    msg = b"vote sign bytes"
    sig = priv.sign(msg)
    assert len(sig) == 64
    assert sig[63] & 0x80  # schnorrkel marker bit
    assert pub.verify(msg, sig)
    # tamper: message, signature, wrong key
    assert not pub.verify(b"other message", sig)
    bad = bytearray(sig)
    bad[1] ^= 1
    assert not pub.verify(msg, bytes(bad))
    assert not gen_sr25519(b"\x02" * 32).pub_key().verify(msg, sig)


def test_sr25519_rejects_missing_marker_and_high_s():
    priv = gen_sr25519(b"\x03" * 32)
    msg = b"m"
    sig = bytearray(priv.sign(msg))
    sig[63] &= 0x7F  # clear marker
    assert not priv.pub_key().verify(msg, bytes(sig))


def test_mixed_batch_routes_by_key_type():
    from tendermint_tpu.crypto.keys import gen_ed25519

    ed = gen_ed25519(b"\x04" * 32)
    sr = gen_sr25519(b"\x05" * 32)
    msgs = [b"m0", b"m1", b"m2", b"m3"]
    pubkeys = [ed.pub_key().bytes(), sr.pub_key().bytes(), ed.pub_key().bytes(), sr.pub_key().bytes()]
    sigs = [ed.sign(msgs[0]), sr.sign(msgs[1]), ed.sign(b"WRONG"), sr.sign(b"WRONG")]
    types = ["ed25519", "sr25519", "ed25519", "sr25519"]
    mask = cbatch.verify_batch(pubkeys, msgs, sigs, backend="cpu", key_types=types)
    assert mask.tolist() == [True, True, False, False]


def test_mixed_validator_set_commit():
    """A commit from a mixed ed25519+sr25519 validator set verifies
    (BASELINE config 5 shape, small)."""
    import time

    from tendermint_tpu.crypto.keys import gen_ed25519
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.types.vote import Vote
    from tendermint_tpu.types.vote_set import VoteSet

    privs = [gen_ed25519(bytes([i]) * 32) if i % 2 == 0 else gen_sr25519(bytes([i]) * 32) for i in range(1, 7)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    bid = BlockID(b"\x10" * 32, PartSetHeader(total=1, hash=b"\x11" * 32))
    vs = VoteSet("mixed-chain", 5, 0, SignedMsgType.PRECOMMIT, vals)
    import dataclasses

    for p in privs:
        addr = p.pub_key().address()
        idx, _ = vals.get_by_address(addr)
        v = Vote(
            type=SignedMsgType.PRECOMMIT, height=5, round=0, block_id=bid,
            timestamp_ns=time.time_ns(), validator_address=addr, validator_index=idx,
        )
        sig = p.sign(v.sign_bytes("mixed-chain"))
        assert vs.add_vote(dataclasses.replace(v, signature=sig))
    commit = vs.make_commit()
    vals.verify_commit("mixed-chain", bid, 5, commit)  # must not raise
    vals.verify_commit_light("mixed-chain", bid, 5, commit)
    from tendermint_tpu.types.validator_set import Fraction

    vals.verify_commit_light_trusting("mixed-chain", commit, Fraction(1, 3))
