"""Module-filtered structured logging (reference: libs/log + filter.go).

setup(level_spec) configures the framework's loggers from a spec like the
reference's --log_level: "info", "consensus:debug,p2p:none,*:error" —
per-module levels with '*' as the default. Modules map to the
"tendermint_tpu.<module>" logger namespace.
"""

from __future__ import annotations

import logging
from typing import Dict

ROOT = "tendermint_tpu"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
    "none": logging.CRITICAL + 10,
}


def parse_level_spec(spec: str) -> Dict[str, int]:
    """'consensus:debug,p2p:none,*:error' -> {module: level}. A bare level
    ('info') applies to '*' (reference: libs/log/filter.go ParseLogLevel)."""
    out: Dict[str, int] = {}
    spec = (spec or "info").strip()
    if ":" not in spec:
        out["*"] = _level(spec)
        return out
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        mod, _, lvl = item.partition(":")
        out[mod.strip() or "*"] = _level(lvl.strip())
    out.setdefault("*", logging.INFO)
    return out


def _level(name: str, strict: bool = True) -> int:
    try:
        return _LEVELS[name.lower()]
    except KeyError:
        if strict:
            raise ValueError(
                f"unknown log level {name!r} (expected one of {sorted(_LEVELS)})"
            ) from None
        logging.getLogger(ROOT).warning(
            "unknown log level %r; falling back to info", name
        )
        return logging.INFO


def setup(level_spec: str = "info", fmt: str = "%(asctime)s %(name)s %(levelname)s %(message)s") -> None:
    """Configure the tendermint_tpu logger tree from a level spec. A bad spec
    degrades to INFO with a warning — a typo in config.toml must not stop a
    node from booting."""
    try:
        levels = parse_level_spec(level_spec)
    except ValueError:
        logging.getLogger(ROOT).warning(
            "invalid log_level spec %r; using info", level_spec
        )
        levels = {"*": logging.INFO}
    root = logging.getLogger(ROOT)
    if not root.handlers and not logging.getLogger().handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(fmt))
        root.addHandler(handler)
    root.setLevel(levels.get("*", logging.INFO))
    for mod, lvl in levels.items():
        if mod == "*":
            continue
        logging.getLogger(f"{ROOT}.{mod}").setLevel(lvl)
