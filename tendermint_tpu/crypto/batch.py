"""Batch signature verification — the framework's north-star interface.

`verify_batch(pubkeys, msgs, sigs) -> bool mask` with two backends:

- "cpu": serial host loop over OpenSSL (the reference-shaped baseline — this is
  exactly what the reference does in Go, one VerifySignature per validator,
  reference: types/validator_set.go:680-702).
- "jax": the TPU path — host computes h = SHA512(R||A||M) mod L per item
  (cheap, C-speed hashlib), then one jitted kernel verifies the whole batch on
  device (tendermint_tpu.ops.ed25519_jax).

Every O(validators) verification site in the framework (VerifyCommit,
VerifyCommitLight/Trusting, vote storms, fast-sync replay, evidence) funnels
through this module.
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Sequence

import numpy as np

from tendermint_tpu.crypto.ed25519_ref import L

_BUCKET_SIZES = [2**i for i in range(17)]  # jit shape buckets: 1..65536


def _bucket(n: int) -> int:
    for b in _BUCKET_SIZES:
        if n <= b:
            return b
    return n


def backend_default() -> str:
    env = os.environ.get("TMTPU_CRYPTO_BACKEND")
    if env:
        return env
    try:
        import jax  # noqa: F401

        return "jax"
    except Exception:  # pragma: no cover
        return "cpu"


def verify_batch_cpu(
    pubkeys: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> np.ndarray:
    from tendermint_tpu.crypto.keys import Ed25519PubKey

    out = np.zeros(len(pubkeys), dtype=bool)
    for i, (pk, msg, sig) in enumerate(zip(pubkeys, msgs, sigs)):
        try:
            out[i] = Ed25519PubKey(bytes(pk)).verify(bytes(msg), bytes(sig))
        except ValueError:
            out[i] = False
    return out


def _signed_radix16(vals: np.ndarray) -> np.ndarray:
    """uint8[N, 32] little-endian scalars (< 2^253) -> int8[64, N] signed
    radix-16 digits in [-8, 8], LSB-first. Vectorized over the batch."""
    n = vals.shape[0]
    digits = np.empty((n, 64), dtype=np.int16)
    digits[:, 0::2] = vals & 0x0F
    digits[:, 1::2] = vals >> 4
    carry = np.zeros(n, dtype=np.int16)
    for i in range(64):
        d = digits[:, i] + carry
        carry = (d > 8).astype(np.int16)
        digits[:, i] = d - 16 * carry
    # scalars < 2^253 => top digit <= 1 before carry, <= 2 after: no overflow
    assert not carry.any()
    return np.ascontiguousarray(digits.T.astype(np.int8))


def prepare_batch(
    pubkeys: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
):
    """Host-side preprocessing for the device kernel.

    Returns (a_bytes[32,B], r_bytes[32,B], s_digits[64,B], h_digits[64,B],
    precheck[N] bool, n) with B = padded bucket size.
    """
    n = len(pubkeys)
    b = _bucket(max(n, 1))
    a = np.zeros((b, 32), dtype=np.uint8)
    r = np.zeros((b, 32), dtype=np.uint8)
    s = np.zeros((b, 32), dtype=np.uint8)
    h = np.zeros((b, 32), dtype=np.uint8)
    precheck = np.zeros(n, dtype=bool)
    for i in range(n):
        pk, msg, sig = bytes(pubkeys[i]), bytes(msgs[i]), bytes(sigs[i])
        if len(pk) != 32 or len(sig) != 64:
            continue
        s_int = int.from_bytes(sig[32:], "little")
        if s_int >= L:
            continue  # non-canonical s: reject without device work
        precheck[i] = True
        a[i] = np.frombuffer(pk, dtype=np.uint8)
        r[i] = np.frombuffer(sig[:32], dtype=np.uint8)
        s[i] = np.frombuffer(sig[32:], dtype=np.uint8)
        h_int = (
            int.from_bytes(hashlib.sha512(sig[:32] + pk + msg).digest(), "little") % L
        )
        h[i] = np.frombuffer(h_int.to_bytes(32, "little"), dtype=np.uint8)
    return (
        np.ascontiguousarray(a.T),
        np.ascontiguousarray(r.T),
        _signed_radix16(s),
        _signed_radix16(h),
        precheck,
        n,
    )


def verify_batch_jax(
    pubkeys: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> np.ndarray:
    from tendermint_tpu.ops.ed25519_jax import verify_prepared

    a, r, s_bits, h_bits, precheck, n = prepare_batch(pubkeys, msgs, sigs)
    mask = np.asarray(verify_prepared(a, r, s_bits, h_bits))[:n]
    return mask & precheck


def verify_batch(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    backend: str | None = None,
    key_types: Sequence[str] | None = None,
) -> np.ndarray:
    """Verify N (pubkey, msg, sig) triples; returns bool[N].

    key_types: per-row key type ("ed25519"/"sr25519"); None means all
    ed25519. Mixed sets (BASELINE config 5) route ed25519 rows through the
    selected backend (TPU batch on "jax") and sr25519 rows through the host
    schnorrkel path."""
    if not (len(pubkeys) == len(msgs) == len(sigs)):
        raise ValueError("pubkeys/msgs/sigs length mismatch")
    if len(pubkeys) == 0:
        return np.zeros(0, dtype=bool)
    if key_types is not None and any(t != "ed25519" for t in key_types):
        from tendermint_tpu.crypto.sr25519 import sr25519_verify

        out = np.zeros(len(pubkeys), dtype=bool)
        ed_idx = [i for i, t in enumerate(key_types) if t == "ed25519"]
        sr_idx = [i for i, t in enumerate(key_types) if t == "sr25519"]
        if ed_idx:
            sub = verify_batch(
                [pubkeys[i] for i in ed_idx],
                [msgs[i] for i in ed_idx],
                [sigs[i] for i in ed_idx],
                backend,
            )
            out[ed_idx] = sub
        for i in sr_idx:
            out[i] = sr25519_verify(bytes(pubkeys[i]), bytes(msgs[i]), bytes(sigs[i]))
        return out
    be = backend or backend_default()
    if be == "cpu":
        return verify_batch_cpu(pubkeys, msgs, sigs)
    if be == "jax":
        return verify_batch_jax(pubkeys, msgs, sigs)
    raise ValueError(f"unknown crypto backend {be!r}")


class Ed25519BatchVerifier:
    """Accumulate-and-flush batch verifier (the interface the consensus vote
    path and commit verification use)."""

    def __init__(self, backend: str | None = None) -> None:
        self._backend = backend
        self._pubkeys: List[bytes] = []
        self._msgs: List[bytes] = []
        self._sigs: List[bytes] = []

    def add(self, pubkey: bytes, msg: bytes, sig: bytes) -> None:
        self._pubkeys.append(bytes(pubkey))
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(sig))

    def __len__(self) -> int:
        return len(self._pubkeys)

    def verify(self) -> np.ndarray:
        """Verify all accumulated triples; the batch stays (call reset())."""
        return verify_batch(self._pubkeys, self._msgs, self._sigs, self._backend)

    def reset(self) -> None:
        self._pubkeys.clear()
        self._msgs.clear()
        self._sigs.clear()
