"""Test configuration.

Must run before jax initializes: force the CPU platform with 8 virtual devices
so multi-chip sharding paths (jax.sharding.Mesh over 8 devices) are exercised
without TPU hardware. Real-TPU benchmarking goes through bench.py, which does
not import this file.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
