"""Crash-resumable catch-up checkpoint (ISSUE 12).

The pipelined blocksync reactor verifies a window of fetched blocks as one
cross-height super-batch BEFORE applying them. A node killed between verify
and apply used to re-fetch and re-verify that whole window on restart; the
checkpoint persists the verified-but-unapplied blocks so the restarted
pipeline re-enters at its last applied height and applies the survivors
without re-verifying (the signatures were already checked — the file's
hash-chain linkage proof below makes a tampered checkpoint fail closed).

Format (JSON, atomic tmp+rename writes so a crash never leaves a torn file):

    {"v": 1,
     "applied_height": H,            # state.last_block_height at write time
     "blocks": ["<hex>", ...]}       # encoded blocks H+1..H+k, verified,
                                     # plus the trailing (k+1)-th block whose
                                     # last_commit covers block H+k

On load the blocks are decoded and the chain linkage re-proved: block i+1's
header.last_block_id.hash must equal block i's hash, and the first block
must sit at exactly applied_height+1. Any mismatch (stale file, disk
corruption, an attacker editing the file) discards the checkpoint — the
node then just re-fetches, which is always safe.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import List, Optional

logger = logging.getLogger("tendermint_tpu.blocksync")

# cap the persisted window: checkpoints are rewritten per applied run, and an
# unbounded window would turn every write into a multi-MB fsync
MAX_CHECKPOINT_BLOCKS = 64


class CatchupCheckpoint:
    def __init__(self, path: Optional[str]):
        """path=None disables persistence (memdb test nodes): save/load are
        no-ops and the pipeline behaves exactly as without a checkpoint."""
        self.path = path

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    def save(self, applied_height: int, blocks: List[object]) -> None:
        """blocks: verified-but-unapplied blocks, contiguous from
        applied_height+1 (the last entry is the trailing commit carrier).
        Entries may be Block objects or their already-encoded bytes."""
        if not self.path:
            return
        payload = {
            "v": 1,
            "applied_height": int(applied_height),
            "blocks": [
                (b if isinstance(b, (bytes, bytearray)) else b.encode()).hex()
                for b in blocks[:MAX_CHECKPOINT_BLOCKS]
            ],
        }
        d = os.path.dirname(self.path) or "."
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".catchup-")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            logger.exception("catch-up checkpoint write failed (continuing)")

    def load(self, expect_applied_height: int) -> List[object]:
        """Verified blocks for expect_applied_height+1.., or [] when the
        checkpoint is absent, stale, or fails the linkage proof.

        A file written at applied height H0 stays usable after a crash that
        landed anywhere inside its window (state at H >= H0): the
        already-applied prefix is skipped and the remainder re-proved."""
        if not self.path:
            return []
        try:
            with open(self.path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return []
        try:
            if payload.get("v") != 1:
                return []
            base = int(payload["applied_height"])
            skip = int(expect_applied_height) - base
            if skip < 0 or skip >= len(payload["blocks"]):
                logger.info(
                    "catch-up checkpoint (applied %s, %d blocks) does not "
                    "cover state height %d; discarding", payload.get(
                        "applied_height"), len(payload["blocks"]),
                    expect_applied_height,
                )
                return []
            from tendermint_tpu.types.block import Block

            blocks = [
                Block.decode(bytes.fromhex(h)) for h in payload["blocks"][skip:]
            ]
        except Exception:
            logger.warning("catch-up checkpoint unreadable; discarding", exc_info=True)
            return []
        # linkage proof: contiguous heights anchored at applied_height+1,
        # each block committing to its predecessor's hash
        for i, b in enumerate(blocks):
            if b.header.height != expect_applied_height + 1 + i:
                logger.warning("catch-up checkpoint heights not contiguous; discarding")
                return []
            if i > 0 and b.header.last_block_id.hash != blocks[i - 1].hash():
                logger.warning("catch-up checkpoint linkage broken; discarding")
                return []
        return blocks

    def clear(self) -> None:
        if not self.path:
            return
        try:
            os.unlink(self.path)
        except OSError:
            pass
