"""Multi-connection ABCI proxy (reference: proxy/multi_app_conn.go:21,
proxy/app_conn.go:13-56).

One ClientCreator yields four independent clients — Consensus, Mempool, Query,
Snapshot — so block execution, CheckTx, RPC queries, and state-sync snapshots
proceed concurrently without blocking one another. For the local client they
share one app lock (same as the reference's local mode)."""

from __future__ import annotations

import threading
from typing import Callable

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import ABCIClient, LocalClient, ReconnectingClient

ClientCreator = Callable[[], ABCIClient]


def local_client_creator(app: abci.Application) -> ClientCreator:
    lock = threading.RLock()

    def create() -> ABCIClient:
        return LocalClient(app, lock)

    return create


def socket_client_creator(addr: str, call_timeout: float = 30.0) -> ClientCreator:
    def create() -> ABCIClient:
        from tendermint_tpu.abci.socket import SocketClient

        return SocketClient(addr, call_timeout=call_timeout)

    return create


def grpc_client_creator(addr: str) -> ClientCreator:
    def create() -> ABCIClient:
        from tendermint_tpu.abci.grpc import GrpcClient

        return GrpcClient(addr)

    return create


def default_client_creator(
    proxy_app: str, transport: str, app=None, call_timeout: float = 30.0
) -> ClientCreator:
    """The reference's DefaultClientCreator (proxy/client.go): an address in
    proxy_app selects a remote transport ("socket" default, "grpc"); empty
    means run the in-process app."""
    if proxy_app:
        if transport == "grpc":
            return grpc_client_creator(proxy_app)
        return socket_client_creator(proxy_app, call_timeout=call_timeout)
    if app is None:
        raise ValueError("no proxy_app address and no in-process app")
    return local_client_creator(app)


class AppConns:
    """Four logical connections. With resilient=True (remote apps), the
    mempool/query/snapshot connections survive an app restart via
    ReconnectingClient; the CONSENSUS connection is never wrapped — its
    failure must stay fatal-loud (a node that silently retries block
    execution against a restarted app risks nondeterministic state)."""

    def __init__(
        self,
        creator: ClientCreator,
        resilient: bool = False,
        attempts: int = 5,
        base_delay: float = 0.2,
        max_delay: float = 5.0,
    ):
        self._creator = creator
        self.consensus: ABCIClient = creator()
        if resilient:
            kw = dict(attempts=attempts, base_delay=base_delay, max_delay=max_delay)
            self.mempool: ABCIClient = ReconnectingClient(creator, name="mempool", **kw)
            self.query: ABCIClient = ReconnectingClient(creator, name="query", **kw)
            self.snapshot: ABCIClient = ReconnectingClient(creator, name="snapshot", **kw)
        else:
            self.mempool = creator()
            self.query = creator()
            self.snapshot = creator()

    def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            c.close()
