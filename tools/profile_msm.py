"""Stage-wise device profiling of the RLC/Pippenger MSM kernel on real TPU.

Times each pipeline stage of ops/msm_jax.py separately (decompress, lane
gather + pair-tree up-sweep, Fenwick node gather + prefix reduce, weighted
bucket sum, Horner window combine) plus the full cached kernel, with
device-resident inputs and multi-iteration async-dispatch timing (one sync
at the end) so the tunnel RTT is amortized out. Also dumps XLA's
cost_analysis for the full kernel to anchor a roofline estimate (PERF.md).

Stage compiles land in the shared .jax_cache, so the cost is once-per-machine.

Usage: python tools/profile_msm.py [NA] [ITERS]  (defaults 10240, 8)
"""

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO, ".jax_cache"))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from tendermint_tpu.ops import fe25519 as fe
from tendermint_tpu.ops import msm_jax as M
from tendermint_tpu.ops.ed25519_jax import Point, decompress, make_ctx


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _sync(out):
    """Force a REAL device sync: block_until_ready is a no-op through the
    axon tunnel (measured r4); only a D2H fetch drains the queue."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf)[(0,) * leaf.ndim]


def timeit(name, fn, *args, iters=8):
    """Compile+warm once, then slope-time: (t(iters) - t(1)) / (iters - 1)
    with a forced D2H sync per measurement — subtracts the (large, variable)
    tunnel sync constant. CAVEAT: the tunnel memoizes identical executions
    in some paths (observed r4); treat identical-input slopes as lower
    bounds and prefer distinct-data pipelines (bench.py) for decisions."""
    t0 = time.perf_counter()
    out = fn(*args)
    _sync(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = fn(*args)
    _sync(out)
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    t_many = time.perf_counter() - t0
    per = max((t_many - t_one) / (iters - 1), 0.0)
    log(f"  {name:28s} {per*1e3:9.2f} ms/iter   (first call {compile_s:.1f}s)")
    return per, compile_s


def main():
    na = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    nr = na
    n = na + nr
    log(f"devices: {jax.devices()}  backend: {jax.default_backend()}")
    log(f"shape: NA={na} NR={nr} lanes={n} windows={M.NWIN}")

    rng = np.random.default_rng(0)
    # Scalars with realistic digit distributions (A lanes ~253-bit, R lanes
    # ~127-bit like real RLC coefficients) — the sort/Fenwick layout depends
    # on digit spread, the device work does not depend on values.
    scalars = [int.from_bytes(rng.bytes(32), "little") >> 3 for _ in range(na)] + [
        int.from_bytes(rng.bytes(16), "little") for _ in range(nr)
    ]
    digits = M.scalars_to_bytes(scalars, n)
    t0 = time.perf_counter()
    perm, ends = M.sort_windows(digits)
    log(f"host sort_windows: {(time.perf_counter()-t0)*1e3:.1f} ms")

    bx, by, bz, bt = M.basepoint_coords()
    a_coords = tuple(
        np.ascontiguousarray(np.broadcast_to(c[:, None], (fe.NLIMBS, na)))
        for c in (bx, by, bz, bt)
    )
    from tendermint_tpu.crypto.ed25519_ref import BASE, point_compress

    b_enc = np.frombuffer(point_compress(BASE), dtype=np.uint8)
    r_bytes_t = np.ascontiguousarray(np.tile(b_enc, (nr, 1)).T)

    dev = jax.devices()[0]
    put = lambda x: jax.device_put(x, dev)
    d_a = tuple(put(c) for c in a_coords)
    d_rb = put(r_bytes_t)
    d_perm = put(perm)
    d_ends = put(ends)
    d_nodes = put(np.asarray(M.fenwick_nodes_device(ends, n)))
    fctx = make_ctx((nr,))
    C = M.make_small_ctx()

    results = {}

    # --- full cached kernel (the production 10k path) ---------------------
    full = lambda *a: M._rlc_cached_jit(*a)
    per, comp = timeit(
        "full cached kernel", full, *d_a, d_rb, d_perm, d_ends, fctx, C, iters=iters
    )
    results["full_cached_ms"] = per * 1e3
    results["full_cached_compile_s"] = comp

    compiled = M._rlc_cached_jit.lower(*d_a, d_rb, d_perm, d_ends, fctx, C).compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        results["cost_analysis"] = {
            k: v for k, v in ca.items()
            if k in ("flops", "bytes accessed", "transcendentals", "utilization")
            or "bytes accessed" in k
        }
        log(f"  cost_analysis: flops={ca.get('flops'):.3e} "
            f"bytes={ca.get('bytes accessed'):.3e}")
    except Exception as e:  # pragma: no cover
        log(f"  cost_analysis unavailable: {e}")
    try:
        mem = compiled.memory_analysis()
        results["temp_bytes"] = getattr(mem, "temp_size_in_bytes", None)
        log(f"  temp memory: {results['temp_bytes']/1e6:.0f} MB")
    except Exception:
        pass

    # --- stages -----------------------------------------------------------
    s0 = jax.jit(lambda rb, fc: decompress(fc, rb))
    per, comp = timeit("S0 decompress R", s0, d_rb, fctx, iters=iters)
    results["s0_decompress_ms"] = per * 1e3

    d_r_pts = tuple(s0(d_rb, fctx)[0])
    cat = jax.jit(
        lambda ac, rc: tuple(jnp.concatenate([a, b], -1) for a, b in zip(ac, rc))
    )
    d_pts = tuple(cat(d_a, d_r_pts))

    s1 = jax.jit(
        lambda pts, p: tuple(M._tree_levels(C, M._gather_lanes(Point(*pts), p)))
    )
    per, comp = timeit("S1 gather+tree up-sweep", s1, d_pts, d_perm, iters=iters)
    results["s1_tree_ms"] = per * 1e3

    d_tree = tuple(s1(d_pts, d_perm))
    s2 = jax.jit(
        lambda tr, ni: tuple(M._reduce_last_axis(C, M._gather_nodes(Point(*tr), ni)))
    )
    per, comp = timeit("S2 fenwick gather+reduce", s2, d_tree, d_nodes, iters=iters)
    results["s2_fenwick_ms"] = per * 1e3

    d_prefix = tuple(s2(d_tree, d_nodes))
    s3 = jax.jit(lambda pr: tuple(M._weighted_bucket_sum(C, Point(*pr))))
    per, comp = timeit("S3 weighted bucket sum", s3, d_prefix, iters=iters)
    results["s3_bucket_ms"] = per * 1e3

    d_wp = tuple(s3(d_prefix))
    s4 = jax.jit(lambda wp: tuple(M._combine_windows(C, Point(*wp))))
    per, comp = timeit("S4 horner combine", s4, d_wp, iters=iters)
    results["s4_horner_ms"] = per * 1e3

    # --- micro: field-mul throughput ceiling ------------------------------
    # One batched field multiply at tree width — an upper bound on how fast
    # point ops can go; ratio vs measured add cost shows codegen efficiency.
    big = jnp.asarray(rng.integers(0, 1 << 13, (fe.NLIMBS, 32, n), dtype=np.int32))
    fmul = jax.jit(lambda a, b: fe.mul(a, b))
    per, comp = timeit("micro fe.mul (32,N) lanes", fmul, big, big, iters=iters)
    results["fe_mul_32xN_ms"] = per * 1e3
    # one unified point add at the same width
    p_big = Point(big, big, big, big)
    padd = jax.jit(lambda p, q: tuple(M._padd(C, Point(*p), Point(*q))))
    per, comp = timeit("micro point add (32,N)", padd, tuple(p_big), tuple(p_big), iters=iters)
    results["padd_32xN_ms"] = per * 1e3

    stages = (
        results["s0_decompress_ms"] + results["s1_tree_ms"]
        + results["s2_fenwick_ms"] + results["s3_bucket_ms"]
        + results["s4_horner_ms"]
    )
    log(f"  stage sum {stages:.1f} ms vs full {results['full_cached_ms']:.1f} ms")
    print(json.dumps(results))


if __name__ == "__main__":
    main()
