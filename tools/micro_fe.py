"""Field-arithmetic microbenchmarks on the real TPU.

Decides the round-4 kernel direction with measurements, not guesses:
- int32 13-bit-limb mul (current fe25519) vs an f32 8-bit-limb prototype —
  v5e's VPU runs f32 FMA at full rate while 32-bit integer multiply is
  emulated; if the f32 conv wins, the whole MSM pipeline scales with it.
- chained (data-dependent) ops so XLA cannot CSE the loop away — the r3
  microbench that "proved" int mul was free measured a CSE'd graph.
- scan vs unrolled sequential point-doubling chains (the Horner combine's
  64 ms is ~2 ms/iteration of lax.scan overhead on tiny tensors).

Usage: python tools/micro_fe.py
"""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO, ".jax_cache"))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from tendermint_tpu.ops import fe25519 as fe


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def sync(x):
    """Force a real device sync: fetch one element (block_until_ready is not
    a reliable barrier through the axon tunnel)."""
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def timeit(name, fn, *args, iters=5):
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    per = (time.perf_counter() - t0) / iters
    log(f"  {name:40s} {per*1e3:9.3f} ms")
    return per


CHAIN = 8  # dependent ops per jit call; per-op cost = total / CHAIN


def main():
    log(f"backend: {jax.default_backend()}")
    rng = np.random.default_rng(0)
    shape = (32, 20480)  # windows x lanes, the tree's hot shape
    nl = fe.NLIMBS

    a32 = jnp.asarray(rng.integers(0, 1 << 13, (nl, *shape), dtype=np.int32))
    b32 = jnp.asarray(rng.integers(0, 1 << 13, (nl, *shape), dtype=np.int32))

    # RTT floor: the cost of sync() itself
    tiny = jnp.zeros((1,))
    t0 = time.perf_counter()
    for _ in range(5):
        sync(tiny)
    log(f"  sync RTT floor: {(time.perf_counter()-t0)/5*1e3:.1f} ms")

    # -- int32 chained mul (current implementation) ------------------------
    @jax.jit
    def chain_mul_i32(a, b):
        x = a
        for _ in range(CHAIN):
            x = fe.mul(x, b)
        return x

    per = timeit("int32 fe.mul chained", chain_mul_i32, a32, b32, iters=4)
    log(f"    -> {per/CHAIN*1e3:.2f} ms per mul @ {shape}")

    # -- f32 8-bit-limb prototype -----------------------------------------
    # 32 limbs x 8 bits; conv terms bounded by 32*255^2 < 2^21 (exact in
    # f32); wrap 2^256 = 38 mod p applied after an 8-bit carry pass.
    NL8 = 32

    def f32_carry(x):
        # one parallel carry pass: x -> digits in [0,256) + carries up
        c = jnp.floor(x * (1.0 / 256.0))
        lo = x - c * 256.0
        wrapped = jnp.concatenate([38.0 * c[NL8 - 1:], c[: NL8 - 1]], axis=0)
        return lo + wrapped

    def f32_mul(a, b):
        # schoolbook conv via shifted accumulation into 63 coefficients
        out = jnp.zeros((2 * NL8 - 1, *a.shape[1:]), dtype=jnp.float32)
        for i in range(NL8):
            out = out.at[i : i + NL8].add(a[i] * b)
        hi = out[NL8:]  # 31 coeffs, weight 2^(8(k+32)) = 38 * 2^(8k) mod p
        lo = out[:NL8]
        # hi < 2^21 but 38*hi > 2^24: split hi = 256*hc + h0 first so every
        # folded term stays exact in the f32 mantissa.
        hc = jnp.floor(hi * (1.0 / 256.0))
        h0 = hi - hc * 256.0
        x = lo
        x = x.at[: NL8 - 1].add(38.0 * h0)
        x = x.at[1:NL8].add(38.0 * hc)
        x = f32_carry(x)
        x = f32_carry(x)
        x = f32_carry(x)
        return x

    af = jnp.asarray(rng.integers(0, 256, (NL8, *shape)).astype(np.float32))
    bf = jnp.asarray(rng.integers(0, 256, (NL8, *shape)).astype(np.float32))

    @jax.jit
    def chain_mul_f32(a, b):
        x = a
        for _ in range(CHAIN):
            x = f32_mul(x, b)
        return x

    per = timeit("f32 8-bit-limb mul chained", chain_mul_f32, af, bf, iters=4)
    log(f"    -> {per/CHAIN*1e3:.2f} ms per mul @ {shape}")

    # correctness spot check of the f32 prototype
    def to_int_f32(limbs):
        arr = np.asarray(limbs, dtype=np.float64)
        return sum(int(round(arr[i].flat[0])) * (1 << (8 * i)) for i in range(NL8)) % fe.P

    xa = int.from_bytes(rng.bytes(31), "little")
    xb = int.from_bytes(rng.bytes(31), "little")
    la = jnp.asarray(np.array([(xa >> (8 * i)) & 0xFF for i in range(NL8)], dtype=np.float32)[:, None, None])
    lb = jnp.asarray(np.array([(xb >> (8 * i)) & 0xFF for i in range(NL8)], dtype=np.float32)[:, None, None])
    got = to_int_f32(f32_mul(la, lb))
    want = xa * xb % fe.P
    log(f"  f32 mul correctness: {'OK' if got == want else f'FAIL {got} != {want}'}")

    # -- int32 multiply vs add raw rate ------------------------------------
    @jax.jit
    def chain_raw_mul(a, b):
        x = a
        for _ in range(CHAIN * 4):
            x = (x * b) & 0x1FFF
        return x

    @jax.jit
    def chain_raw_fma_f32(a, b):
        x = a
        for _ in range(CHAIN * 4):
            x = x * b + a
        return x

    big_i = jnp.asarray(rng.integers(0, 1 << 13, (nl, *shape), dtype=np.int32))
    big_f = big_i.astype(jnp.float32)
    per_i = timeit("raw int32 mul+mask chain", chain_raw_mul, big_i, big_i, iters=4)
    per_f = timeit("raw f32 fma chain", chain_raw_fma_f32, big_f, big_f, iters=4)
    log(f"    -> int32 {per_i/(CHAIN*4)*1e3:.3f} ms/op vs f32 {per_f/(CHAIN*4)*1e3:.3f} ms/op")

    # -- scan vs unrolled tiny-tensor sequential chain ---------------------
    from tendermint_tpu.ops.msm_jax import SmallCtx, _pdbl, make_small_ctx
    from tendermint_tpu.ops.ed25519_jax import Point

    C = make_small_ctx()
    p0 = tuple(jnp.asarray(rng.integers(0, 1 << 13, (nl, 32), dtype=np.int32)) for _ in range(4))

    @jax.jit
    def dbl_scan(p):
        def body(st, _):
            return tuple(_pdbl(C, Point(*st))), None

        st, _ = jax.lax.scan(body, p, None, length=248)
        return st

    @jax.jit
    def dbl_unrolled(p):
        q = Point(*p)
        for _ in range(248):
            q = _pdbl(C, q)
        return tuple(q)

    timeit("248 doublings (20,32) via scan", dbl_scan, p0, iters=4)
    timeit("248 doublings (20,32) unrolled", dbl_unrolled, p0, iters=4)


if __name__ == "__main__":
    main()
