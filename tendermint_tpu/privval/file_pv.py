"""File-backed private validator with double-sign protection
(reference: privval/file.go:150).

Key file: JSON {address, pub_key, priv_key}. State file: JSON last-sign-state
{height, round, step, signature, signbytes}. CheckHRS refuses to sign lower
(H,R,S) and allows idempotent re-signing of the identical payload; votes that
differ only in timestamp re-use the previous signature+timestamp
(reference: privval/file.go:93 CheckHRS, checkVotesOnlyDifferByTimestamp)."""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import replace
from typing import Optional, Tuple

from tendermint_tpu.crypto.keys import Ed25519PrivKey, PrivKey, PubKey
from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.types.basic import SignedMsgType
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

STEP_PROPOSAL = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_STEP_FOR_TYPE = {
    SignedMsgType.PROPOSAL: STEP_PROPOSAL,
    SignedMsgType.PREVOTE: STEP_PREVOTE,
    SignedMsgType.PRECOMMIT: STEP_PRECOMMIT,
}


class DoubleSignError(Exception):
    pass


def _atomic_write(path: str, data: str) -> None:
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class FilePVLastSignState:
    def __init__(self, height=0, round_=0, step=0, signature=b"", sign_bytes=b""):
        self.height = height
        self.round = round_
        self.step = step
        self.signature = signature
        self.sign_bytes = sign_bytes

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Returns True if we might be re-signing the same HRS (caller must
        compare sign bytes); raises on regression (reference: privval/file.go:93)."""
        if self.height > height:
            raise DoubleSignError(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(f"round regression at height {height}. Got {round_}, last round {self.round}")
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at height {height} round {round_}. Got {step}, last step {self.step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no sign_bytes but HRS matches")
                    return True
        return False


class FilePV:
    """Implements the PrivValidator contract: get_pub_key / sign_vote /
    sign_proposal (reference: types/priv_validator.go)."""

    def __init__(self, priv_key: PrivKey, key_file: Optional[str] = None, state_file: Optional[str] = None):
        self.priv_key = priv_key
        self.key_file = key_file
        self.state_file = state_file
        self.last_sign_state = FilePVLastSignState()
        if state_file and os.path.exists(state_file):
            self._load_state()

    # -- persistence --------------------------------------------------------

    @classmethod
    def generate(cls, key_file: Optional[str] = None, state_file: Optional[str] = None, seed: Optional[bytes] = None) -> "FilePV":
        from tendermint_tpu.crypto.keys import gen_ed25519

        pv = cls(gen_ed25519(seed), key_file, state_file)
        if key_file:
            pv.save_key()
        if state_file:
            pv._save_state()
        return pv

    @classmethod
    def load(cls, key_file: str, state_file: str) -> "FilePV":
        with open(key_file) as f:
            o = json.load(f)
        priv = Ed25519PrivKey(bytes.fromhex(o["priv_key"]))
        return cls(priv, key_file, state_file)

    def save_key(self) -> None:
        pub = self.priv_key.pub_key()
        _atomic_write(
            self.key_file,
            json.dumps(
                {
                    "address": pub.address().hex().upper(),
                    "pub_key": pub.bytes().hex(),
                    "priv_key": self.priv_key.bytes().hex(),
                },
                indent=2,
            ),
        )

    def _save_state(self) -> None:
        s = self.last_sign_state
        _atomic_write(
            self.state_file,
            json.dumps(
                {
                    "height": s.height,
                    "round": s.round,
                    "step": s.step,
                    "signature": s.signature.hex(),
                    "sign_bytes": s.sign_bytes.hex(),
                },
                indent=2,
            ),
        )

    def _load_state(self) -> None:
        with open(self.state_file) as f:
            o = json.load(f)
        self.last_sign_state = FilePVLastSignState(
            o["height"], o["round"], o["step"], bytes.fromhex(o["signature"]), bytes.fromhex(o["sign_bytes"])
        )

    # -- PrivValidator interface --------------------------------------------

    def get_pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        """(reference: privval/file.go signVote)"""
        step = _STEP_FOR_TYPE[vote.type]
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(vote.height, vote.round, step)
        sign_bytes = vote.sign_bytes(chain_id)

        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                return vote.with_signature(lss.signature)
            ts = _vote_timestamp_swap(lss.sign_bytes, sign_bytes)
            if ts is not None:
                # votes differ only by timestamp: re-use previous signature
                return replace(vote, timestamp_ns=ts, signature=lss.signature)
            raise DoubleSignError("conflicting data: same HRS, different sign bytes")

        sig = self.priv_key.sign(sign_bytes)
        self._update_state(vote.height, vote.round, step, sign_bytes, sig)
        return vote.with_signature(sig)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(proposal.height, proposal.round, STEP_PROPOSAL)
        sign_bytes = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                return proposal.with_signature(lss.signature)
            ts = _proposal_timestamp_swap(lss.sign_bytes, sign_bytes)
            if ts is not None:
                return replace(proposal, timestamp_ns=ts, signature=lss.signature)
            raise DoubleSignError("conflicting data: same HRS, different sign bytes")
        sig = self.priv_key.sign(sign_bytes)
        self._update_state(proposal.height, proposal.round, STEP_PROPOSAL, sign_bytes, sig)
        return proposal.with_signature(sig)

    def _update_state(self, height: int, round_: int, step: int, sign_bytes: bytes, sig: bytes) -> None:
        self.last_sign_state = FilePVLastSignState(height, round_, step, sig, sign_bytes)
        if self.state_file:
            self._save_state()


def _strip_timestamp(sign_bytes: bytes, ts_field: int) -> Optional[Tuple[bytes, int]]:
    """Remove the timestamp field from canonical sign bytes; returns
    (bytes-without-timestamp, timestamp_ns)."""
    try:
        body, _ = pw.read_length_delimited(sign_bytes)
        out = pw.Writer()
        ts_ns = 0
        for f, wt, v in pw.Reader(body):
            if f == ts_field and wt == pw.BYTES:
                sec = nanos = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        sec = pw.int64_from_varint(vv)
                    elif ff == 2:
                        nanos = pw.int64_from_varint(vv)
                ts_ns = sec * 1_000_000_000 + nanos
                continue
            if wt == pw.VARINT:
                out.varint_field(f, v)
            elif wt == pw.FIXED64:
                out.fixed64_field(f, v)
            elif wt == pw.BYTES:
                out.bytes_field(f, v, emit_empty=True)
        return out.bytes(), ts_ns
    except ValueError:
        return None


def _vote_timestamp_swap(last: bytes, new: bytes) -> Optional[int]:
    """If vote sign bytes differ only by timestamp (field 5), return the LAST
    timestamp (to re-sign identically); else None."""
    a = _strip_timestamp(last, 5)
    b = _strip_timestamp(new, 5)
    if a is None or b is None or a[0] != b[0]:
        return None
    return a[1]


def _proposal_timestamp_swap(last: bytes, new: bytes) -> Optional[int]:
    a = _strip_timestamp(last, 6)
    b = _strip_timestamp(new, 6)
    if a is None or b is None or a[0] != b[0]:
        return None
    return a[1]
