"""Batched Ed25519 verification on TPU (JAX).

The validator-axis hot loop of the whole framework: verifies N signatures at
once, replacing the reference's serial per-signature loop
(reference: types/validator_set.go:680-702, types/vote_set.go:203,
crypto/ed25519/ed25519.go:148).

Semantics: COFACTORED verification (ZIP-215-style) — accept iff
[8]([s]B + [h](-A) - R) == identity, with canonical A/R encodings and s < L
(enforced host-side). This is the framework's single verification predicate:
the host wrapper (crypto/keys.py), this kernel, and the RLC batch path
(ops/msm_jax.py) all implement it exactly, so acceptance never depends on
which path a node runs. Divergences from golang.org/x/crypto (cofactorless,
accepts non-canonical A) exist only for crafted torsion/non-canonical
inputs; honest keys and signatures are torsion-free and canonical, where
all predicates agree (see crypto/ed25519_ref.verify_cofactored).

Layout: batch on the TRAILING axis everywhere (limbs/bytes/digits leading) so
the batch maps onto TPU vector lanes. Points are (X, Y, Z, T) extended twisted
Edwards coordinates; adds use the unified a=-1 formulas, so identity and
doubling need no special cases inside the scan.

The scalar multiplication is a joint windowed double-scalar ladder in signed
radix-16: scalars are recoded host-side into 64 digits in [-8, 8] (LSB-first
in memory, scanned MSB-first). Each scan step does 4 doublings, one mixed add
from the basepoint table (j*B in affine niels form, j=0..8, negation by
coordinate swap) and one unified add from the per-signature table j*(-A)
(j=0..8 extended points, built with 7 adds + 1 double before the scan).

TPU performance note (measured on v5e): XLA compiles per-limb CONSTANT
broadcasts (a (20,1) constant against a (20,B) tensor) into fusions ~200x
slower than the same op against a real (20,B) buffer. Every non-uniform
constant the kernel needs — field constants, the basepoint niels table —
is therefore materialized ONCE as a device array (FieldCtx) outside the jit
and passed in as an argument. Inside foreign traces (shard_map on CPU, the
multichip dryrun) the ctx falls back to in-trace broadcasts, which is
correct everywhere and only slow where it doesn't matter.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519_ref as _ref
from tendermint_tpu.crypto.ed25519_ref import BX as _BX, _BY
from tendermint_tpu.ops import fe25519 as fe

SCALAR_BITS = 253  # s, h < L < 2^253
NUM_DIGITS = 64  # signed radix-16 digits covering 256 bits
WINDOW = 8  # table holds j*P for j in 0..8; sign handled by negation


class Point(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def _basepoint_niels_table() -> np.ndarray:
    """Host precompute: j*B for j=0..8 in affine niels form (y+x, y-x, 2dxy),
    canonical limbs. Shape (9, 3, 20) int32. Entry 0 is the identity (1,1,0),
    so digit 0 rides the same unified mixed-add formula."""
    tab = np.zeros((WINDOW + 1, 3, fe.NLIMBS), dtype=np.int32)
    tab[0, 0] = fe.from_int(1)
    tab[0, 1] = fe.from_int(1)
    for j in range(1, WINDOW + 1):
        X, Y, Z, _T = _ref.point_mul(j, _ref.BASE)
        zinv = pow(Z, fe.P - 2, fe.P)
        x, y = X * zinv % fe.P, Y * zinv % fe.P
        tab[j, 0] = fe.from_int((y + x) % fe.P)
        tab[j, 1] = fe.from_int((y - x) % fe.P)
        tab[j, 2] = fe.from_int(2 * fe.D * x * y % fe.P)
    return tab


_B_NIELS_HOST = _basepoint_niels_table()  # (9, 3, 20)


class FieldCtx(NamedTuple):
    """Materialized per-batch-shape constants (see module docstring)."""

    comp: jnp.ndarray  # (20, ...batch) — fe.COMP
    corr: jnp.ndarray  # (20, ...batch) — fe.CORR
    one: jnp.ndarray  # (20, ...batch) — field 1
    d: jnp.ndarray  # (20, ...batch) — curve d
    d2: jnp.ndarray  # (20, ...batch) — 2d
    sqrt_m1: jnp.ndarray  # (20, ...batch)
    bniels: jnp.ndarray  # (9, 3, 20, ...batch) — basepoint niels table

    # -- field helpers bound to the materialized constants ------------------

    def sub(self, a, b):
        return fe.sub(a, b, self.comp, self.corr)

    def neg(self, a):
        return fe.sub(jnp.zeros_like(a), a, self.comp, self.corr)

    def zero(self):
        return jnp.zeros_like(self.one)


def _broadcast(x: np.ndarray, batch_shape) -> jnp.ndarray:
    return jnp.asarray(
        np.broadcast_to(
            x.reshape(x.shape + (1,) * len(batch_shape)), x.shape + tuple(batch_shape)
        ).copy()
    )


_CTX_CACHE: dict = {}
_CTX_CACHE_MAX = 8  # bniels is ~2.6KB/element; bound the device pinning


def make_ctx(batch_shape) -> FieldCtx:
    """Eagerly build (and cache, FIFO-bounded) the materialized constants for
    a batch shape. Must be called OUTSIDE any jax trace to produce real
    device buffers."""
    key = tuple(batch_shape)
    ctx = _CTX_CACHE.get(key)
    if ctx is None:
        while len(_CTX_CACHE) >= _CTX_CACHE_MAX:
            _CTX_CACHE.pop(next(iter(_CTX_CACHE)))
        ctx = FieldCtx(
            comp=_broadcast(np.asarray(fe.COMP), batch_shape),
            corr=_broadcast(np.asarray(fe.CORR), batch_shape),
            one=_broadcast(fe.from_int(1), batch_shape),
            d=_broadcast(fe.from_int(fe.D), batch_shape),
            d2=_broadcast(fe.from_int(fe.D2), batch_shape),
            sqrt_m1=_broadcast(fe.from_int(fe.SQRT_M1), batch_shape),
            bniels=_broadcast(_B_NIELS_HOST, batch_shape),
        )
        _CTX_CACHE[key] = ctx
    return ctx


def _trace_ctx(batch_shape) -> FieldCtx:
    """In-trace fallback: plain broadcast constants (correct, not fast)."""

    def bc(x):
        x = jnp.asarray(np.asarray(x, dtype=np.int32))
        return jnp.broadcast_to(
            x.reshape(x.shape + (1,) * len(batch_shape)), x.shape + tuple(batch_shape)
        )

    return FieldCtx(
        comp=bc(fe.COMP),
        corr=bc(fe.CORR),
        one=bc(fe.from_int(1)),
        d=bc(fe.from_int(fe.D)),
        d2=bc(fe.from_int(fe.D2)),
        sqrt_m1=bc(fe.from_int(fe.SQRT_M1)),
        bniels=bc(_B_NIELS_HOST),
    )


def identity(ctx: FieldCtx) -> Point:
    z = ctx.zero()
    return Point(z, ctx.one, ctx.one, z)


def point_add(ctx: FieldCtx, p: Point, q: Point) -> Point:
    """Unified a=-1 extended addition (add-2008-hwcd-3): 8M + 1 const-mul."""
    a = fe.mul(ctx.sub(p.y, p.x), ctx.sub(q.y, q.x))
    b = fe.mul(fe.add(p.y, p.x), fe.add(q.y, q.x))
    c = fe.mul(fe.mul(p.t, q.t), ctx.d2)
    d = fe.mul_small(fe.mul(p.z, q.z), 2)
    e = ctx.sub(b, a)
    f = ctx.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_double(ctx: FieldCtx, p: Point) -> Point:
    """dbl-2008-hwcd for a=-1: 4M + 4S (cheaper than unified add)."""
    xx = fe.square(p.x)  # A
    yy = fe.square(p.y)  # B
    zz2 = fe.mul_small(fe.square(p.z), 2)  # C
    xy2 = fe.square(fe.add(p.x, p.y))
    e = ctx.sub(xy2, fe.add(xx, yy))  # E = (X+Y)^2 - A - B = 2XY
    g = ctx.sub(yy, xx)  # G = D + B = B - A   (D = aA = -A)
    f = ctx.sub(g, zz2)  # F = G - C
    h = ctx.neg(fe.add(xx, yy))  # H = D - B = -(A + B)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_neg(ctx: FieldCtx, p: Point) -> Point:
    return Point(ctx.neg(p.x), p.y, p.z, ctx.neg(p.t))


def point_select(cond: jnp.ndarray, a: Point, b: Point) -> Point:
    """cond ? a : b, cond shaped like the batch."""
    return Point(
        fe.select(cond, a.x, b.x),
        fe.select(cond, a.y, b.y),
        fe.select(cond, a.z, b.z),
        fe.select(cond, a.t, b.t),
    )


def decompress(ctx: FieldCtx, s_bytes: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """uint8[32, ...batch] -> (Point, ok mask). RFC 8032 §5.1.3."""
    s_bytes = jnp.asarray(s_bytes)
    sign = (s_bytes[31] >> 7).astype(jnp.int32)
    y = fe.from_bytes(s_bytes, mask_high_bit=True)
    canonical = fe.is_canonical_bytes(s_bytes)

    one = ctx.one
    yy = fe.square(y)
    u = ctx.sub(yy, one)
    v = fe.add(fe.mul(yy, ctx.d), one)
    v3 = fe.mul(fe.square(v), v)
    v7 = fe.mul(fe.square(v3), v)
    t = fe.pow_p58(fe.mul(u, v7))
    x = fe.mul(fe.mul(u, v3), t)  # candidate sqrt(u/v)

    vxx = fe.mul(v, fe.square(x))
    ok_direct = fe.eq(vxx, u)
    ok_flipped = fe.eq(vxx, ctx.neg(u))
    x = fe.select(ok_direct, x, fe.mul(x, ctx.sqrt_m1))
    ok = canonical & (ok_direct | ok_flipped)

    x_frozen = fe.freeze(x)
    x_is_zero = fe.is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    flip = fe.bit(x_frozen, 0) != sign
    x = fe.select(flip, ctx.neg(x), x)
    return Point(x, y, one, fe.mul(x, y)), ok


def compress(p: Point) -> jnp.ndarray:
    """Point -> canonical encoding uint8[32, ...batch]."""
    zinv = fe.inv(p.z)
    x = fe.freeze(fe.mul(p.x, zinv))
    y = fe.mul(p.y, zinv)
    out = fe.to_bytes(y)
    sign = (fe.bit(x, 0) << jnp.int32(7)).astype(jnp.uint8)
    return out.at[31].set(out[31] | sign)


def _onehot(digit_mag: jnp.ndarray) -> jnp.ndarray:
    """int32[...batch] in [0,8] -> int32[9, ...batch] one-hot."""
    idx = jnp.arange(WINDOW + 1, dtype=jnp.int32).reshape(
        (WINDOW + 1,) + (1,) * digit_mag.ndim
    )
    return (digit_mag[None] == idx).astype(jnp.int32)


def _select_b_niels(ctx: FieldCtx, digit: jnp.ndarray):
    """Signed select from the materialized basepoint table.
    digit int32 in [-8,8]."""
    oh = _onehot(jnp.abs(digit))  # (9, ...batch)
    sel = jnp.sum(ctx.bniels * oh[:, None, None], axis=0)  # (3, 20, ...batch)
    yplus, yminus, xy2d = sel[0], sel[1], sel[2]
    neg = digit < 0
    yplus2 = fe.select(neg, yminus, yplus)
    yminus2 = fe.select(neg, yplus, yminus)
    xy2d2 = fe.select(neg, ctx.neg(xy2d), xy2d)
    return yplus2, yminus2, xy2d2


def add_niels(ctx: FieldCtx, p: Point, yplus, yminus, xy2d) -> Point:
    """Mixed add of an affine niels point (7M): the unified a=-1 formula with
    Z2=1 and the (y2+x2, y2-x2, 2d*x2*y2) products precomputed."""
    a = fe.mul(ctx.sub(p.y, p.x), yminus)
    b = fe.mul(fe.add(p.y, p.x), yplus)
    c = fe.mul(p.t, xy2d)
    d = fe.mul_small(p.z, 2)
    e = ctx.sub(b, a)
    f = ctx.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _select_point_table(ctx: FieldCtx, tx, ty, tz, tt, digit: jnp.ndarray) -> Point:
    """Signed select of an extended point from a per-batch table
    (9, 20, ...batch) per coordinate. Negation: x -> -x, t -> -t."""
    oh = _onehot(jnp.abs(digit))[:, None]  # (9, 1, ...batch)
    x = jnp.sum(tx * oh, axis=0)
    y = jnp.sum(ty * oh, axis=0)
    z = jnp.sum(tz * oh, axis=0)
    t = jnp.sum(tt * oh, axis=0)
    neg = digit < 0
    return Point(fe.select(neg, ctx.neg(x), x), y, z, fe.select(neg, ctx.neg(t), t))


def _verify_core(
    a_bytes: jnp.ndarray,
    r_bytes: jnp.ndarray,
    s_digits: jnp.ndarray,
    h_digits: jnp.ndarray,
    ctx: FieldCtx,
) -> jnp.ndarray:
    """Core batched check (cofactored): [8]([s]B + [h](-A) - R) == identity.
    Returns bool[...batch]."""
    a_bytes = jnp.asarray(a_bytes)
    r_bytes = jnp.asarray(r_bytes)
    s_digits = jnp.asarray(s_digits, dtype=jnp.int8).astype(jnp.int32)
    h_digits = jnp.asarray(h_digits, dtype=jnp.int8).astype(jnp.int32)

    neg_a, ok_a = decompress(ctx, a_bytes)
    neg_a = point_neg(ctx, neg_a)
    r_pt, ok_r = decompress(ctx, r_bytes)
    r_pt = point_select(ok_r, r_pt, identity(ctx))

    # Per-signature table: j*(-A) for j=0..8 (identity, -A, 2(-A), ..., 8(-A)).
    entries = [identity(ctx), neg_a]
    entries.append(point_double(ctx, neg_a))
    for _ in range(3, WINDOW + 1):
        entries.append(point_add(ctx, entries[-1], neg_a))
    ta_x = jnp.stack([e.x for e in entries])  # (9, 20, ...batch)
    ta_y = jnp.stack([e.y for e in entries])
    ta_z = jnp.stack([e.z for e in entries])
    ta_t = jnp.stack([e.t for e in entries])

    # MSB-first scan over digit pairs.
    xs = jnp.stack([s_digits[::-1], h_digits[::-1]], axis=1)  # (64, 2, ...batch)

    def step(acc: Point, dd):
        ds, dh = dd[0], dd[1]
        acc = point_double(ctx, point_double(ctx, point_double(ctx, point_double(ctx, acc))))
        acc = add_niels(ctx, acc, *_select_b_niels(ctx, ds))
        acc = point_add(ctx, acc, _select_point_table(ctx, ta_x, ta_y, ta_z, ta_t, dh))
        return acc, None

    acc, _ = jax.lax.scan(step, identity(ctx), xs)
    # Cofactored acceptance: q = acc - R, then [8]q must be the identity.
    # (Replacing the old enc(acc) == enc(R) compare also drops a field
    # inversion from the kernel.) The z != 0 guard rejects the (0,0,0,0)
    # output an exceptional unified addition on crafted torsion inputs
    # could produce, instead of silently accepting it.
    q = point_add(ctx, acc, point_neg(ctx, r_pt))
    for _ in range(3):
        q = point_double(ctx, q)
    is_id = fe.is_zero(q.x) & fe.eq(q.y, q.z) & ~fe.is_zero(q.z)
    return ok_a & ok_r & is_id


_verify_jit = jax.jit(_verify_core)


def verify_prepared(
    a_bytes: jnp.ndarray,
    r_bytes: jnp.ndarray,
    s_digits: jnp.ndarray,
    h_digits: jnp.ndarray,
) -> jnp.ndarray:
    """Public entry: batched cofactorless verification, bool[...batch].

    Outside a trace, materialized constants are built eagerly (fast path);
    inside someone else's jit/shard_map the in-trace fallback keeps it
    correct."""
    batch = jnp.shape(a_bytes)[1:]
    if any(
        isinstance(x, jax.core.Tracer)
        for x in (a_bytes, r_bytes, s_digits, h_digits)
    ):
        return _verify_core(a_bytes, r_bytes, s_digits, h_digits, _trace_ctx(batch))
    from tendermint_tpu.libs.trace import tracer as _tracer
    from tendermint_tpu.ops import aot_cache  # lazy: avoids import cycle

    if _tracer.enabled:
        with _tracer.span("kernel.persig", lanes=int(batch[0]) if batch else 1):
            return aot_cache.call(
                "persig", _verify_jit, a_bytes, r_bytes, s_digits, h_digits,
                make_ctx(batch),
            )
    return aot_cache.call(
        "persig", _verify_jit, a_bytes, r_bytes, s_digits, h_digits, make_ctx(batch)
    )
