"""RPC clients (reference: rpc/client/http + rpc/client/local).

HTTPClient speaks JSON-RPC over HTTP (aiohttp) to any node's RPC server;
LocalClient calls the in-process server handlers directly (backs the light
client's provider and tests without a socket, reference: rpc/client/local)."""

from __future__ import annotations

import json
from typing import Optional

import aiohttp


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(f"RPC error {code}: {message} {data}")
        self.code = code


class HTTPClient:
    """(reference: rpc/client/http/http.go)"""

    def __init__(self, base_url: str):
        if not base_url.startswith("http"):
            base_url = "http://" + base_url.replace("tcp://", "")
        self.base_url = base_url.rstrip("/")
        self._session: Optional[aiohttp.ClientSession] = None
        self._id = 0

    async def _ensure(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()

    async def call(self, method: str, **params):
        session = await self._ensure()
        self._id += 1
        payload = {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        async with session.post(self.base_url + "/", json=payload) as resp:
            body = await resp.json(content_type=None)
        if body.get("error"):
            err = body["error"]
            raise RPCError(err.get("code", -1), err.get("message", ""), err.get("data", ""))
        return body.get("result")

    # convenience wrappers (the route set mirrors rpc/core/routes.go)
    async def status(self):
        return await self.call("status")

    async def health(self):
        return await self.call("health")

    async def block(self, height: Optional[int] = None):
        return await self.call("block", **({"height": height} if height else {}))

    async def block_by_hash(self, block_hash: str):
        return await self.call("block_by_hash", hash=block_hash)

    async def block_results(self, height: Optional[int] = None):
        return await self.call("block_results", **({"height": height} if height else {}))

    async def commit(self, height: Optional[int] = None):
        return await self.call("commit", **({"height": height} if height else {}))

    async def validators(self, height: Optional[int] = None):
        return await self.call("validators", **({"height": height} if height else {}))

    async def genesis(self):
        return await self.call("genesis")

    async def tx(self, tx_hash: str):
        return await self.call("tx", hash=tx_hash)

    async def tx_search(self, query: str, page: int = 1, per_page: int = 30):
        return await self.call("tx_search", query=query, page=page, per_page=per_page)

    async def block_search(self, query: str, page: int = 1, per_page: int = 30):
        return await self.call("block_search", query=query, page=page, per_page=per_page)

    async def broadcast_tx_sync(self, tx: bytes):
        return await self.call("broadcast_tx_sync", tx="0x" + tx.hex())

    async def broadcast_tx_commit(self, tx: bytes):
        return await self.call("broadcast_tx_commit", tx="0x" + tx.hex())

    async def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = False):
        return await self.call("abci_query", path=path, data=data.hex(), height=height, prove=prove)

    async def net_info(self):
        return await self.call("net_info")

    async def consensus_state(self):
        return await self.call("consensus_state")

    async def consensus_params(self, height=None):
        return await self.call("consensus_params", height=height)

    async def dump_consensus_state(self):
        return await self.call("dump_consensus_state")


class LocalClient:
    """Direct in-process calls against a node's RPC handler table
    (reference: rpc/client/local/local.go)."""

    def __init__(self, node):
        from tendermint_tpu.rpc.server import RPCServer

        self._server = RPCServer(node) if node.rpc_server is None else node.rpc_server

    async def call(self, method: str, **params):
        handler = self._server._routes.get(method)
        if handler is None:
            raise RPCError(-32601, f"method {method} not found")
        return await handler(params)

    def __getattr__(self, name):
        async def _proxy(**params):
            return await self.call(name, **params)

        return _proxy
