"""The overload soak (slow lane; ISSUE 5 acceptance): one flooding peer
saturating the mempool channel of a live 4-validator net plus a concurrent
RPC broadcast burst. The chain must commit >= 20 heights with zero safety
violations, block interval within 2x the unloaded baseline, the flooder
throttled (shed counters) and reported by the rate limiter, and once the
flood stops the node re-admits txs (shed switches flip back).

Flood payloads and timing derive from TMTPU_OVERLOAD_SEED (default
20260803), so a failing run replays from its seed. Runs over the plaintext
transport — works in minimal containers without the `cryptography` wheel."""

import asyncio
import os
import random
import time

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

pytestmark = pytest.mark.slow

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import test_config
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.mempool.reactor import MEMPOOL_CHANNEL, encode_txs
from tendermint_tpu.node.node import Node
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.rpc.client import LocalClient, RPCError
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

SEED = int(os.environ.get("TMTPU_OVERLOAD_SEED", "20260803"))
TARGET_HEIGHTS = 20
N = 4


def make_overload_net(tmp_path):
    privs = [FilePV(gen_ed25519(bytes([40 + i]) * 32)) for i in range(N)]
    gen = GenesisDoc(
        chain_id="overload-soak",
        validators=[GenesisValidator(p.get_pub_key(), 10) for p in privs],
    )

    def make_node(i):
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.base.fast_sync = False
        cfg.rpc.laddr = ""
        cfg.rpc.max_inflight_requests = 8
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.plaintext = True
        cfg.p2p.pex = False
        # tight inbound budgets so the flood sheds fast and the flooder is
        # reported within the soak window (the in-process net's single
        # event loop caps arrival at tens of msgs/s, so budgets scale down
        # with it — production defaults are 2000 msgs/s / 1MB/s)
        cfg.p2p.recv_rate_msgs_per_channel = 10
        cfg.p2p.recv_rate_bytes_per_channel = 8 * 1024
        cfg.p2p.recv_rate_strikes = 25
        cfg.p2p.recv_rate_strike_window = 10.0
        # small pool: the burst must trigger eviction/quota, not disappear
        cfg.mempool.size = 150
        cfg.mempool.ttl_num_blocks = 8
        cfg.mempool.max_txs_per_sender = 60
        cfg.overload.sample_interval = 0.1
        # SLO policy for the soak (ISSUE 8): a 10% error budget — the guard
        # trips on a SUSTAINED fraction (>=40% at burn 4x) of over-budget
        # blocks, not on scattered outliers; the commit-interval budget
        # itself is declared at runtime from the measured baseline
        cfg.slo.target = 0.9
        cfg.root_dir = ""
        cfg.consensus.wal_path = str(tmp_path / f"wal{i}" / "wal")
        priv = FilePV(
            gen_ed25519(bytes([40 + i]) * 32),
            state_file=str(tmp_path / f"pv_state_{i}.json"),
        )
        return Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())

    return make_node


def assert_safety(nodes):
    top = max(n.block_store.height for n in nodes)
    for h in range(1, top + 1):
        hashes = {
            b.hash().hex()
            for b in (n.block_store.load_block(h) for n in nodes if n.block_store.height >= h)
            if b is not None
        }
        assert len(hashes) <= 1, f"SAFETY VIOLATION at height {h}: {hashes}"


async def _wait_height(node, h, deadline, what):
    while node.block_store.height < h:
        assert asyncio.get_event_loop().time() < deadline, (
            f"{what}: stalled at height {node.block_store.height} (want {h})"
        )
        await asyncio.sleep(0.05)


def test_overload_soak_flood_shed_recover(tmp_path):
    rng = random.Random(SEED)

    async def run():
        make_node = make_overload_net(tmp_path)
        nodes = [make_node(i) for i in range(N)]
        for n in nodes:
            await n.start()
        loop = asyncio.get_event_loop()
        deadline = loop.time() + 600.0
        stop_flood = asyncio.Event()
        try:
            # full mesh
            for a in nodes:
                for b in nodes:
                    if a is not b and not a.switch.peers.has(b.node_key.id):
                        await a.switch.dial_peers_async(
                            [f"{b.node_key.id}@{b.p2p_addr}"], persistent=True
                        )

            victim, flooder = nodes[0], nodes[3]
            victim_id, flooder_id = victim.node_key.id, flooder.node_key.id

            # ---- unloaded baseline ------------------------------------
            await _wait_height(victim, 4, deadline, "warmup")
            h0, t0 = victim.block_store.height, loop.time()
            await _wait_height(victim, h0 + 6, deadline, "baseline")
            baseline = (loop.time() - t0) / 6

            # declare the soak's commit-interval budget from the measured
            # baseline (ISSUE 8: the soak asserts SLOs instead of an ad-hoc
            # interval ratio — same 2x bound, now burn-rate evaluated: a
            # trip means a sustained fraction of blocks blew the budget,
            # one slow block alone cannot fail the soak)
            assert victim.slo is not None
            victim.slo.budgets["commit_interval"] = 2 * baseline + 0.25

            # ---- flood phase ------------------------------------------
            async def flood():
                """Mempool-channel saturation from the flooding peer: raw
                batched tx gossip frames straight onto the wire, bypassing
                the flooder's own mempool/admission (a misbehaving client).
                Batches of 20 keep the per-message cost high enough to blow
                the victim's bytes budget at in-process arrival rates."""
                n = 0
                while not stop_flood.is_set():
                    peer = flooder.switch.peers.get(victim_id)
                    if peer is None:  # disconnected by the limiter: re-dial
                        await asyncio.sleep(0.05)
                        continue
                    batch = [
                        b"flood=%d:%d" % (n * 20 + j, rng.getrandbits(32))
                        for j in range(20)
                    ]
                    peer.try_send(MEMPOOL_CHANNEL, encode_txs(batch))
                    n += 1
                    if n % 10 == 0:
                        await asyncio.sleep(0.002)

            async def rpc_burst(client):
                codes = {"ok": 0, "shed": 0, "mempool": 0}

                async def one(i):
                    try:
                        res = await client.broadcast_tx_sync(
                            tx="0x" + (b"burst=%d:%d" % (i, SEED)).hex()
                        )
                        if res["code"] == 0:
                            codes["ok"] += 1
                    except RPCError as e:
                        if e.code == -32005:
                            codes["shed"] += 1
                        elif e.code == -32001:
                            codes["mempool"] += 1
                        else:
                            raise
                    except Exception:
                        codes["mempool"] += 1  # structured reject via raise path

                for batch in range(6):
                    await asyncio.gather(*(one(batch * 50 + i) for i in range(50)))
                    await asyncio.sleep(0.2)
                return codes

            h1, t1 = victim.block_store.height, loop.time()
            flood_task = asyncio.create_task(flood())
            client = LocalClient(victim)
            # register the handler-only server on the node so the overload
            # controller governs ITS load gate (no TCP listener needed)
            victim.rpc_server = client._server
            burst_task = asyncio.create_task(rpc_burst(client))
            await _wait_height(victim, h1 + TARGET_HEIGHTS, deadline, "flood phase")
            flood_interval = (loop.time() - t1) / (victim.block_store.height - h1)
            codes = await burst_task
            stop_flood.set()
            await flood_task

            # liveness: block production survived the flood — the declared
            # commit-interval budget held (libs/slo.py burn-rate guard; the
            # measured mean rides the failure message for triage)
            victim.slo.assert_budgets(["commit_interval"])
            assert flood_interval <= 3 * baseline + 0.5, (
                f"block interval collapsed: {flood_interval:.3f}s vs "
                f"baseline {baseline:.3f}s"
            )
            # the RPC burst was actually served/shed, not lost
            assert sum(codes.values()) == 300, codes
            assert codes["ok"] > 0

            # the victim THROTTLED the flooder: mempool-channel sheds on the
            # flooder's connection, and the rate limiter reported it
            vm = victim.metrics.p2p
            shed = sum(
                v for k, v in vm.rate_limited_msgs._values.items() if k == ("0x30",)
            )
            assert shed > 0, "no inbound mempool gossip was shed"
            reports = vm.rate_limit_disconnects._values.get((), 0)
            assert reports >= 1, "flooder never reported for rate-limit misbehavior"
            # and nothing was EVER shed from the consensus channels
            for chid in ("0x20", "0x21", "0x22", "0x23"):
                assert vm.rate_limited_msgs._values.get((chid,), 0) == 0, (
                    f"votes/proposals shed on channel {chid}"
                )

            # admission control did real work under the burst
            mp = victim.mempool
            assert mp.size() <= mp.max_txs
            assert (
                mp.evicted_total > 0
                or victim.metrics.mempool.rejected_txs._values
            ), "the burst never exercised eviction/rejection"

            # ---- recovery ---------------------------------------------
            # pressure drains: shed switches must flip back and a fresh tx
            # must be re-admitted and committed
            t_rec = loop.time()
            while victim.mempool_reactor.shed or client._server.gate.shed_writes:
                assert loop.time() - t_rec < 60.0, "shed switches never reset"
                await asyncio.sleep(0.1)
            res = await client.broadcast_tx_sync(tx="0x" + b"post-flood=1".hex())
            assert res["code"] == 0
            h2 = victim.block_store.height
            await _wait_height(victim, h2 + 3, deadline, "post-flood liveness")

            assert_safety(nodes)

            # chain observatory (ISSUE 8 acceptance): merge every node's
            # dump into the fleet report — the waterfall must cover all
            # nodes on at least one height, and the victim's declared
            # commit-interval budget verdict rides the SLO section
            from tendermint_tpu.tools import chain_observatory as obs

            dump_dir = str(tmp_path / "observatory")
            for n in nodes:
                obs.write_node_dump(n, dump_dir)
            report = obs.merge(obs.load_dumps(dump_dir))
            labels = {n.node_key.id[:10] for n in nodes}
            covered = [
                rec for rec in report["heights"]
                if labels <= set(rec["nodes"])
                and all(rec["nodes"][l]["commit_ms"] is not None for l in labels)
            ]
            assert covered, (
                f"no height's waterfall covered all {len(labels)} nodes: "
                f"{[(r['height'], sorted(r['nodes'])) for r in report['heights']]}"
            )
            assert report["peer_lag"], "no propagation aggregates in the report"
            assert any(
                e["objective"] == "commit_interval" and not e["tripped"]
                for e in report["slo"]
            )
            (tmp_path / "observatory" / "chain_report.md").write_text(
                obs.render_markdown(report)
            )
        finally:
            stop_flood.set()
            for n in nodes:
                try:
                    await n.stop()
                except Exception:
                    pass

    asyncio.run(run())
