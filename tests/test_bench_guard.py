"""bench.py's stall guards: the driver's end-of-round bench must emit its
one JSON line even when the device tunnel hangs uninterruptibly (observed
r5: jax.devices() blocked in C without servicing SIGALRM, indefinitely)."""

import contextlib
import io
import json
import os
import sys
import time

import pytest


def _bench():
    import bench

    return bench


def test_watchdog_fires_and_resets():
    bench = _bench()
    with pytest.raises(TimeoutError):
        with bench.watchdog(1):
            time.sleep(3)
    # alarm cleared: nothing fires after the context exits
    with bench.watchdog(1):
        pass
    time.sleep(1.2)


def test_guarded_main_passes_child_json_through(tmp_path, monkeypatch):
    bench = _bench()
    stub = tmp_path / "stub_bench.py"
    stub.write_text('print(\'{"metric": "stub", "value": 1, "unit": "ms", "vs_baseline": 2.0}\')\n')
    monkeypatch.setattr(bench, "__file__", str(stub))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    out = buf.getvalue()
    assert json.loads(out)["metric"] == "stub"
    assert out.count("\n") == 1


def test_guarded_main_emits_fallback_on_hung_child(tmp_path, monkeypatch):
    bench = _bench()
    stub = tmp_path / "hang_bench.py"
    stub.write_text("import time\ntime.sleep(600)\n")
    monkeypatch.setattr(bench, "__file__", str(stub))
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "1")
    monkeypatch.setenv("TMTPU_BENCH_HARD_MARGIN_S", "1")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["value"] == -1
    assert "deadline" in rep["extra"]["error"]


def test_guarded_main_salvages_json_printed_before_hang(tmp_path, monkeypatch):
    """A child that prints its complete result and THEN hangs in teardown
    (the tunnel client's threads) must have that result forwarded."""
    bench = _bench()
    stub = tmp_path / "hang_after_json.py"
    stub.write_text(
        'import sys, time\n'
        'print(\'{"metric": "late", "value": 7, "unit": "ms", "vs_baseline": 3.0}\', flush=True)\n'
        "time.sleep(600)\n"
    )
    monkeypatch.setattr(bench, "__file__", str(stub))
    # deadline must comfortably cover interpreter startup under load: the
    # stub prints immediately, so 8 s total is plenty and stays flake-free
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "4")
    monkeypatch.setenv("TMTPU_BENCH_HARD_MARGIN_S", "4")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["metric"] == "late" and rep["value"] == 7


def test_guarded_main_salvages_json_from_crashing_child(tmp_path, monkeypatch):
    """A child that prints the result then exits NONZERO (teardown crash)
    must still have the result forwarded, not replaced by the fallback."""
    bench = _bench()
    stub = tmp_path / "crash_after_json.py"
    stub.write_text(
        'import sys\n'
        'print(\'{"metric": "crashy", "value": 9, "unit": "ms", "vs_baseline": 1.5}\')\n'
        "sys.exit(134)\n"
    )
    monkeypatch.setattr(bench, "__file__", str(stub))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["metric"] == "crashy" and rep["value"] == 9


def test_help_documents_flight_recorder_breakdown():
    """Acceptance: the per-stage breakdown bench attaches to its JSON
    `extra` is documented in `bench.py --help`."""
    import subprocess

    p = subprocess.run(
        [sys.executable, "bench.py", "--help"],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.abspath(_bench().__file__)),
        timeout=120,
    )
    assert p.returncode == 0
    assert "verify_stats" in p.stdout
    assert "device_health" in p.stdout
    assert "stage_seconds" in p.stdout


def test_flight_recorder_extra_present_in_results():
    """extra.verify_stats carries the per-stage breakdown after a CPU flush,
    and even the stall-fallback JSON includes it (so a -1 result still
    localises the failed stage)."""
    import contextlib
    import io

    bench = _bench()
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto.keys import gen_ed25519

    priv = gen_ed25519(b"\x54" * 32)
    msgs = [b"bench-extra-%d" % i for i in range(3)]
    sigs = [priv.sign(m) for m in msgs]
    assert B.verify_batch(
        [priv.pub_key().bytes()] * 3, msgs, sigs, backend="cpu"
    ).all()

    extra = bench._flight_recorder_extra()
    assert extra["verify_stats"]["totals"]["cpu/cpu"]["flushes"] >= 1
    assert "stage_seconds" in extra["verify_stats"]
    assert "last_flush" in extra["verify_stats"]
    assert "device_up" in extra["device_health"]

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit_fallback("device initialization stalled (test)")
    rep = json.loads(buf.getvalue())
    assert rep["value"] == -1
    assert rep["extra"]["error"].startswith("device initialization stalled")
    assert "verify_stats" in rep["extra"]
    assert "device_health" in rep["extra"]


def _last_json(buf: str) -> dict:
    for line in reversed(buf.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    raise AssertionError(f"no JSON in output: {buf!r}")


def test_scenario_fault_degrades_one_scenario_not_the_run(monkeypatch, capsys):
    """ISSUE 6 acceptance: a device fault in ONE scenario yields a
    clearly-marked CPU-fallback datapoint for that scenario while every
    other scenario (and the headline) survives — no whole-run -1."""
    bench = _bench()
    monkeypatch.setenv("TMTPU_BENCH_INPROC", "1")
    monkeypatch.setenv("TMTPU_BENCH_SCENARIOS", "cfg_a,dead_b,extra_c")
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "600")
    monkeypatch.setattr(bench, "_CONFIG_SIZES", {"cfg_a": (8, None)})
    fns = {
        "cfg_a": lambda: {"n": 8, "tpu_e2e_ms": 1.25, "speedup_e2e": 2.0},
        "dead_b": lambda: (_ for _ in ()).throw(
            RuntimeError("injected device stall")
        ),
        "extra_c": lambda: {"blocks_per_sec": 42},
    }
    monkeypatch.setattr(bench, "_scenario_fns", lambda: fns)
    monkeypatch.setattr(
        bench,
        "_cpu_fallback_fns",
        lambda: {"dead_b": lambda: {"cpu_blocks_per_sec": 3}},
    )
    bench.main()
    rep = _last_json(capsys.readouterr().out)
    # headline survived the faulted scenario
    assert rep["metric"] == "cfg_a_latency" and rep["value"] == 1.25
    # the faulted scenario still emitted a parseable, clearly-marked datapoint
    dead = rep["extra"]["dead_b"]
    assert dead["degraded"] == "cpu-fallback"
    assert "injected device stall" in dead["degrade_reason"]
    assert dead["cpu_blocks_per_sec"] == 3
    # unaffected scenarios ran normally
    assert rep["extra"]["extra_c"] == {"blocks_per_sec": 42}


def test_degraded_headline_is_marked_at_top_level(monkeypatch, capsys):
    """When the only available headline is a CPU-fallback measurement, the
    top-level JSON says so — a consumer tracking metric/value across rounds
    must never mistake a host-loop number for a device datapoint."""
    bench = _bench()
    monkeypatch.setenv("TMTPU_BENCH_INPROC", "1")
    monkeypatch.setenv("TMTPU_BENCH_SCENARIOS", "cfg_a")
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "600")
    monkeypatch.setattr(bench, "_CONFIG_SIZES", {"cfg_a": (8, None)})

    def boom():
        raise RuntimeError("device gone")

    monkeypatch.setattr(bench, "_scenario_fns", lambda: {"cfg_a": boom})
    monkeypatch.setattr(
        bench,
        "_cpu_fallback_fns",
        lambda: {"cfg_a": lambda: {"n": 8, "tpu_e2e_ms": 9.9, "speedup_e2e": 1.0}},
    )
    bench.main()
    rep = _last_json(capsys.readouterr().out)
    assert rep["value"] == 9.9
    assert rep["degraded"] == "cpu-fallback"
    assert "device gone" in rep["degrade_reason"]
    assert rep["extra"]["cfg_a"]["degraded"] == "cpu-fallback"


def test_all_scenarios_failing_still_emits_every_datapoint(monkeypatch, capsys):
    bench = _bench()
    monkeypatch.setenv("TMTPU_BENCH_INPROC", "1")
    monkeypatch.setenv("TMTPU_BENCH_SCENARIOS", "dead_a,dead_b")
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "600")
    monkeypatch.setattr(bench, "_CONFIG_SIZES", {})

    def boom():
        raise RuntimeError("tunnel down")

    monkeypatch.setattr(
        bench, "_scenario_fns", lambda: {"dead_a": boom, "dead_b": boom}
    )
    monkeypatch.setattr(bench, "_cpu_fallback_fns", lambda: {})
    bench.main()
    rep = _last_json(capsys.readouterr().out)
    assert rep["value"] == -1  # no headline possible...
    for name in ("dead_a", "dead_b"):  # ...but every scenario is accounted for
        assert rep["extra"][name]["degraded"] == "cpu-fallback"
        assert "tunnel down" in rep["extra"][name]["degrade_reason"]


def test_bench_fault_hook_fires_for_named_scenario_only(monkeypatch, capsys):
    bench = _bench()
    monkeypatch.setenv("TMTPU_BENCH_INPROC", "1")
    monkeypatch.setenv("TMTPU_BENCH_SCENARIOS", "selftest_fast")
    monkeypatch.setenv("TMTPU_BENCH_FAULT", "selftest_fast:raise")
    monkeypatch.setenv("TMTPU_BENCH_BUDGET_S", "600")
    monkeypatch.setattr(bench, "_CONFIG_SIZES", {})
    bench.main()
    rep = _last_json(capsys.readouterr().out)
    st = rep["extra"]["selftest_fast"]
    assert st["degraded"] == "cpu-fallback"
    assert "injected bench fault" in st["degrade_reason"]
    # the degraded (CPU) retry must NOT re-fire the fault
    assert "error" not in st


def test_scenario_child_subprocess_protocol():
    """One real scenario child: prints exactly one JSON line with the
    scenario report, isolated in its own process."""
    import subprocess

    env = dict(
        os.environ,
        TMTPU_BENCH_SCENARIO="selftest_fast",
        JAX_PLATFORMS="cpu",
        TMTPU_CRYPTO_BACKEND="cpu",
    )
    p = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.abspath(_bench().__file__)),
        env=env,
        timeout=240,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    assert rep["scenario"] == "selftest_fast"
    assert rep["ok"] is True
    assert rep["result"]["marker"] == "selftest"
    assert "verify_stats" in rep["flight"]


def test_help_documents_scenario_isolation_and_slope():
    import subprocess

    p = subprocess.run(
        [sys.executable, "bench.py", "--help"],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.abspath(_bench().__file__)),
        timeout=120,
    )
    assert p.returncode == 0
    assert "slope_samples" in p.stdout
    assert "cpu-fallback" in p.stdout
    assert "TMTPU_BENCH_FAULT" in p.stdout


def test_guarded_main_emits_fallback_on_dead_child(tmp_path, monkeypatch):
    bench = _bench()
    stub = tmp_path / "dead_bench.py"
    stub.write_text("import sys\nsys.exit(3)\n")
    monkeypatch.setattr(bench, "__file__", str(stub))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.guarded_main()
    rep = json.loads(buf.getvalue())
    assert rep["value"] == -1
    assert "rc=3" in rep["extra"]["error"]


def test_multichip_scenario_shape(monkeypatch):
    """ISSUE 11 satellite: the `multichip` scenario wires the fused
    single-chip AND sharded arms into one report (internals stubbed — the
    real kernels are device-round work; this pins the plumbing: slope
    samples attached, mesh telemetry attached, the ledger's `speedup`
    key present)."""
    import numpy as np

    bench = _bench()
    from tendermint_tpu.crypto import batch as B

    monkeypatch.setattr(bench, "time_rlc", lambda *a, **k: (0.5, 0.2, 0.01))
    monkeypatch.setattr(
        bench, "rlc_slope_samples", lambda *a, **k: ([[1, 0.1], [2, 0.2]], 100.0)
    )
    monkeypatch.setattr(
        bench, "make_batch",
        lambda n, **k: ([b"\x01" * 32] * n, [b"m"] * n, [b"\x02" * 64] * n,
                        ["ed25519"] * n),
    )
    monkeypatch.setattr(
        B, "verify_batch_jax",
        lambda pk, ms, sg: np.ones(len(pk), dtype=bool),
    )
    monkeypatch.setattr(B, "_sharded_env", lambda: (8, None, None))
    B.LAST_JAX_PATH[0] = "rlc-sharded"
    rep = bench.bench_multichip(n=64)
    assert rep["single_chip"]["rlc_e2e_ms"] == 200.0
    assert rep["single_chip"]["slope_samples"] == [[1, 0.1], [2, 0.2]]
    assert rep["sharded"]["n_devices"] == 8
    assert "mesh_telemetry" in rep["sharded"]
    assert rep["speedup"] > 0  # single-vs-sharded ratio (stub arms)
    assert rep["sigs_per_sec_sharded"] > 0
    assert os.environ.get("TMTPU_SHARDED") is None  # env restored
