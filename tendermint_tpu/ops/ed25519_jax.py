"""Batched Ed25519 verification on TPU (JAX).

The validator-axis hot loop of the whole framework: verifies N signatures at
once, replacing the reference's serial per-signature loop
(reference: types/validator_set.go:680-702, types/vote_set.go:203,
crypto/ed25519/ed25519.go:148).

Semantics: cofactorless verification — accept iff [s]B == R + [h]A exactly,
computed as enc([s]B + [h](-A)) == enc(R), with s < L enforced host-side —
the same equation golang.org/x/crypto/ed25519 checks. One (documented)
divergence: we reject public keys whose y coordinate is non-canonical (>= p),
which x/crypto accepts; honest keys are never affected (and non-canonical
keys are refused at validator ingestion, crypto/keys.py).

Layout: batch on the TRAILING axis everywhere (limbs/bytes/digits leading) so
the batch maps onto TPU vector lanes. Points are (X, Y, Z, T) extended twisted
Edwards coordinates; adds use the unified a=-1 formulas, so identity and
doubling need no special cases inside the scan.

The scalar multiplication is a joint windowed double-scalar ladder in signed
radix-16: scalars are recoded host-side into 64 digits in [-8, 8] (LSB-first
in memory, scanned MSB-first). Each scan step does 4 doublings, one mixed add
from a CONSTANT basepoint table (j*B in affine niels form, j=0..8, negation by
coordinate swap) and one unified add from the per-signature table j*(-A)
(j=0..8 extended points, built with 7 adds + 1 double before the scan). 64
steps of ~48 field muls replaces the round-1 design's 253 steps of ~17 — ~25%
fewer field muls and 4x fewer sequential scan iterations.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519_ref as _ref
from tendermint_tpu.crypto.ed25519_ref import BX as _BX, _BY
from tendermint_tpu.ops import fe25519 as fe

SCALAR_BITS = 253  # s, h < L < 2^253
NUM_DIGITS = 64  # signed radix-16 digits covering 256 bits
WINDOW = 8  # table holds j*P for j in 0..8; sign handled by negation


class Point(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def identity(batch_shape) -> Point:
    return Point(
        fe.const_fe(0, batch_shape),
        fe.const_fe(1, batch_shape),
        fe.const_fe(1, batch_shape),
        fe.const_fe(0, batch_shape),
    )


def basepoint(batch_shape) -> Point:
    return Point(
        fe.const_fe(_BX, batch_shape),
        fe.const_fe(_BY, batch_shape),
        fe.const_fe(1, batch_shape),
        fe.const_fe(_BX * _BY % fe.P, batch_shape),
    )


def point_add(p: Point, q: Point) -> Point:
    """Unified a=-1 extended addition (add-2008-hwcd-3): 8M + 1 const-mul."""
    a = fe.mul(fe.sub(p.y, p.x), fe.sub(q.y, q.x))
    b = fe.mul(fe.add(p.y, p.x), fe.add(q.y, q.x))
    c = fe.mul(fe.mul(p.t, q.t), fe.const_fe(fe.D2, p.t.shape[1:]))
    d = fe.mul_small(fe.mul(p.z, q.z), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_double(p: Point) -> Point:
    """dbl-2008-hwcd for a=-1: 4M + 4S (cheaper than unified add)."""
    xx = fe.square(p.x)  # A
    yy = fe.square(p.y)  # B
    zz2 = fe.mul_small(fe.square(p.z), 2)  # C
    xy2 = fe.square(fe.add(p.x, p.y))
    e = fe.sub(xy2, fe.add(xx, yy))  # E = (X+Y)^2 - A - B = 2XY
    g = fe.sub(yy, xx)  # G = D + B = B - A   (D = aA = -A)
    f = fe.sub(g, zz2)  # F = G - C
    h = fe.neg(fe.add(xx, yy))  # H = D - B = -(A + B)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_neg(p: Point) -> Point:
    return Point(fe.neg(p.x), p.y, p.z, fe.neg(p.t))


def point_select(cond: jnp.ndarray, a: Point, b: Point) -> Point:
    """cond ? a : b, cond shaped like the batch."""
    return Point(
        fe.select(cond, a.x, b.x),
        fe.select(cond, a.y, b.y),
        fe.select(cond, a.z, b.z),
        fe.select(cond, a.t, b.t),
    )


def decompress(s_bytes: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """uint8[32, ...batch] -> (Point, ok mask). RFC 8032 §5.1.3."""
    s_bytes = jnp.asarray(s_bytes)
    sign = (s_bytes[31] >> 7).astype(jnp.uint32)
    y = fe.from_bytes(s_bytes, mask_high_bit=True)
    canonical = fe.is_canonical_bytes(s_bytes)

    batch = y.shape[1:]
    one = fe.const_fe(1, batch)
    yy = fe.square(y)
    u = fe.sub(yy, one)
    v = fe.add(fe.mul(yy, fe.const_fe(fe.D, batch)), one)
    v3 = fe.mul(fe.square(v), v)
    v7 = fe.mul(fe.square(v3), v)
    t = fe.pow_p58(fe.mul(u, v7))
    x = fe.mul(fe.mul(u, v3), t)  # candidate sqrt(u/v)

    vxx = fe.mul(v, fe.square(x))
    ok_direct = fe.eq(vxx, u)
    ok_flipped = fe.eq(vxx, fe.neg(u))
    x = fe.select(ok_direct, x, fe.mul(x, fe.const_fe(fe.SQRT_M1, batch)))
    ok = canonical & (ok_direct | ok_flipped)

    x_frozen = fe.freeze(x)
    x_is_zero = fe.is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    flip = fe.bit(x_frozen, 0) != sign
    x = fe.select(flip, fe.neg(x), x)
    return Point(x, y, fe.const_fe(1, batch), fe.mul(x, y)), ok


def compress(p: Point) -> jnp.ndarray:
    """Point -> canonical encoding uint8[32, ...batch]."""
    zinv = fe.inv(p.z)
    x = fe.freeze(fe.mul(p.x, zinv))
    y = fe.mul(p.y, zinv)
    out = fe.to_bytes(y)
    sign = (fe.bit(x, 0) << jnp.uint32(7)).astype(jnp.uint8)
    return out.at[31].set(out[31] | sign)


def _basepoint_niels_table() -> np.ndarray:
    """Host precompute: j*B for j=0..8 in affine niels form (y+x, y-x, 2dxy),
    canonical limbs. Shape (9, 3, 20) uint32. Entry 0 is the identity (1,1,0),
    so digit 0 rides the same unified mixed-add formula."""
    tab = np.zeros((WINDOW + 1, 3, fe.NLIMBS), dtype=np.uint32)
    tab[0, 0] = fe.from_int(1)
    tab[0, 1] = fe.from_int(1)
    for j in range(1, WINDOW + 1):
        X, Y, Z, _T = _ref.point_mul(j, _ref.BASE)
        zinv = pow(Z, fe.P - 2, fe.P)
        x, y = X * zinv % fe.P, Y * zinv % fe.P
        tab[j, 0] = fe.from_int((y + x) % fe.P)
        tab[j, 1] = fe.from_int((y - x) % fe.P)
        tab[j, 2] = fe.from_int(2 * fe.D * x * y % fe.P)
    return tab


_B_NIELS = jnp.asarray(_basepoint_niels_table())  # (9, 3, 20)


def add_niels(p: Point, yplus: jnp.ndarray, yminus: jnp.ndarray, xy2d: jnp.ndarray) -> Point:
    """Mixed add of an affine niels point (7M): the unified a=-1 formula with
    Z2=1 and the (y2+x2, y2-x2, 2d*x2*y2) products precomputed."""
    a = fe.mul(fe.sub(p.y, p.x), yminus)
    b = fe.mul(fe.add(p.y, p.x), yplus)
    c = fe.mul(p.t, xy2d)
    d = fe.mul_small(p.z, 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _onehot(digit_mag: jnp.ndarray) -> jnp.ndarray:
    """int32[...batch] in [0,8] -> uint32[9, ...batch] one-hot."""
    idx = jnp.arange(WINDOW + 1, dtype=jnp.int32).reshape(
        (WINDOW + 1,) + (1,) * digit_mag.ndim
    )
    return (digit_mag[None] == idx).astype(jnp.uint32)


def _select_b_niels(digit: jnp.ndarray):
    """Signed select from the constant basepoint table. digit int32 in [-8,8]."""
    oh = _onehot(jnp.abs(digit))  # (9, ...batch)
    tab = _B_NIELS.reshape((WINDOW + 1, 3, fe.NLIMBS) + (1,) * digit.ndim)
    sel = jnp.sum(tab * oh[:, None, None], axis=0)  # (3, 20, ...batch)
    yplus, yminus, xy2d = sel[0], sel[1], sel[2]
    neg = digit < 0
    yplus2 = fe.select(neg, yminus, yplus)
    yminus2 = fe.select(neg, yplus, yminus)
    xy2d2 = fe.select(neg, fe.neg(xy2d), xy2d)
    return yplus2, yminus2, xy2d2


def _select_point_table(tx, ty, tz, tt, digit: jnp.ndarray) -> Point:
    """Signed select of an extended point from a per-batch table
    (9, 20, ...batch) per coordinate. Negation: x -> -x, t -> -t."""
    oh = _onehot(jnp.abs(digit))[:, None]  # (9, 1, ...batch)
    x = jnp.sum(tx * oh, axis=0)
    y = jnp.sum(ty * oh, axis=0)
    z = jnp.sum(tz * oh, axis=0)
    t = jnp.sum(tt * oh, axis=0)
    neg = digit < 0
    return Point(fe.select(neg, fe.neg(x), x), y, z, fe.select(neg, fe.neg(t), t))


@jax.jit
def verify_prepared(
    a_bytes: jnp.ndarray,  # uint8[32, ...batch] public keys
    r_bytes: jnp.ndarray,  # uint8[32, ...batch] signature R
    s_digits: jnp.ndarray,  # int8[64, ...batch] signed radix-16 digits of s, LSB-first
    h_digits: jnp.ndarray,  # int8[64, ...batch] digits of SHA512(R||A||M) mod L
) -> jnp.ndarray:
    """Core batched check: enc([s]B + [h](-A)) == enc(R). Returns bool[...batch]."""
    a_bytes = jnp.asarray(a_bytes)
    r_bytes = jnp.asarray(r_bytes)
    s_digits = jnp.asarray(s_digits, dtype=jnp.int8).astype(jnp.int32)
    h_digits = jnp.asarray(h_digits, dtype=jnp.int8).astype(jnp.int32)
    batch = a_bytes.shape[1:]

    neg_a, ok_a = decompress(a_bytes)
    neg_a = point_neg(neg_a)

    # Per-signature table: j*(-A) for j=0..8 (identity, -A, 2(-A), ..., 8(-A)).
    entries = [identity(batch), neg_a]
    dbl2 = point_double(neg_a)
    entries.append(dbl2)
    for _ in range(3, WINDOW + 1):
        entries.append(point_add(entries[-1], neg_a))
    ta_x = jnp.stack([e.x for e in entries])  # (9, 20, ...batch)
    ta_y = jnp.stack([e.y for e in entries])
    ta_z = jnp.stack([e.z for e in entries])
    ta_t = jnp.stack([e.t for e in entries])

    # MSB-first scan over digit pairs.
    xs = jnp.stack([s_digits[::-1], h_digits[::-1]], axis=1)  # (64, 2, ...batch)

    def step(acc: Point, dd):
        ds, dh = dd[0], dd[1]
        acc = point_double(point_double(point_double(point_double(acc))))
        acc = add_niels(acc, *_select_b_niels(ds))
        acc = point_add(acc, _select_point_table(ta_x, ta_y, ta_z, ta_t, dh))
        return acc, None

    acc, _ = jax.lax.scan(step, identity(batch), xs)
    enc = compress(acc)
    return ok_a & jnp.all(enc == r_bytes, axis=0)
