"""Snapshot pool: dedup + peer tracking + ranking of advertised snapshots.

reference: statesync/snapshots.go — snapshotKey (:23), Snapshot (:29),
snapshotPool (:55), Add (:78), Best/Ranked (:161,169), Reject* (:183-219).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tendermint_tpu.crypto import tmhash


@dataclass(frozen=True)
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""
    trusted_app_hash: bytes = b""  # filled in by the syncer, not advertised

    def key(self) -> bytes:
        """Unique id over (height, format, chunks, hash, metadata)
        (reference: statesync/snapshots.go:44 Key)."""
        w = bytearray()
        w += self.height.to_bytes(8, "big")
        w += self.format.to_bytes(4, "big")
        w += self.chunks.to_bytes(4, "big")
        w += self.hash
        w += self.metadata
        return tmhash.sum_truncated(bytes(w))


class SnapshotPool:
    """reference: statesync/snapshots.go:55."""

    def __init__(self):
        self._snapshots: Dict[bytes, Snapshot] = {}
        self._peers: Dict[bytes, Set[str]] = {}  # key -> peer ids
        self._rejected_snapshots: Set[bytes] = set()
        self._rejected_formats: Set[int] = set()
        self._rejected_peers: Set[str] = set()

    def add(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Returns True if this snapshot is new (reference: :78 Add)."""
        key = snapshot.key()
        if key in self._rejected_snapshots or snapshot.format in self._rejected_formats:
            return False
        if peer_id in self._rejected_peers:
            return False
        self._peers.setdefault(key, set()).add(peer_id)
        if key in self._snapshots:
            return False
        self._snapshots[key] = snapshot
        return True

    def best(self) -> Optional[Snapshot]:
        ranked = self.ranked()
        return ranked[0] if ranked else None

    def ranked(self) -> List[Snapshot]:
        """Order: height desc, format desc, more peers first
        (reference: :169 Ranked)."""
        return sorted(
            self._snapshots.values(),
            key=lambda s: (-s.height, -s.format, -len(self._peers.get(s.key(), ()))),
        )

    def get_peers(self, snapshot: Snapshot) -> List[str]:
        return sorted(self._peers.get(snapshot.key(), ()))

    def reject(self, snapshot: Snapshot) -> None:
        key = snapshot.key()
        self._rejected_snapshots.add(key)
        self._snapshots.pop(key, None)
        self._peers.pop(key, None)

    def reject_format(self, fmt: int) -> None:
        self._rejected_formats.add(fmt)
        for key, s in list(self._snapshots.items()):
            if s.format == fmt:
                self._snapshots.pop(key, None)
                self._peers.pop(key, None)

    def reject_peer(self, peer_id: str) -> None:
        self._rejected_peers.add(peer_id)
        self.remove_peer(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        for key in list(self._peers):
            self._peers[key].discard(peer_id)
            if not self._peers[key]:
                # no peer can serve it any more
                self._peers.pop(key, None)
                self._snapshots.pop(key, None)

    def __len__(self) -> int:
        return len(self._snapshots)
