"""Consensus write-ahead log (reference: consensus/wal.go).

Every message (peer msg, internal msg, timeout) is written before processing;
self-generated messages are fsynced (WriteSync). Framing: crc32(IEEE) ‖
length ‖ protobuf body (reference: consensus/wal.go:290 WALEncoder), with
rotating files via a size-capped group (reference: libs/autofile/group.go).
EndHeightMessage marks a completed height for crash replay
(reference: consensus/wal.go:42,231)."""

from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple, Union

from tendermint_tpu.consensus.messages import decode_message, encode_message
from tendermint_tpu.libs import hotstats as _hotstats
from tendermint_tpu.libs import protowire as pw

MAX_MSG_SIZE_BYTES = 1024 * 1024  # 1MB (reference: consensus/wal.go:32)
DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # autofile group head limit
DEFAULT_GROUP_TOTAL_LIMIT = 1024 * 1024 * 1024


@dataclass(frozen=True)
class EndHeightMessage:
    height: int


@dataclass(frozen=True)
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int


@dataclass(frozen=True)
class MsgInfo:
    msg: object  # a consensus message
    peer_id: str = ""


@dataclass(frozen=True)
class EventRoundState:
    height: int
    round: int
    step: int


WALMessage = Union[EndHeightMessage, TimeoutInfo, MsgInfo, EventRoundState]


# Precomputed tags for the flattened MsgInfo fast path below (byte-identical
# to the Writer-built form; pinned by test_wal_repair round-trips and the
# group-commit byte-identity test).
_TAG_PEER = pw.tag(1, pw.BYTES)
_TAG_INNER = pw.tag(2, pw.BYTES)
_TAG_MSGINFO = pw.tag(3, pw.BYTES)


def _encode_wal_message(msg: WALMessage) -> bytes:
    if isinstance(msg, MsgInfo):
        # The hot variant (one per gossiped vote): assemble with precomputed
        # tags and direct concat — three nested Writer objects per vote were
        # a measurable slice of the receive loop's WAL cost.
        enc = pw.encode_varint
        inner = encode_message(msg.msg)
        peer = msg.peer_id.encode()
        body = (
            (_TAG_PEER + enc(len(peer)) + peer if peer else b"")
            + _TAG_INNER + enc(len(inner)) + inner
        )
        return _TAG_MSGINFO + enc(len(body)) + body
    w = pw.Writer()
    if isinstance(msg, EndHeightMessage):
        w.varint_field(1, msg.height, emit_zero=True)
    elif isinstance(msg, TimeoutInfo):
        body = pw.Writer()
        body.varint_field(1, int(msg.duration_s * 1e9))
        body.varint_field(2, msg.height)
        body.varint_field(3, msg.round)
        body.varint_field(4, msg.step)
        w.message_field(2, body.bytes(), always=True)
    elif isinstance(msg, EventRoundState):
        body = pw.Writer()
        body.varint_field(1, msg.height)
        body.varint_field(2, msg.round)
        body.varint_field(3, msg.step)
        w.message_field(4, body.bytes(), always=True)
    else:
        raise TypeError(f"unknown WAL message {type(msg)}")
    return w.bytes()


def _decode_wal_message(data: bytes) -> WALMessage:
    for f, _, v in pw.Reader(data):
        if f == 1:
            return EndHeightMessage(pw.int64_from_varint(v))
        if f == 2:
            vals = [0, 0, 0, 0]
            for ff, _, vv in pw.Reader(v):
                if 1 <= ff <= 4:
                    vals[ff - 1] = pw.int64_from_varint(vv)
            return TimeoutInfo(vals[0] / 1e9, vals[1], vals[2], vals[3])
        if f == 3:
            peer = ""
            inner = None
            for ff, _, vv in pw.Reader(v):
                if ff == 1:
                    peer = vv.decode()
                elif ff == 2:
                    inner = decode_message(vv)
            return MsgInfo(inner, peer)
        if f == 4:
            vals = [0, 0, 0]
            for ff, _, vv in pw.Reader(v):
                if 1 <= ff <= 3:
                    vals[ff - 1] = pw.int64_from_varint(vv)
            return EventRoundState(*vals)
    raise ValueError("empty WAL message")


class CorruptedWALError(Exception):
    pass


def wal_files(path: str) -> List[str]:
    """All files of a rotated WAL group, oldest first (….000, …, head)."""
    files = []
    idx = 0
    while os.path.exists(f"{path}.{idx:03d}"):
        files.append(f"{path}.{idx:03d}")
        idx += 1
    if os.path.exists(path):
        files.append(path)
    return files


def iter_wal_messages(path: str, strict: bool = False) -> Iterator[WALMessage]:
    """Decode all messages across a WAL group WITHOUT opening it for append
    (the WAL class constructor writes an EndHeight(0) anchor into fresh
    files — a read-only consumer like tools/wal_inspect.py must never do
    that to a post-mortem artifact). Non-strict mode stops at the first
    corrupted frame (torn write at crash)."""
    for fname in wal_files(path):
        with open(fname, "rb") as f:
            data = f.read()
        pos = 0
        while pos < len(data):
            if pos + 8 > len(data):
                if strict:
                    raise CorruptedWALError("truncated frame header")
                return
            crc, length = struct.unpack_from(">II", data, pos)
            if length > MAX_MSG_SIZE_BYTES:
                if strict:
                    raise CorruptedWALError("frame too large")
                return
            if pos + 8 + length > len(data):
                if strict:
                    raise CorruptedWALError("truncated frame body")
                return
            body = data[pos + 8 : pos + 8 + length]
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                if strict:
                    raise CorruptedWALError("crc mismatch")
                return
            try:
                yield _decode_wal_message(body)
            except ValueError:
                if strict:
                    raise CorruptedWALError("undecodable message")
                return
            pos += 8 + length


class WAL:
    """Size-rotated WAL. Files: <path>, <path>.000, <path>.001 … (rotated
    heads); head is always <path>.

    Group-commit mode (`group_commit=True`): `write()` appends frames to an
    in-memory buffer instead of the file; `flush_buffered()` lands the whole
    buffer as ONE buffered file write. The consensus receive loop calls it
    once per queue drain, so a 512-vote storm batch pays one write syscall
    instead of 512 write+tell round trips (the LMAX/Aurora-style write
    coalescing — CometBFT's v0.38 vote-extension work hit the same per-vote
    wall; note BufferedWriter.tell() in append mode forces a flush, so the
    old per-message `write()` was a hidden syscall per vote).

    fsync policy: `group_commit_max_latency` bounds the AGE of any
    un-fsynced write — a drain whose oldest pending byte has aged past the
    bound fsyncs; younger data rides until a later drain, write_sync, or
    close. On a storm cadence (drains spaced wider than the bound) that is
    exactly one buffered write + one fsync per drain; on dense drains the
    fsyncs coalesce further. The reference's WAL is looser still — plain
    Write never fsyncs and durability comes from a 2s flush ticker
    (reference: consensus/wal.go flushAndSyncTicker). Against MACHINE
    crashes the aged fsync strictly improves on the pre-batching writer
    (which never fsynced peer messages); against a hard PROCESS kill the
    in-process buffer can lose up to one drain of peer frames that the old
    per-message write would have left in the OS page cache — a replay-
    completeness window (bounded by the drain size and the latency bound),
    never a safety one, since self-generated messages fsync inline.

    Remaining semantics are PRESERVED relative to the non-batched writer:
    - `write_sync()` (self-generated messages, EndHeight markers) flushes
      any buffered frames first — ordering is exact — and fsyncs before
      returning, so a self-generated message is never processed un-durably.
    - frames are CRC-framed, so a crash mid-flush tears at a frame boundary
      at worst — replay recovers the clean prefix exactly as before.
    """

    def __init__(
        self,
        path: str,
        head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
        total_size_limit: int = DEFAULT_GROUP_TOTAL_LIMIT,
        group_commit: bool = False,
        group_commit_max_latency: float = 0.02,
    ):
        self.path = path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self.group_commit = group_commit
        self.group_commit_max_latency = group_commit_max_latency
        self._buf = bytearray()  # frames awaiting the next flush (group mode)
        # perf_counter of the OLDEST write not yet fsynced (buffered in
        # memory or sitting in OS cache) — drives the max-latency bound
        self._dirty_since: Optional[float] = None
        # instrumentation for the no-redundant-work guard + bench breakdown
        self.fsync_count = 0
        self.write_calls = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self._fh = open(path, "ab")
        self._flushed = True
        if fresh and len(self._all_files()) <= 1:
            # Empty WAL: mark "height 0 done" so catchup replay after a crash
            # mid-height-1 finds its search anchor (reference: consensus/wal.go
            # OnStart writes EndHeightMessage{0} into an empty group).
            self.write_end_height(0)

    # -- writing ------------------------------------------------------------

    def _frame(self, msg: WALMessage) -> bytes:
        body = _encode_wal_message(msg)
        if len(body) > MAX_MSG_SIZE_BYTES:
            raise ValueError(f"msg is too big: {len(body)} bytes")
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return struct.pack(">II", crc, len(body)) + body

    def write(self, msg: WALMessage) -> None:
        """(reference: consensus/wal.go:184 Write — async, no fsync)"""
        hs = _hotstats.stats if _hotstats.stats.enabled else None
        if hs is None:
            return self._write(msg)
        t0 = _hotstats.perf_counter()
        self._write(msg)
        hs.add("wal", _hotstats.perf_counter() - t0)

    def _write(self, msg: WALMessage) -> None:
        self.write_calls += 1
        frame = self._frame(msg)
        if self.group_commit:
            now = time.perf_counter()
            if self._dirty_since is None:
                self._dirty_since = now
            self._buf += frame
            # bound both staleness and memory: aged un-synced data or an
            # oversized buffer flushes inline instead of waiting for the
            # drain boundary
            if (
                now - self._dirty_since > self.group_commit_max_latency
                or len(self._buf) >= self.head_size_limit
            ):
                # untimed variant: write()'s own hotstats wrapper already
                # covers this inline flush — the timed public method here
                # would double-count the flush into the 'wal' stage
                self._flush_buffered()
            return
        self._fh.write(frame)
        self._flushed = False
        self._maybe_rotate()

    def write_sync(self, msg: WALMessage) -> None:
        """(reference: consensus/wal.go:201 WriteSync — fsync before returning).
        In group-commit mode any buffered frames land first (exact ordering),
        in the same write+fsync."""
        hs = _hotstats.stats if _hotstats.stats.enabled else None
        t0 = _hotstats.perf_counter() if hs is not None else 0.0
        self.write_calls += 1
        frame = self._frame(msg)
        if self.group_commit:
            self._buf += frame
        else:
            self._fh.write(frame)
        self.flush_and_sync()
        self._maybe_rotate()
        if hs is not None:
            hs.add("wal", _hotstats.perf_counter() - t0)

    def flush_buffered(self) -> None:
        """Group-commit boundary (called once per receive-loop queue drain):
        land all buffered frames in ONE buffered write, and fsync iff the
        oldest un-synced write has aged past the max-latency bound. No-op
        when nothing is pending (so callers can invoke it unconditionally
        per queue drain, in either mode)."""
        if self._dirty_since is None and not self._buf:
            return
        hs = _hotstats.stats if _hotstats.stats.enabled else None
        if hs is None:
            return self._flush_buffered()
        t0 = _hotstats.perf_counter()
        self._flush_buffered()
        hs.add("wal", _hotstats.perf_counter() - t0, n=0)

    def _flush_buffered(self) -> None:
        if (
            self._dirty_since is not None
            and time.perf_counter() - self._dirty_since >= self.group_commit_max_latency
        ):
            self.flush_and_sync()
        else:
            self._drain_buffer()
            self._fh.flush()
        self._maybe_rotate()

    def _drain_buffer(self) -> None:
        if self._buf:
            self._fh.write(self._buf)
            del self._buf[:]
            self._flushed = False

    def flush_and_sync(self) -> None:
        self._drain_buffer()
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self.fsync_count += 1
        self._dirty_since = None
        self._flushed = True

    def write_end_height(self, height: int) -> None:
        self.write_sync(EndHeightMessage(height))

    def _maybe_rotate(self) -> None:
        if self._fh.tell() < self.head_size_limit:
            return
        self.flush_and_sync()
        self._fh.close()
        # shift: find next rotation index
        idx = 0
        while os.path.exists(f"{self.path}.{idx:03d}"):
            idx += 1
        os.replace(self.path, f"{self.path}.{idx:03d}")
        self._fh = open(self.path, "ab")
        self._enforce_total_limit(idx)

    def _enforce_total_limit(self, latest_idx: int) -> None:
        files = [f"{self.path}.{i:03d}" for i in range(latest_idx + 1)]
        files = [f for f in files if os.path.exists(f)]
        total = sum(os.path.getsize(f) for f in files)
        for f in files:
            if total <= self.total_size_limit:
                break
            total -= os.path.getsize(f)
            os.unlink(f)

    def close(self) -> None:
        try:
            self.flush_and_sync()
        finally:
            self._fh.close()

    # -- reading ------------------------------------------------------------

    def _all_files(self) -> List[str]:
        return wal_files(self.path)

    def iter_messages(self, strict: bool = False) -> Iterator[WALMessage]:
        """Decode all messages across rotated files. Non-strict mode stops at
        the first corrupted frame (torn write at crash). Frames still in the
        group-commit buffer are written through first (no fsync — reading
        back our own writes needs file content, not durability)."""
        self._drain_buffer()
        self._fh.flush()
        yield from iter_wal_messages(self.path, strict=strict)

    def search_for_end_height(self, height: int) -> Optional[List[WALMessage]]:
        """Returns messages AFTER EndHeightMessage(height), or None if the
        marker is absent (reference: consensus/wal.go:231)."""
        found = False
        out: List[WALMessage] = []
        for msg in self.iter_messages():
            if isinstance(msg, EndHeightMessage) and msg.height == height:
                found = True
                out = []
                continue
            if found:
                out.append(msg)
        return out if found else None
