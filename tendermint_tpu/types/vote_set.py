"""VoteSet: tallies votes of one (height, round, type) (reference: types/vote_set.go).

Tracks one canonical vote per validator, per-block power sums, 2/3 majority
detection, conflict detection (→ DuplicateVoteEvidence material) and
peer-claimed majorities (used by the consensus reactor's VoteSetBits gossip).
The add path mirrors the reference's addVerifiedVote exactly
(reference: types/vote_set.go:229-290): a conflicting vote is still tracked
under its block key when a peer claims that block has 2/3, and the canonical
vote is replaced when the conflict is FOR the established maj23 block.

Signature verification: votes are verified on arrival through the host path by
default; `defer_verification=True` accumulates unverified votes and `flush()`
batch-verifies them on the TPU in one kernel call — the mode the consensus
vote-storm path uses (north star: SURVEY.md §3.3). Conflicts discovered during
flush are queued and retrievable via pop_conflicts().
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from tendermint_tpu.crypto.batch import verify_batch
from tendermint_tpu.libs import hotstats
from tendermint_tpu.types.basic import BlockID, SignedMsgType
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote


class VoteSetError(Exception):
    pass


class ConflictingVotesError(VoteSetError):
    def __init__(self, vote_a: Vote, vote_b: Vote):
        super().__init__("conflicting votes from validator")
        self.vote_a = vote_a  # existing
        self.vote_b = vote_b  # new


@dataclass
class _BlockVotes:
    peer_maj23: bool
    votes: List[Optional[Vote]]
    sum: int = 0

    def add_verified(self, idx: int, vote: Vote, power: int) -> None:
        if self.votes[idx] is None:
            self.votes[idx] = vote
            self.sum += power

    def get_by_index(self, idx: int) -> Optional[Vote]:
        return self.votes[idx]


class VoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: SignedMsgType,
        val_set: ValidatorSet,
        defer_verification: bool = False,
    ):
        if height == 0:
            raise ValueError("cannot make VoteSet for height == 0")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self.defer_verification = defer_verification

        n = val_set.size()
        self._votes: List[Optional[Vote]] = [None] * n
        self._votes_bit_array: List[bool] = [False] * n
        self._sum = 0
        self._maj23: Optional[BlockID] = None
        self._votes_by_block: Dict[bytes, _BlockVotes] = {}
        self._peer_maj23s: Dict[str, BlockID] = {}
        # deferred-verification queue: (idx, vote, validator, peer_id)
        self._pending: List[tuple] = []
        self._pending_seen: Set[Tuple[int, bytes, bytes]] = set()
        self._conflicts: List[ConflictingVotesError] = []

    # -- introspection ------------------------------------------------------

    def size(self) -> int:
        return self.val_set.size()

    def bit_array(self) -> List[bool]:
        return list(self._votes_bit_array)

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[List[bool]]:
        bv = self._votes_by_block.get(block_id.key())
        if bv is None:
            return None
        return [v is not None for v in bv.votes]

    def get_by_index(self, idx: int) -> Optional[Vote]:
        return self._votes[idx]

    def get_by_address(self, address: bytes) -> Optional[Vote]:
        idx, _ = self.val_set.get_by_address(address)
        return self._votes[idx] if idx >= 0 else None

    def list_votes(self) -> List[Vote]:
        return [v for v in self._votes if v is not None]

    def has_two_thirds_majority(self) -> bool:
        return self._maj23 is not None

    def two_thirds_majority(self) -> Optional[BlockID]:
        return self._maj23

    def has_two_thirds_any(self) -> bool:
        return self._sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        return self._sum == self.val_set.total_voting_power()

    def sum_power(self) -> int:
        return self._sum

    def pop_conflicts(self) -> List[ConflictingVotesError]:
        out, self._conflicts = self._conflicts, []
        return out

    def pending_count(self) -> int:
        """Number of deferred (accepted-but-unverified) votes awaiting flush()."""
        return len(self._pending)

    # -- adding votes -------------------------------------------------------

    def _get_vote(self, idx: int, block_key: bytes) -> Optional[Vote]:
        """Existing vote by idx for this block key, canonical or conflict-tracked
        (reference: types/vote_set.go getVote)."""
        existing = self._votes[idx]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        bv = self._votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(idx)
        return None

    def add_vote(self, vote: Vote, peer_id: str = ""):
        """Returns a truthy value if the vote was newly accepted: True when
        verified-and-committed, the string "pending" when queued for
        deferred batch verification (NOT yet verified — callers must not
        gossip/advertise it until flush() commits it). Raises VoteSetError
        on invalid votes and ConflictingVotesError on equivocation
        (reference: types/vote_set.go:143-290).

        peer_id: the gossiping peer, when known — deferred votes carry it
        as row provenance (crypto/provenance.py "peer:<id>" tags) so a
        peer whose votes fail batch verification gets quarantined and
        punished instead of poisoning every later vote flush; "" means a
        locally originated/replayed vote."""
        if vote is None:
            raise VoteSetError("nil vote")
        idx = vote.validator_index
        if idx < 0:
            raise VoteSetError("index < 0")
        if not vote.signature:
            raise VoteSetError("no signature")
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type != self.signed_msg_type
        ):
            raise VoteSetError(
                f"expected {self.height}/{self.round}/{self.signed_msg_type}, got "
                f"{vote.height}/{vote.round}/{vote.type}"
            )
        addr, val = self.val_set.get_by_index(idx)
        if val is None:
            raise VoteSetError(f"cannot find validator {idx} in valSet of size {self.size()}")
        if addr != vote.validator_address:
            raise VoteSetError("validator address does not match index")

        block_key = vote.block_id.key()
        existing = self._get_vote(idx, block_key)
        if existing is not None:
            if existing.signature == vote.signature:
                return False  # duplicate
            raise VoteSetError("non-deterministic signature for the same block")

        if self.defer_verification:
            seen_key = (idx, block_key, vote.signature)
            if seen_key in self._pending_seen:
                return False
            self._pending_seen.add(seen_key)
            # carry the resolved Validator so flush() skips a second
            # get_by_index per vote, and the gossiping peer for provenance
            self._pending.append((idx, vote, val, peer_id))
            return "pending"

        if not self._verify_now(vote, val.pub_key):
            raise VoteSetError(f"invalid signature from validator {idx}")
        added, conflicting = self._add_verified(idx, vote, val.voting_power, block_key)
        if conflicting is not None:
            raise ConflictingVotesError(conflicting, vote)
        return added

    def _verify_now(self, vote: Vote, pub_key) -> bool:
        hs = hotstats.stats if hotstats.stats.enabled else None
        if hs is None:
            return pub_key.verify(vote.sign_bytes(self.chain_id), vote.signature)
        msg = vote.sign_bytes(self.chain_id)  # counted under "encode" by the memo
        t0 = hotstats.perf_counter()
        ok = pub_key.verify(msg, vote.signature)
        hs.add("verify", hotstats.perf_counter() - t0)
        return ok

    def flush(self) -> Tuple[List[Vote], List[int]]:
        """Batch-verify all deferred votes in one device call; commits the
        valid ones through the same conflict-aware path as add_vote. Returns
        (committed votes — safe to publish/gossip now, indices of votes that
        FAILED verification); conflicts discovered are available via
        pop_conflicts()."""
        if not self._pending:
            return [], []
        from tendermint_tpu.types import canonical

        pubkeys, sigs, key_types, sources = [], [], [], []
        for _idx, vote, val, peer_id in self._pending:
            pubkeys.append(val.pub_key.bytes())
            sigs.append(vote.signature)
            key_types.append(val.pub_key.type_name())
            sources.append(f"peer:{peer_id}" if peer_id else "lane:votes")
        # One batched sign-bytes pass (shared type/height/round/chain_id;
        # profiled: the per-vote builder was 72% of flush time).
        msgs = canonical.vote_sign_bytes_many(
            self.chain_id,
            self.signed_msg_type,
            self.height,
            self.round,
            ((vote.block_id, vote.timestamp_ns) for _, vote, _, _ in self._pending),
        )
        # key_types matters: in a mixed validator set an sr25519 vote
        # verified under ed25519 rules always fails (marker bit forces
        # s >= L) — dropping valid votes on the deferred path would be a
        # liveness break (mirrors validator_set.py batched Verify*).
        hs = hotstats.stats if hotstats.stats.enabled else None
        if hs is not None:
            t0 = hotstats.perf_counter()
        # Global verification scheduler (crypto/scheduler.py): the deferred
        # vote flush rides the VOTES lane — it PREEMPTS queued bulk work
        # (light/admission/catch-up rows never inflate a vote flush's wall)
        # and its verdicts are byte-identical to the direct call (the
        # combined flush recovers the exact per-row mask). Process-global
        # default (last node wins, the tracer model): VoteSet has no wiring
        # path from the Node; with no scheduler the direct path is
        # unchanged.
        from tendermint_tpu.crypto import scheduler as _scheduler

        sched = _scheduler.default_scheduler()
        if sched is not None:
            mask = sched.verify_rows("votes", pubkeys, msgs, sigs, key_types,
                                     sources)
        else:
            mask = verify_batch(pubkeys, msgs, sigs, key_types=key_types,
                                sources=sources)
        if hs is not None:
            hs.add("verify", hotstats.perf_counter() - t0, n=len(pubkeys))
        committed = []
        failed = []
        for ok, (idx, vote, val, _peer) in zip(mask, self._pending):
            if not ok:
                failed.append(idx)
                continue
            block_key = vote.block_id.key()
            # Re-check: an earlier pending vote may have committed already.
            if self._get_vote(idx, block_key) is not None:
                continue
            added, conflicting = self._add_verified(idx, vote, val.voting_power, block_key)
            if added:
                committed.append(vote)
            if conflicting is not None:
                self._conflicts.append(ConflictingVotesError(conflicting, vote))
        self._pending.clear()
        self._pending_seen.clear()
        return committed, failed

    def _add_verified(
        self, idx: int, vote: Vote, power: int, block_key: Optional[bytes] = None
    ) -> Tuple[bool, Optional[Vote]]:
        """Mirror of reference addVerifiedVote (types/vote_set.go:229-290).
        Assumes the signature is already verified. `block_key` is accepted
        from callers that already computed it (the add path computes it for
        duplicate detection; recomputing here was measurable under storms)."""
        if block_key is None:
            block_key = vote.block_id.key()
        conflicting: Optional[Vote] = None

        existing = self._votes[idx]
        if existing is not None:
            conflicting = existing
            # Replace the canonical vote if the new one is for the maj23 block.
            if self._maj23 is not None and self._maj23.key() == block_key:
                self._votes[idx] = vote
                self._votes_bit_array[idx] = True
            # sum is NOT incremented for a replacement
        else:
            self._votes[idx] = vote
            self._votes_bit_array[idx] = True
            self._sum += power

        bv = self._votes_by_block.get(block_key)
        if bv is not None:
            if conflicting is not None and not bv.peer_maj23:
                return False, conflicting
        else:
            if conflicting is not None:
                return False, conflicting
            bv = _BlockVotes(peer_maj23=False, votes=[None] * self.size())
            self._votes_by_block[block_key] = bv

        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        orig_sum = bv.sum
        bv.add_verified(idx, vote, power)
        if orig_sum < quorum <= bv.sum and self._maj23 is None:
            self._maj23 = vote.block_id
            # Promote all votes under this block to canonical.
            for i, bvote in enumerate(bv.votes):
                if bvote is not None:
                    self._votes[i] = bvote
                    self._votes_bit_array[i] = True
        return True, conflicting

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """Record a peer's claim that a block has 2/3 (reference:
        types/vote_set.go:291-330)."""
        existing = self._peer_maj23s.get(peer_id)
        if existing is not None and existing != block_id:
            raise VoteSetError(f"setPeerMaj23: conflicting blockID from peer {peer_id}")
        self._peer_maj23s[peer_id] = block_id
        key = block_id.key()
        bv = self._votes_by_block.get(key)
        if bv is None:
            bv = _BlockVotes(peer_maj23=True, votes=[None] * self.size())
            self._votes_by_block[key] = bv
        else:
            bv.peer_maj23 = True

    def make_commit(self):
        """Build a Commit from 2/3 precommits for a block
        (reference: types/vote_set.go:578-602 MakeCommit)."""
        from tendermint_tpu.types.block import Commit, CommitSig
        from tendermint_tpu.types.basic import BlockIDFlag

        if self.signed_msg_type != SignedMsgType.PRECOMMIT:
            raise VoteSetError("cannot MakeCommit() unless VoteSet.Type is PRECOMMIT")
        if self._maj23 is None:
            raise VoteSetError("cannot MakeCommit() unless a blockhash has +2/3")
        sigs = []
        for vote in self._votes:
            if vote is None:
                sigs.append(CommitSig.absent_sig())
            elif vote.block_id == self._maj23:
                sigs.append(
                    CommitSig(
                        BlockIDFlag.COMMIT,
                        vote.validator_address,
                        vote.timestamp_ns,
                        vote.signature,
                    )
                )
            elif vote.block_id.is_zero():
                sigs.append(
                    CommitSig(
                        BlockIDFlag.NIL,
                        vote.validator_address,
                        vote.timestamp_ns,
                        vote.signature,
                    )
                )
            else:
                # Vote for a different block: counted as absent in the commit.
                sigs.append(CommitSig.absent_sig())
        return Commit(self.height, self.round, self._maj23, tuple(sigs))
