"""Utility libs: service lifecycle, log filtering, amino JSON, fuzz conn
(reference models: libs/service/service_test.go, libs/log/filter_test.go,
libs/json tests, p2p/fuzz.go)."""

import asyncio
import logging
import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tests.conftest import requires_cryptography

from tendermint_tpu.libs import amino_json
from tendermint_tpu.libs import log as tmlog
from tendermint_tpu.libs.service import (
    AlreadyStartedError,
    BaseService,
    ServiceError,
)


class Counting(BaseService):
    def __init__(self):
        super().__init__("counting")
        self.starts = 0
        self.stops = 0

    async def on_start(self):
        self.starts += 1

    async def on_stop(self):
        self.stops += 1


def test_service_lifecycle():
    async def go():
        s = Counting()
        assert not s.is_running()
        await s.start()
        assert s.is_running()
        with pytest.raises(AlreadyStartedError):
            await s.start()

        waiter = asyncio.create_task(s.wait_stopped())
        await asyncio.sleep(0)
        assert not waiter.done()
        await s.stop()
        await asyncio.wait_for(waiter, 1)
        assert not s.is_running()
        await s.stop()  # idempotent
        assert s.stops == 1

        # restart only after reset
        await s.reset()
        await s.start()
        assert s.starts == 2
        # reset while running is illegal
        with pytest.raises(ServiceError):
            await s.reset()
        await s.stop()

    asyncio.run(go())


def test_log_level_spec_parsing_and_setup():
    levels = tmlog.parse_level_spec("consensus:debug,p2p:none,*:error")
    assert levels["consensus"] == logging.DEBUG
    assert levels["p2p"] > logging.CRITICAL
    assert levels["*"] == logging.ERROR

    assert tmlog.parse_level_spec("info")["*"] == logging.INFO
    with pytest.raises(ValueError):
        tmlog.parse_level_spec("bogus")

    tmlog.setup("consensus:debug,*:error")
    assert logging.getLogger("tendermint_tpu.consensus").isEnabledFor(logging.DEBUG)
    assert not logging.getLogger("tendermint_tpu").isEnabledFor(logging.INFO)
    tmlog.setup("info")  # restore


def test_amino_json_roundtrip_and_errors():
    from tendermint_tpu.crypto.keys import Ed25519PubKey, gen_ed25519

    priv = gen_ed25519(b"\x21" * 32)
    pub = priv.pub_key()
    s = amino_json.marshal(pub)
    assert '"tendermint/PubKeyEd25519"' in s
    back = amino_json.unmarshal(s)
    assert isinstance(back, Ed25519PubKey)
    assert back.bytes() == pub.bytes()

    with pytest.raises(amino_json.UnregisteredTypeError):
        amino_json.marshal(object())
    with pytest.raises(amino_json.UnregisteredTypeError):
        amino_json.unmarshal('{"type": "nope", "value": 1}')
    with pytest.raises(ValueError):
        amino_json.unmarshal('[1, 2]')


@requires_cryptography
def test_fuzzed_connection_drops_writes():
    import random

    from tendermint_tpu.p2p.fuzz import FuzzConfig, FuzzedConnection

    class Sink:
        def __init__(self):
            self.writes = []
            self.closed = False

        async def write(self, data):
            self.writes.append(data)

        async def read(self, n):
            return b"\x00" * n

        def close(self):
            self.closed = True

    async def go():
        sink = Sink()
        fz = FuzzedConnection(
            sink,
            FuzzConfig(mode="drop", prob_drop_rw=0.5, start_after=0.0),
            rng=random.Random(7),
        )
        for i in range(100):
            await fz.write(b"%d" % i)
        assert 10 < len(sink.writes) < 90  # some dropped, some through
        fz.close()
        assert sink.closed

    asyncio.run(go())


@requires_cryptography
def test_debug_dump_cli(tmp_path, capsys):
    from tendermint_tpu.cli.main import init_files, main

    home = str(tmp_path / "h")
    init_files(home, chain_id="dbg")
    capsys.readouterr()
    out_zip = str(tmp_path / "dump.zip")
    assert main(["--home", home, "debug", "--output", out_zip]) == 0
    capsys.readouterr()
    import zipfile

    with zipfile.ZipFile(out_zip) as z:
        names = z.namelist()
    assert "config/config.toml" in names
    assert "config/genesis.json" in names


@requires_cryptography
def test_behaviour_reporter_and_trust_metric():
    """Bad conduct decays trust and eventually disconnects the peer
    (reference models: behaviour/reporter.go, p2p/trust/metric_test.go)."""
    from tendermint_tpu.p2p.behaviour import (
        BAD_MESSAGE,
        CONSENSUS_VOTE,
        PeerBehaviour,
        Reporter,
        TrustMetric,
    )

    m = TrustMetric()
    assert m.score() == 1.0
    for _ in range(3):
        m.record_good()
    assert m.score() > 0.9
    for _ in range(10):
        m.record_bad()
    assert m.score() < 0.5

    class FakeSwitch:
        def __init__(self):
            self.stopped = []

            class Peers:
                def __init__(self, outer):
                    self.outer = outer

                def get(self, pid):
                    return pid  # any truthy token

            self.peers = Peers(self)

        async def stop_peer_for_error(self, peer, reason):
            self.stopped.append((peer, str(reason)))

    async def go():
        sw = FakeSwitch()
        rep = Reporter(sw)
        await rep.report(PeerBehaviour("peer-1", CONSENSUS_VOTE))
        assert sw.stopped == []
        for _ in range(12):
            await rep.report(PeerBehaviour("peer-1", BAD_MESSAGE, "garbage"))
        assert sw.stopped and sw.stopped[0][0] == "peer-1"
        assert rep.score("peer-1") < 0.3
        assert rep.score("unknown") == 1.0

    asyncio.run(go())


@requires_cryptography
def test_signer_harness_cli(capsys):
    from tendermint_tpu.cli.main import main
    from tendermint_tpu.crypto.keys import gen_ed25519
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.privval.remote import SignerServer

    pv = FilePV(gen_ed25519(b"\x61" * 32))
    server = SignerServer(pv, "harness-chain")
    server.start()
    try:
        rc = main(["signer-harness", "--addr", f"tcp://127.0.0.1:{server.addr[1]}"])
        assert rc == 0
        import json as _json

        out = _json.loads(capsys.readouterr().out)
        assert out["passed"] is True
        assert out["results"]["double_sign_guard"] == "ok"
    finally:
        server.stop()
