"""Differential tests: JAX batched ed25519 vs pure-python RFC 8032 reference."""

import pytest

pytestmark = [pytest.mark.kernel, pytest.mark.slow]  # heavy one-time
# compiles: excluded from the tier-1 budget lane (-m 'not slow'); run
# explicitly via -m kernel

import numpy as np

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.ops import ed25519_jax as ed
from tendermint_tpu.ops import fe25519 as fe

rng = np.random.default_rng(42)


def fe_batch(ints):
    return np.stack([fe.from_int(x) for x in ints], axis=-1)


def ctx_for(n):
    return ed.make_ctx((n,))


def point_batch(points):
    """List of reference extended points -> JAX Point batch."""
    return ed.Point(
        fe_batch([p[0] for p in points]),
        fe_batch([p[1] for p in points]),
        fe_batch([p[2] for p in points]),
        fe_batch([p[3] for p in points]),
    )


def point_to_ints(p, i):
    return tuple(
        fe.to_int(np.asarray(c)[:, i]) for c in (p.x, p.y, p.z, p.t)
    )


def rand_points(n):
    pts = []
    for _ in range(n):
        k = int.from_bytes(rng.bytes(32), "little") % ref.L
        pts.append(ref.point_mul(k, ref.BASE))
    return pts


def assert_points_equal(jp, ref_points):
    for i, rp in enumerate(ref_points):
        got = point_to_ints(jp, i)
        assert ref.point_equal(got, rp), f"point {i} mismatch"
        # T must remain consistent: T = XY/Z
        x, y, z, t = got
        assert (x * y - t * z) % ref.P == 0


def test_point_add_matches_reference():
    n = 8
    ps, qs = rand_points(n), rand_points(n)
    out = ed.point_add(ctx_for(n), point_batch(ps), point_batch(qs))
    assert_points_equal(out, [ref.point_add(p, q) for p, q in zip(ps, qs)])


def test_point_double_matches_reference_and_unified_add():
    n = 8
    ps = rand_points(n)
    jp = point_batch(ps)
    doubled = ed.point_double(ctx_for(n), jp)
    assert_points_equal(doubled, [ref.point_double(p) for p in ps])
    via_add = ed.point_add(ctx_for(n), jp, jp)
    for i in range(n):
        assert ref.point_equal(point_to_ints(doubled, i), point_to_ints(via_add, i))


def test_add_identity_and_double_identity():
    n = 4
    ps = rand_points(n)
    ident = ed.identity(ctx_for(n))
    out = ed.point_add(ctx_for(n), point_batch(ps), ident)
    assert_points_equal(out, ps)
    out2 = ed.point_double(ctx_for(n), ident)
    assert_points_equal(out2, [ref.IDENTITY] * n)


def test_compress_decompress_roundtrip():
    n = 8
    ps = rand_points(n)
    enc_ref = [ref.point_compress(p) for p in ps]
    enc = np.asarray(ed.compress(point_batch(ps)))
    for i in range(n):
        assert enc[:, i].tobytes() == enc_ref[i]
    dec, ok = ed.decompress(ctx_for(len(enc_ref)), np.stack([np.frombuffer(e, dtype=np.uint8) for e in enc_ref], axis=-1))
    assert np.asarray(ok).all()
    assert_points_equal(dec, ps)


def test_decompress_rejects_invalid():
    good = ref.point_compress(ref.BASE)
    bad_not_on_curve = None
    # find a y that has no valid x
    for cand in range(2, 200):
        if ref.point_decompress(int.to_bytes(cand, 32, "little")) is None:
            bad_not_on_curve = int.to_bytes(cand, 32, "little")
            break
    assert bad_not_on_curve is not None
    noncanonical = int.to_bytes(ref.P + 1, 32, "little")  # y >= p
    arr = np.stack(
        [np.frombuffer(x, dtype=np.uint8) for x in (good, bad_not_on_curve, noncanonical)],
        axis=-1,
    )
    _, ok = ed.decompress(ctx_for(3), arr)
    assert list(np.asarray(ok)) == [True, False, False]


def _make_sigs(n, tamper=()):
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = bytes([i + 1]) * 32
        msg = b"block-vote-%d" % i
        pub = ref.public_key(seed)
        sig = ref.sign(seed, msg)
        pubs.append(pub)
        msgs.append(msg)
        sigs.append(sig)
    for i in tamper:
        b = bytearray(sigs[i])
        b[2] ^= 0xFF
        sigs[i] = bytes(b)
    return pubs, msgs, sigs


def test_verify_batch_jax_all_valid():
    pubs, msgs, sigs = _make_sigs(5)
    mask = cbatch.verify_batch(pubs, msgs, sigs, backend="jax")
    assert mask.tolist() == [True] * 5


def test_verify_batch_jax_detects_bad():
    pubs, msgs, sigs = _make_sigs(6, tamper=(1, 4))
    mask = cbatch.verify_batch(pubs, msgs, sigs, backend="jax")
    assert mask.tolist() == [True, False, True, True, False, True]
    # cpu backend agrees exactly
    cpu = cbatch.verify_batch(pubs, msgs, sigs, backend="cpu")
    assert cpu.tolist() == mask.tolist()


def test_verify_batch_jax_rejects_high_s():
    pubs, msgs, sigs = _make_sigs(2)
    s = int.from_bytes(sigs[0][32:], "little")
    sigs[0] = sigs[0][:32] + int.to_bytes(s + ref.L, 32, "little")
    mask = cbatch.verify_batch(pubs, msgs, sigs, backend="jax")
    assert mask.tolist() == [False, True]


def test_verify_batch_wrong_message_and_key():
    pubs, msgs, sigs = _make_sigs(3)
    msgs[0] = b"different"
    pubs[1], pubs[2] = pubs[2], pubs[1]  # swapped keys
    mask = cbatch.verify_batch(pubs, msgs, sigs, backend="jax")
    assert mask.tolist() == [False, False, False]


def test_verify_batch_malformed_inputs():
    pubs, msgs, sigs = _make_sigs(3)
    pubs[0] = pubs[0][:31]  # short key
    sigs[1] = sigs[1][:63]  # short sig
    mask = cbatch.verify_batch(pubs, msgs, sigs, backend="jax")
    assert mask.tolist() == [False, False, True]


def test_batch_verifier_interface():
    pubs, msgs, sigs = _make_sigs(4, tamper=(2,))
    bv = cbatch.Ed25519BatchVerifier(backend="jax")
    for p, m, s in zip(pubs, msgs, sigs):
        bv.add(p, m, s)
    assert len(bv) == 4
    assert bv.verify().tolist() == [True, True, False, True]
    bv.reset()
    assert len(bv) == 0


def test_empty_batch():
    assert cbatch.verify_batch([], [], []).tolist() == []


def test_persig_kernel_is_cofactored():
    from tendermint_tpu.crypto.keys import Ed25519PubKey

    from tests.sigutil import torsion_defect_sig

    a_enc, msg, sig = torsion_defect_sig(seed=8, msg=b"kernel-torsion-agreement")
    mask = cbatch.verify_batch_jax([a_enc], [msg], [sig])
    assert mask.tolist() == [True]
    # agrees with the host wrapper (OpenSSL fast path + cofactored referee)
    assert Ed25519PubKey(a_enc).verify(msg, sig)
    assert not ref.verify(a_enc, msg, sig)  # cofactorless would reject
    # a genuinely bad signature still fails on the kernel
    bad = bytearray(sig)
    bad[34] ^= 1
    assert cbatch.verify_batch_jax([a_enc], [msg], [bytes(bad)]).tolist() == [False]
