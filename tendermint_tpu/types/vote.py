"""Vote type (reference: types/vote.go).

A Vote is a signed prevote or precommit for a block (or nil). Sign-bytes are
the canonical length-delimited proto (tendermint_tpu.types.canonical); the wire
encoding mirrors proto/tendermint/types/types.proto Vote (fields 1-8).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tendermint_tpu.crypto.keys import PubKey
from tendermint_tpu.libs import hotstats
from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.types import canonical
from tendermint_tpu.types.basic import BlockID, SignedMsgType, ts_seconds_nanos

# Instrumentation: actual protowire/sign-bytes COMPUTES (cache misses), not
# calls. A Vote is immutable post-construction, so each instance should pay
# for each at most once no matter how many ingest layers serialize it (WAL
# frame, gossip re-send, verify). tests/test_hotpath_guard.py budgets these
# per vote; a new call site that bypasses the memo shows up as a counter
# regression there, not as a wall-clock flake.
ENCODE_COMPUTES = 0
SIGN_BYTES_COMPUTES = 0


@dataclass(frozen=True)
class Vote:
    type: SignedMsgType
    height: int
    round: int
    block_id: BlockID
    timestamp_ns: int
    validator_address: bytes
    validator_index: int
    signature: bytes = b""

    def is_nil(self) -> bool:
        return self.block_id.is_zero()

    def sign_bytes(self, chain_id: str) -> bytes:
        """Canonical sign-bytes, memoized per instance (a Vote's fields are
        frozen, so the result can never go stale; dataclasses.replace — e.g.
        with_signature — builds a NEW instance with an empty cache)."""
        cached = self.__dict__.get("_sign_bytes")
        if cached is not None and cached[0] == chain_id:
            return cached[1]
        global SIGN_BYTES_COMPUTES
        SIGN_BYTES_COMPUTES += 1
        hs = hotstats.stats if hotstats.stats.enabled else None
        if hs is not None:
            t0 = hotstats.perf_counter()
        data = canonical.vote_sign_bytes(
            chain_id, self.type, self.height, self.round, self.block_id, self.timestamp_ns
        )
        if hs is not None:
            hs.add("encode", hotstats.perf_counter() - t0)
        object.__setattr__(self, "_sign_bytes", (chain_id, data))
        return data

    def seed_sign_bytes(self, chain_id: str, data: bytes) -> None:
        """Prime the sign-bytes memo from a batched builder
        (canonical.vote_sign_bytes_many) so a follow-up serial verify does
        not re-run the per-vote encoder. `data` is length-delimited, exactly
        what sign_bytes returns."""
        object.__setattr__(self, "_sign_bytes", (chain_id, data))

    def verify(self, chain_id: str, pubkey: PubKey) -> bool:
        """Serial verification (reference: types/vote.go:149). The batched path
        goes through crypto.batch instead."""
        from tendermint_tpu.crypto.keys import address_from_pubkey_bytes

        if address_from_pubkey_bytes(pubkey.bytes()) != self.validator_address:
            return False
        return pubkey.verify(self.sign_bytes(chain_id), self.signature)

    def validate_basic(self) -> None:
        if self.type not in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT):
            raise ValueError("invalid vote type")
        if self.height < 0:
            raise ValueError("negative height")
        if self.round < 0:
            raise ValueError("negative round")
        self.block_id.validate_basic()
        if not self.block_id.is_zero() and not self.block_id.is_complete():
            raise ValueError(f"blockID must be either empty or complete, got: {self.block_id}")
        if len(self.validator_address) != 20:
            raise ValueError("wrong validator address size")
        if self.validator_index < 0:
            raise ValueError("negative validator index")
        if not self.signature:
            raise ValueError("signature is missing")
        # 96 = compressed-G2 BLS signature; ed25519/sr25519 remain 64
        # (reference caps at MaxSignatureSize=64; raised for the BLS
        # aggregate backend, docs/BLS.md)
        if len(self.signature) > 96:
            raise ValueError("signature too big")

    def with_signature(self, sig: bytes) -> "Vote":
        return replace(self, signature=sig)

    # Precomputed field tags for the flattened encoder below (byte-identical
    # to the Writer-built form; pinned by the decode round-trip tests).
    _T1 = pw.tag(1, pw.VARINT)
    _T2 = pw.tag(2, pw.VARINT)
    _T3 = pw.tag(3, pw.VARINT)
    _T4 = pw.tag(4, pw.BYTES)
    _T5 = pw.tag(5, pw.BYTES)
    _T6 = pw.tag(6, pw.BYTES)
    _T7 = pw.tag(7, pw.VARINT)
    _T8 = pw.tag(8, pw.BYTES)

    # Wire encoding (proto Vote, fields per types.proto), memoized per
    # instance: the ingest path serializes the same Vote for the WAL frame
    # and again for every gossip re-send — immutable post-construction, so
    # one protowire pass serves them all. Flattened (no Writer objects):
    # this runs once per vote on the live receive loop.
    def encode(self) -> bytes:
        cached = self.__dict__.get("_wire")
        if cached is not None:
            return cached
        global ENCODE_COMPUTES
        ENCODE_COMPUTES += 1
        hs = hotstats.stats if hotstats.stats.enabled else None
        if hs is not None:
            t0 = hotstats.perf_counter()
        enc = pw.encode_varint
        parts = []
        t = int(self.type)
        if t:
            parts.append(self._T1 + enc(t))
        if self.height:
            parts.append(self._T2 + enc(self.height))
        if self.round:
            parts.append(self._T3 + enc(self.round))
        bid = self.block_id.encode()
        parts.append(self._T4 + enc(len(bid)) + bid)
        sec, nanos = ts_seconds_nanos(self.timestamp_ns)
        ts = pw.encode_timestamp(sec, nanos)
        parts.append(self._T5 + enc(len(ts)) + ts)
        if self.validator_address:
            parts.append(self._T6 + enc(len(self.validator_address)) + self.validator_address)
        if self.validator_index:
            parts.append(self._T7 + enc(self.validator_index))
        if self.signature:
            parts.append(self._T8 + enc(len(self.signature)) + self.signature)
        data = b"".join(parts)
        if hs is not None:
            hs.add("encode", hotstats.perf_counter() - t0)
        object.__setattr__(self, "_wire", data)
        return data

    @classmethod
    def decode(cls, data: bytes) -> "Vote":
        vals = {
            "type": SignedMsgType.UNKNOWN,
            "height": 0,
            "round": 0,
            "block_id": BlockID(),
            "timestamp_ns": 0,
            "validator_address": b"",
            "validator_index": 0,
            "signature": b"",
        }
        for f, _, v in pw.Reader(data):
            if f == 1:
                vals["type"] = SignedMsgType(v)
            elif f == 2:
                vals["height"] = pw.int64_from_varint(v)
            elif f == 3:
                vals["round"] = pw.int64_from_varint(v)
            elif f == 4:
                vals["block_id"] = BlockID.decode(v)
            elif f == 5:
                sec = nanos = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        sec = pw.int64_from_varint(vv)
                    elif ff == 2:
                        nanos = pw.int64_from_varint(vv)
                vals["timestamp_ns"] = sec * 1_000_000_000 + nanos
            elif f == 6:
                vals["validator_address"] = v
            elif f == 7:
                vals["validator_index"] = pw.int64_from_varint(v)
            elif f == 8:
                vals["signature"] = v
        return cls(**vals)
