"""Operational tooling: load generation (tools/loadtest.py).

The reference delegates load testing to the external tm-load-test project
(reference: README.md:153-155); this package ships the equivalent in-tree
so the framework is self-contained.
"""
