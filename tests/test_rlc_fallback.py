"""RLC degradation-ladder coverage (satellite: until now the
`crypto/batch.py` RLC→per-sig fallback was only exercised by accident).

Fast (tier-1) test: an injected device error mid-flush (the RLC submit call
raises) must flip LAST_FLUSH_DETAIL["rlc_fallback"], land on the per-sig
path, and produce a mask byte-identical to the CPU path — with the device
kernel stubbed so tier-1 pays no compile.

Slow tests: the same ladder over the REAL kernels, both the legitimate
combined-check failure (one bad signature in the batch) and the injected
device-error variant."""

import os

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.chaos.device import DeviceFaultInjector
from tendermint_tpu.crypto import batch
from tendermint_tpu.crypto.keys import gen_ed25519
from tendermint_tpu.crypto.ed25519_ref import L


def make_mixed_validity_batch(n=8):
    """Valid signatures plus rows that fail PRECHECK (bad pubkey length,
    non-canonical s) — rejected identically by every path, so a stubbed
    device kernel can't mask a wrong verdict."""
    priv = gen_ed25519(b"\x09" * 32)
    pk = priv.pub_key().bytes()
    pks, msgs, sigs = [], [], []
    for i in range(n):
        m = b"ladder-%d" % i
        pks.append(pk)
        msgs.append(m)
        sigs.append(priv.sign(m))
    pks[2] = pk[:16]  # bad pubkey length
    bad_s = sigs[5][:32] + L.to_bytes(32, "little")  # s == L: non-canonical
    sigs[5] = bad_s
    return pks, msgs, sigs


@pytest.fixture
def small_rlc(monkeypatch):
    monkeypatch.setattr(batch, "RLC_MIN", 4)
    yield
    batch.set_device_fault_hook(None)


def test_device_error_mid_flush_falls_back_persig_mask_identical(
    small_rlc, monkeypatch
):
    """RLC submit raises (injected device error) -> per-sig fallback runs ->
    mask byte-identical to CPU, rlc_fallback recorded. Device kernel stubbed
    (all-true lanes); correctness is pinned by the precheck-failing rows."""
    from tendermint_tpu.ops import ed25519_jax, msm_jax

    def boom(*a, **kw):
        raise RuntimeError("injected mid-flush device error")

    monkeypatch.setattr(msm_jax, "rlc_check_submit", boom)
    monkeypatch.setattr(msm_jax, "rlc_check_cached_submit", boom)

    def fake_verify_prepared(a, r, s_bits, h_bits):
        return np.ones(a.shape[1], dtype=bool)

    monkeypatch.setattr(ed25519_jax, "verify_prepared", fake_verify_prepared)

    pks, msgs, sigs = make_mixed_validity_batch()
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")

    assert mask.tobytes() == cpu.tobytes()  # byte-identical verdicts
    assert batch.LAST_FLUSH_DETAIL.get("rlc_fallback") is True
    assert batch.LAST_JAX_PATH[0] == "persig"
    assert not mask[2] and not mask[5] and mask[0]


def test_rlc_fallback_counter_reaches_metrics(small_rlc, monkeypatch):
    from tendermint_tpu.libs import metrics as M
    from tendermint_tpu.ops import ed25519_jax, msm_jax

    def boom(*a, **kw):
        raise RuntimeError("injected")

    monkeypatch.setattr(msm_jax, "rlc_check_submit", boom)
    monkeypatch.setattr(msm_jax, "rlc_check_cached_submit", boom)
    monkeypatch.setattr(
        ed25519_jax,
        "verify_prepared",
        lambda a, r, s, h: np.ones(a.shape[1], dtype=bool),
    )
    before = M.batch_metrics().rlc_fallbacks._values.get((), 0)
    pks, msgs, sigs = make_mixed_validity_batch()
    batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert M.batch_metrics().rlc_fallbacks._values.get((), 0) == before + 1


@pytest.mark.slow
def test_real_kernels_bad_signature_fallback_byte_identical(small_rlc):
    """Real device path: one genuinely bad signature makes the RLC combined
    check fail; the per-sig kernel must recover the EXACT mask the CPU path
    produces, and the fallback must be recorded."""
    pks, msgs, sigs = make_mixed_validity_batch()
    sigs[1] = sigs[1][:63] + bytes([sigs[1][63] ^ 1])  # corrupt one valid sig
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    assert not cpu[1]
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.tobytes() == cpu.tobytes()
    assert batch.LAST_FLUSH_DETAIL.get("rlc_fallback") is True


@pytest.mark.slow
def test_real_kernels_injected_device_error_fallback(small_rlc):
    """Real device path with a chaos-injected one-shot device error at the
    RLC submit: the per-sig kernel (unfaulted) recovers the exact mask."""
    inj = DeviceFaultInjector().install()
    pks, msgs, sigs = make_mixed_validity_batch()
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    inj.arm_errors(1)  # fires at rlc_submit; per-sig then passes
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.tobytes() == cpu.tobytes()
    assert batch.LAST_FLUSH_DETAIL.get("rlc_fallback") is True
    assert ("rlc_submit", "error") in inj.fired
