"""ABCI gRPC transport: client/server round-trip and a full node driving an
out-of-process app over gRPC — the socket e2e matrix on the third transport
(reference test models: abci/tests/client_server_test.go over grpc,
abci/client/grpc_client.go, abci/server/grpc_server.go), plus the minimal
gRPC broadcast API (rpc/grpc/api.go)."""

import asyncio
import os
import subprocess
import sys

from tendermint_tpu.abci import types as a
from tendermint_tpu.abci.grpc import GrpcClient, GrpcServer
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.proxy.multi import grpc_client_creator

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")


def test_grpc_client_server_roundtrip():
    app = KVStoreApplication()
    server = GrpcServer("tcp://127.0.0.1:0", app)
    server.start()
    try:
        client = GrpcClient(f"127.0.0.1:{server.port}")
        assert client.echo("hello-grpc") == "hello-grpc"
        client.flush()
        info = client.info(a.RequestInfo())
        assert info.last_block_height == 0
        res = client.check_tx(a.RequestCheckTx(tx=b"k=v"))
        assert res.code == a.CODE_TYPE_OK
        client.begin_block(a.RequestBeginBlock(hash=b"", header=None))
        for i in range(20):
            r = client.deliver_tx(a.RequestDeliverTx(tx=b"gk%d=gv%d" % (i, i)))
            assert r.code == a.CODE_TYPE_OK
        client.end_block(a.RequestEndBlock(height=1))
        commit = client.commit()
        assert commit.data
        q = client.query(a.RequestQuery(data=b"gk7", path="/store"))
        assert q.value == b"gv7"
        snaps = client.list_snapshots()
        assert snaps.snapshots == []
        client.close()
    finally:
        server.stop()


def test_node_runs_against_grpc_app(tmp_path):
    """Full consensus node with its 4 ABCI connections over gRPC to a kvstore
    app server in ANOTHER PROCESS (the socket e2e scenario on grpc)."""
    script = (
        "import sys\n"
        "from tendermint_tpu.abci.kvstore import KVStoreApplication\n"
        "from tendermint_tpu.abci.grpc import GrpcServer\n"
        "srv = GrpcServer('tcp://127.0.0.1:0', KVStoreApplication())\n"
        "srv.start()\n"
        "print('READY', srv.port, flush=True)\n"
        "import time\n"
        "while True: time.sleep(1)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith("READY")
        port = int(line.split()[1])

        from tendermint_tpu.config.config import test_config
        from tendermint_tpu.crypto import gen_ed25519
        from tendermint_tpu.node.node import Node
        from tendermint_tpu.privval.file_pv import FilePV
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.root_dir = ""
        cfg.consensus.wal_path = str(tmp_path / "wal")
        priv = FilePV(gen_ed25519(b"\x72" * 32))
        gen = GenesisDoc(chain_id="grpc-chain",
                         validators=[GenesisValidator(priv.get_pub_key(), 10)])
        node = Node(cfg, gen, priv_validator=priv,
                    client_creator=grpc_client_creator(f"tcp://127.0.0.1:{port}"))

        async def run():
            await node.start()
            try:
                res = node.mempool.check_tx(b"grpc=works")
                assert res.code == a.CODE_TYPE_OK
                await node.wait_for_height(2, timeout=60)
                committed = False
                for _ in range(200):
                    committed = any(
                        b"grpc=works" in node.block_store.load_block(h).txs
                        for h in range(1, node.block_store.height + 1)
                    )
                    if committed:
                        break
                    await asyncio.sleep(0.1)
                assert committed, "tx never committed through the grpc app"
            finally:
                await node.stop()

        asyncio.run(run())
    finally:
        proc.kill()


def test_grpc_broadcast_api(tmp_path):
    """rpc/grpc BroadcastAPI: BroadcastTx runs CheckTx + waits for commit
    (reference: rpc/grpc/api.go BroadcastTx -> core.BroadcastTxCommit)."""
    import grpc as grpclib

    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.grpc_api import (
        _SERVICE,
        _dec_request_broadcast_tx,
    )
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.libs import protowire as pw

    cfg = test_config()
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = ""
    cfg.rpc.grpc_laddr = "tcp://127.0.0.1:0"
    cfg.root_dir = ""
    cfg.consensus.wal_path = str(tmp_path / "wal")
    priv = FilePV(gen_ed25519(b"\x73" * 32))
    gen = GenesisDoc(chain_id="grpcapi-chain",
                     validators=[GenesisValidator(priv.get_pub_key(), 10)])
    node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())

    async def run():
        await node.start()
        try:
            port = node.grpc_server.port

            def call_broadcast():
                w = pw.Writer()
                w.bytes_field(1, b"gapi=ok")
                channel = grpclib.insecure_channel(f"127.0.0.1:{port}")
                ping = channel.unary_unary(
                    f"/{_SERVICE}/Ping",
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
                assert ping(b"", timeout=10) == b""
                stub = channel.unary_unary(
                    f"/{_SERVICE}/BroadcastTx",
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
                out = stub(w.bytes(), timeout=30)
                channel.close()
                return out

            raw = await asyncio.get_event_loop().run_in_executor(None, call_broadcast)
            # response: field 1 = check_tx, field 2 = deliver_tx; both code 0
            fields = {f: v for f, _, v in pw.Reader(raw)}
            assert 1 in fields and 2 in fields
            for body in (fields[1], fields[2]):
                codes = [v for f, _, v in pw.Reader(body) if f == 1]
                assert not codes or all(c == 0 for c in codes)  # code 0 omitted or 0
        finally:
            await node.stop()

    asyncio.run(run())
