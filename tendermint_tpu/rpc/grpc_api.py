"""Minimal gRPC broadcast API (reference: rpc/grpc/api.go:1 —
service BroadcastAPI { rpc Ping; rpc BroadcastTx }).

The reference keeps this deliberately tiny ("only BroadcastTx") and so do
we: Ping answers empty, BroadcastTx runs the full broadcast_tx_commit
semantics (CheckTx -> wait for DeliverTx event) by scheduling the node's RPC
handler on the node's asyncio loop from the gRPC worker thread.

Wire format matches proto/tendermint/rpc/grpc/types.proto:
  RequestBroadcastTx { bytes tx = 1 }
  ResponseBroadcastTx { abci.ResponseCheckTx check_tx = 1;
                        abci.ResponseDeliverTx deliver_tx = 2 }
"""

from __future__ import annotations

import asyncio
import base64
from concurrent import futures

import grpc

from tendermint_tpu.abci import types as a
from tendermint_tpu.abci.wire import encode_msg
from tendermint_tpu.libs import protowire as pw

_SERVICE = "tendermint.rpc.grpc.BroadcastAPI"


def _dec_request_broadcast_tx(data: bytes) -> bytes:
    for f, _, v in pw.Reader(data):
        if f == 1:
            return v
    return b""


def _enc_response_broadcast_tx(resp: dict) -> bytes:
    """resp: the broadcast_tx_commit JSON-RPC result (rpc/server.py)."""

    def _b64(v):
        return base64.b64decode(v) if v else b""

    check = a.ResponseCheckTx(
        code=int(resp["check_tx"]["code"]),
        data=_b64(resp["check_tx"].get("data")),
        log=resp["check_tx"].get("log", ""),
    )
    deliver = resp.get("deliver_tx") or {}
    deliver_msg = a.ResponseDeliverTx(
        code=int(deliver.get("code", 0)),
        data=_b64(deliver.get("data")),
        log=deliver.get("log", ""),
    )
    w = pw.Writer()
    w.message_field(1, encode_msg(check), always=True)
    w.message_field(2, encode_msg(deliver_msg), always=True)
    return w.bytes()


class GrpcBroadcastServer:
    """Serves Ping + BroadcastTx next to the JSON-RPC server
    (enabled by config.rpc.grpc_laddr, reference: config/config.go
    GRPCListenAddress)."""

    def __init__(self, node, addr: str):
        self.node = node
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        handlers = {
            # grpc-python rejects None from (de)serializers/handlers; empty
            # proto messages travel as b"".
            "Ping": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: b"",
                request_deserializer=lambda d: b"",
                response_serializer=lambda m: b"",
            ),
            "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                self._broadcast_tx,
                request_deserializer=_dec_request_broadcast_tx,
                response_serializer=_enc_response_broadcast_tx,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        host_port = addr.replace("tcp://", "")
        self.port = self._server.add_insecure_port(host_port)

    def _broadcast_tx(self, tx: bytes, context) -> dict:
        from tendermint_tpu.rpc.client import LocalClient

        client = LocalClient(self.node)
        fut = asyncio.run_coroutine_threadsafe(
            client.call("broadcast_tx_commit", tx="0x" + tx.hex()), self._loop
        )
        return fut.result(timeout=30)

    def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)
