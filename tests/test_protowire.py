"""protowire must agree byte-for-byte with the google.protobuf runtime."""

import struct

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from tendermint_tpu.libs import protowire as pw


def _make_dynamic_message_cls():
    """Build a dynamic proto message equivalent to CanonicalVote via descriptors."""
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "test_canonical.proto"
    fdp.package = "testpkg"
    fdp.syntax = "proto3"

    ts = fdp.message_type.add()
    ts.name = "Ts"
    f = ts.field.add()
    f.name = "seconds"
    f.number = 1
    f.type = descriptor_pb2.FieldDescriptorProto.TYPE_INT64
    f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
    f = ts.field.add()
    f.name = "nanos"
    f.number = 2
    f.type = descriptor_pb2.FieldDescriptorProto.TYPE_INT32
    f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL

    m = fdp.message_type.add()
    m.name = "CanonicalVoteLike"
    specs = [
        ("type", 1, descriptor_pb2.FieldDescriptorProto.TYPE_INT64),
        ("height", 2, descriptor_pb2.FieldDescriptorProto.TYPE_SFIXED64),
        ("round", 3, descriptor_pb2.FieldDescriptorProto.TYPE_SFIXED64),
        ("hash", 4, descriptor_pb2.FieldDescriptorProto.TYPE_BYTES),
        ("chain_id", 6, descriptor_pb2.FieldDescriptorProto.TYPE_STRING),
    ]
    for name, num, typ in specs:
        f = m.field.add()
        f.name = name
        f.number = num
        f.type = typ
        f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL
    f = m.field.add()
    f.name = "timestamp"
    f.number = 5
    f.type = descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE
    f.type_name = ".testpkg.Ts"
    f.label = descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    desc = pool.FindMessageTypeByName("testpkg.CanonicalVoteLike")
    ts_desc = pool.FindMessageTypeByName("testpkg.Ts")
    return (
        message_factory.GetMessageClass(desc),
        message_factory.GetMessageClass(ts_desc),
    )


def test_varint_roundtrip():
    for v in [0, 1, 127, 128, 300, 2**32, 2**63 - 1, -1, -5]:
        enc = pw.encode_varint(v)
        dec, pos = pw.decode_varint(enc)
        assert pos == len(enc)
        if v >= 0:
            assert dec == v
        else:
            assert dec == v + (1 << 64)


def test_against_protobuf_runtime():
    VoteCls, TsCls = _make_dynamic_message_cls()

    msg = VoteCls()
    msg.type = 1
    msg.height = 12345
    msg.round = 2
    msg.hash = b"\xaa" * 32
    msg.chain_id = "test-chain"
    msg.timestamp.seconds = 1700000000
    msg.timestamp.nanos = 123456789
    expected = msg.SerializeToString(deterministic=True)

    w = pw.Writer()
    w.varint_field(1, 1)
    w.sfixed64_field(2, 12345)
    w.sfixed64_field(3, 2)
    w.bytes_field(4, b"\xaa" * 32)
    w.message_field(5, pw.encode_timestamp(1700000000, 123456789), always=True)
    w.string_field(6, "test-chain")
    assert w.bytes() == expected


def test_zero_fields_omitted_matches_proto3():
    VoteCls, _ = _make_dynamic_message_cls()
    msg = VoteCls()
    msg.timestamp.seconds = 5  # force presence of the submessage
    expected = msg.SerializeToString(deterministic=True)

    w = pw.Writer()
    w.varint_field(1, 0)
    w.sfixed64_field(2, 0)
    w.sfixed64_field(3, 0)
    w.bytes_field(4, b"")
    w.message_field(5, pw.encode_timestamp(5, 0), always=True)
    w.string_field(6, "")
    assert w.bytes() == expected


def test_negative_varint_is_10_bytes():
    assert len(pw.encode_varint(-1)) == 10


def test_sfixed64_encoding():
    w = pw.Writer()
    w.sfixed64_field(2, -7)
    got = w.bytes()
    assert got[0] == (2 << 3) | 1
    assert struct.unpack("<q", got[1:9])[0] == -7


def test_length_delimited_roundtrip():
    body = b"hello world"
    framed = pw.length_delimited(body)
    out, pos = pw.read_length_delimited(framed)
    assert out == body and pos == len(framed)


def test_reader_roundtrip():
    w = pw.Writer()
    w.varint_field(1, 42)
    w.sfixed64_field(2, -1)
    w.bytes_field(3, b"xyz")
    fields = list(pw.Reader(w.bytes()))
    assert fields[0] == (1, pw.VARINT, 42)
    assert fields[1][0] == 2 and pw.sfixed64_from_unsigned(fields[1][2]) == -1
    assert fields[2] == (3, pw.BYTES, b"xyz")
