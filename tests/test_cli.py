"""CLI + TOML config (reference test models: cmd/tendermint/commands tests,
config/toml_test.go)."""

import asyncio
import json
import os
import subprocess
import sys

import pytest

# module imports reach the p2p stack (secret connection -> the
# `cryptography` wheel); skip cleanly in minimal containers
pytest.importorskip("cryptography")

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.cli.main import init_files, main, make_testnet
from tendermint_tpu.config.config import Config
from tendermint_tpu.config.toml import dumps, load_config, loads, save_config


def test_toml_roundtrip_preserves_all_fields(tmp_path):
    cfg = Config()
    cfg.base.moniker = "alice"
    cfg.base.fast_sync = False
    cfg.rpc.laddr = "tcp://0.0.0.0:36657"
    cfg.p2p.persistent_peers = "aa@1.2.3.4:26656,bb@5.6.7.8:26656"
    cfg.p2p.pex = False
    cfg.consensus.timeout_commit = 2.5
    cfg.statesync.enable = True
    cfg.statesync.rpc_servers = ["http://a:26657", "http://b:26657"]
    cfg.statesync.trust_height = 42
    cfg.statesync.trust_hash = "ab" * 32

    path = str(tmp_path / "config.toml")
    save_config(cfg, path)
    cfg2 = load_config(path)

    assert cfg2.base.moniker == "alice"
    assert cfg2.base.fast_sync is False
    assert cfg2.rpc.laddr == "tcp://0.0.0.0:36657"
    assert cfg2.p2p.persistent_peers == cfg.p2p.persistent_peers
    assert cfg2.p2p.pex is False
    assert cfg2.consensus.timeout_commit == 2.5
    assert cfg2.statesync.enable is True
    assert cfg2.statesync.rpc_servers == cfg.statesync.rpc_servers
    assert cfg2.statesync.trust_height == 42


def test_toml_unknown_keys_ignored_and_defaults_kept():
    cfg = loads('moniker = "m"\nbogus_key = 1\n[rpc]\nladdr = "tcp://h:1"\nnope = true\n[unknown_section]\nx = 2\n')
    assert cfg.base.moniker == "m"
    assert cfg.rpc.laddr == "tcp://h:1"
    # untouched defaults survive
    assert cfg.p2p.pex is True
    assert cfg.consensus.timeout_commit == 1.0


def test_init_creates_tree_and_is_idempotent(tmp_path):
    home = str(tmp_path / "node")
    info = init_files(home, chain_id="cli-chain")
    for rel in (
        "config/config.toml",
        "config/genesis.json",
        "config/priv_validator_key.json",
        "config/node_key.json",
        "data",
    ):
        assert os.path.exists(os.path.join(home, rel)), rel
    # second init keeps the same identity
    info2 = init_files(home, chain_id="other")
    assert info2["node_id"] == info["node_id"]
    assert info2["validator_address"] == info["validator_address"]
    gen = json.load(open(os.path.join(home, "config/genesis.json")))
    assert gen["chain_id"] == "cli-chain"  # not overwritten


def test_testnet_generates_wired_configs(tmp_path):
    out = make_testnet(str(tmp_path / "net"), 4, chain_id="net-chain", starting_port=30000)
    assert len(out) == 4
    genesis_files = set()
    for i, node in enumerate(out):
        cfg = load_config(os.path.join(node["home"], "config", "config.toml"))
        # every node lists the other three as persistent peers
        peers = [p for p in cfg.p2p.persistent_peers.split(",") if p]
        assert len(peers) == 3
        assert all(not p.startswith(node["node_id"]) for p in peers)
        genesis_files.add(open(os.path.join(node["home"], "config", "genesis.json")).read())
    assert len(genesis_files) == 1  # identical genesis everywhere
    gen = json.loads(next(iter(genesis_files)))
    assert len(gen["validators"]) == 4


def test_cli_entrypoints_run(tmp_path, capsys):
    home = str(tmp_path / "h")
    assert main(["--home", home, "init", "--chain-id", "x"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["node_id"]

    assert main(["--home", home, "show-node-id"]) == 0
    assert capsys.readouterr().out.strip() == out["node_id"]

    assert main(["--home", home, "show-validator"]) == 0
    v = json.loads(capsys.readouterr().out)
    assert v["type"] == "tendermint/PubKeyEd25519"  # amino-style type tag

    assert main(["--home", home, "gen-validator"]) == 0
    g = json.loads(capsys.readouterr().out)
    assert len(bytes.fromhex(g["priv_key"])) == 32

    assert main(["--home", home, "version"]) == 0
    capsys.readouterr()

    # unsafe-reset-all wipes data but keeps keys
    datafile = os.path.join(home, "data", "junk")
    open(datafile, "w").write("x")
    assert main(["--home", home, "unsafe-reset-all"]) == 0
    capsys.readouterr()
    assert not os.path.exists(datafile)
    assert os.path.exists(os.path.join(home, "config", "priv_validator_key.json"))


def test_two_node_localnet_from_generated_configs(tmp_path):
    """`testnet` output boots a real 2-validator net that commits blocks —
    the reference's two-command localnet story
    (reference: docs 'Deploy a Testnet' + commands/testnet.go)."""
    from tendermint_tpu.cli.main import load_home
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc

    import socket as s

    ports = []
    for _ in range(2):
        sock = s.socket()
        sock.bind(("127.0.0.1", 0))
        ports.append(sock.getsockname()[1])
        sock.close()

    out = make_testnet(str(tmp_path / "net"), 2, chain_id="localnet", starting_port=ports[0])
    # rewrite the second node's ports to the second free port to avoid clashes
    # (make_testnet allocates sequentially from starting_port)

    async def run():
        nodes = []
        for entry in out:
            cfg = load_home(entry["home"])
            cfg.base.db_backend = "memdb"
            cfg.rpc.laddr = ""
            # fast test timeouts
            cfg.consensus.timeout_propose = 0.4
            cfg.consensus.timeout_prevote = 0.2
            cfg.consensus.timeout_precommit = 0.2
            cfg.consensus.timeout_commit = 0.1
            with open(cfg.genesis_path()) as f:
                gen = GenesisDoc.from_json(f.read())
            pv = FilePV.load(
                cfg.path(cfg.base.priv_validator_key_file),
                cfg.path(cfg.base.priv_validator_state_file),
            )
            nodes.append(Node(cfg, gen, priv_validator=pv))
        try:
            for n in nodes:
                await n.start()
            for n in nodes:
                await n.wait_for_height(3, timeout=90)
        finally:
            for n in nodes:
                await n.stop()

    asyncio.run(run())


def test_replay_steps_through_wal(tmp_path, capsys):
    """`replay` re-drives the in-progress height's WAL through a fresh
    consensus state (reference: consensus/replay_file.go RunReplayFile,
    cmd/tendermint/commands/replay.go)."""
    from tendermint_tpu.cli.main import load_home, run_replay
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc

    home = str(tmp_path / "replayhome")
    init_files(home, "replay-chain")

    async def run_some_blocks():
        cfg = load_home(home)
        cfg.rpc.laddr = ""
        cfg.consensus.timeout_commit = 0.05
        with open(cfg.genesis_path()) as f:
            gen = GenesisDoc.from_json(f.read())
        pv = FilePV.load(
            cfg.path(cfg.base.priv_validator_key_file),
            cfg.path(cfg.base.priv_validator_state_file),
        )
        node = Node(cfg, gen, priv_validator=pv)
        await node.start()
        await node.wait_for_height(2, timeout=60)
        await node.stop()

    asyncio.run(run_some_blocks())

    run_replay(home, console=False)
    out = capsys.readouterr().out
    assert "replaying" in out
    # the final round-state summary is valid JSON with the current height
    last = json.loads(out.strip().splitlines()[-1])
    assert last["height"] >= 2
