"""Light-client-as-a-service (light/service.py + light/coalescer.py +
crypto/batch.FlushAccumulator): the ISSUE 9 acceptance proofs.

- the seeded multi-client integration test: M clients x H heights complete
  with <= ceil(H / window) coalesced device flushes (counted via
  libs/trace.verify_stats totals), verdicts byte-identical to per-request
  serial verification (light/client.py), and the live consensus path keeps
  committing while a PR 5-style admission flood runs concurrently;
- cache single-flight: K concurrent same-height requests -> exactly ONE
  device flush and one provider fetch;
- bisection fallback across a full valset rotation, structured
  conflicting-header errors, service-level shedding (429 semantics);
- the FlushAccumulator's byte-identical slicing guarantee;
- LightStore concurrent readers/pruners (satellite);
- LightProxy's unverified-forward marker (satellite).

Seeded: TMTPU_LIGHT_SEED replays the Zipfian request schedule.
"""

import asyncio
import math
import os
import threading

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

import test_light as lt

from tendermint_tpu.config.config import LightServiceConfig
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.light.client import Client, TrustOptions
from tendermint_tpu.light.provider import MockProvider, ProviderError
from tendermint_tpu.light.service import (
    ErrConflictingHeader,
    ErrHeightNotAvailable,
    ErrLightOverloaded,
    ErrVerificationFailed,
    LightService,
)
from tendermint_tpu.light.store import LightStore
from tendermint_tpu.light.verifier import LightError
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.light import LightBlock, SignedHeader

SEED = int(os.environ.get("TMTPU_LIGHT_SEED", "1337"))


def run(coro):
    return asyncio.run(coro)


def total_flushes() -> int:
    """Process-global device/cpu flush count (libs/trace verify_stats):
    every verify_batch call on any backend records exactly one flush."""
    return sum(t["flushes"] for t in trace.verify_stats()["totals"].values())


def make_service(blocks, **cfg_overrides):
    kwargs = {"coalesce_window": 0.05, "max_heights_per_flush": 64}
    kwargs.update(cfg_overrides)
    cfg = LightServiceConfig(**kwargs)
    svc = LightService(
        lt.CHAIN_ID,
        MockProvider(lt.CHAIN_ID, blocks),
        cfg,
        now_ns=lambda: lt.NOW,
    )
    return svc


def tamper_commit(lb: LightBlock, n_bad: int) -> LightBlock:
    """Replace n_bad signatures with garbage (enough to break +2/3)."""
    commit = lb.signed_header.commit
    sigs = list(commit.signatures)
    for i in range(n_bad):
        s = sigs[i]
        sigs[i] = CommitSig(
            s.block_id_flag, s.validator_address, s.timestamp_ns, b"\x01" * 64
        )
    return LightBlock(
        SignedHeader(
            lb.signed_header.header,
            Commit(commit.height, commit.round, commit.block_id, sigs),
        ),
        lb.validator_set,
    )


# -- FlushAccumulator (crypto/batch cross-request accumulation) ---------------


def test_flush_accumulator_slices_byte_identical():
    """Three independent submits accumulated into one flush return masks
    byte-identical to three standalone verify_batch calls — including a
    sub-batch with a bad row — and the window costs exactly ONE flush."""
    from tendermint_tpu.crypto import batch as B

    from bench import make_batch

    pk, msg, sig, _ = make_batch(12)
    groups = [(pk[:5], msg[:5], sig[:5]), (pk[5:8], msg[5:8], sig[5:8]),
              (pk[8:], msg[8:], sig[8:])]
    # corrupt one row of the middle group
    bad_sigs = list(groups[1][2])
    bad_sigs[1] = b"\x02" * 64
    groups[1] = (groups[1][0], groups[1][1], bad_sigs)

    expect = [B.verify_batch(*g) for g in groups]

    f0 = total_flushes()
    with B.accumulate_flushes() as acc:
        handles = [B.verify_batch_submit(*g) for g in groups]
        assert acc.lanes == 12
    masks = [B.verify_batch_finish(h) for h in handles]
    assert total_flushes() - f0 == 1
    for m, e in zip(masks, expect):
        assert np.array_equal(m, e)
    assert not masks[1][1] and masks[1].sum() == 2
    assert masks[0].all() and masks[2].all()
    # the scope is gone: submits dispatch normally again
    h = B.verify_batch_submit(*groups[0])
    assert B.verify_batch_finish(h).all()


def test_flush_accumulator_empty_and_reuse_guard():
    from tendermint_tpu.crypto import batch as B

    with B.accumulate_flushes() as acc:
        pass
    assert acc.flush().shape == (0,)
    with pytest.raises(RuntimeError):
        acc.add([b"x"], [b"y"], [b"z"], None)


def test_flush_accumulator_failed_flush_rethrows_for_every_finish(monkeypatch):
    """A failed shared flush latches its error: every handle's finish gets
    the REAL failure, never a NoneType slice crash, and the device is not
    re-dispatched per handle."""
    from tendermint_tpu.crypto import batch as B

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("device exploded")

    with B.accumulate_flushes() as acc:
        h1 = B.verify_batch_submit([b"p" * 32], [b"m"], [b"s" * 64])
        h2 = B.verify_batch_submit([b"q" * 32], [b"n"], [b"t" * 64])
    monkeypatch.setattr(B, "verify_batch", boom)
    with pytest.raises(RuntimeError, match="device exploded"):
        B.verify_batch_finish(h1)
    with pytest.raises(RuntimeError, match="device exploded"):
        B.verify_batch_finish(h2)
    assert calls["n"] == 1  # one flush attempt, not one per handle


# -- coalescing: the seeded multi-client integration proof --------------------


def test_coalesced_multi_client_matches_serial():
    """M clients x H heights: <= ceil(H / window capacity) coalesced device
    flushes after anchoring, verdicts byte-identical to per-request serial
    verification through light/client.py — including a tampered height that
    must fail IDENTICALLY on both paths without poisoning its windowmates."""
    import random

    H = 9  # heights 2..10
    M = 6
    blocks = lt.make_chain(10)
    blocks[6] = tamper_commit(blocks[6], 2)  # 2 of 4 sigs bad: below +2/3
    rng = random.Random(SEED)

    # serial comparator: one fresh client per request — what answering each
    # client individually costs/decides
    def serial_verdict(h):
        client = Client(
            lt.CHAIN_ID,
            TrustOptions(lt.PERIOD, 1, blocks[1].hash()),
            MockProvider(lt.CHAIN_ID, blocks),
            [],
            LightStore(MemDB()),
        )

        async def go():
            await client.initialize(lt.NOW)
            return await client.verify_light_block_at_height(h, lt.NOW)

        try:
            return run(go()).hash()
        except LightError:
            return "invalid"

    heights = [rng.randint(2, 10) for _ in range(M * H)]
    serial = {h: serial_verdict(h) for h in set(heights)}
    assert serial[6] == "invalid"  # the tamper is strong enough

    svc = make_service(blocks, max_heights_per_flush=16)

    async def go():
        await svc._ensure_anchor()
        f0 = total_flushes()

        async def one(h):
            try:
                lb, _src = await svc.verify_height(h)
                return lb.hash()
            except ErrVerificationFailed:
                return "invalid"

        verdicts = await asyncio.gather(*[one(h) for h in heights])
        return total_flushes() - f0, verdicts

    flushes, verdicts = run(go())
    svc.close()

    # byte-identical verdicts, request by request
    for h, v in zip(heights, verdicts):
        assert v == serial[h], f"height {h}: coalesced {v!r} != serial {serial[h]!r}"
    # coalescing bound: all misses fit one window capacity of 16
    assert flushes <= math.ceil(H / 16), f"{flushes} flushes for {H} heights"
    assert svc.flushes == flushes
    assert svc.lanes_total > 0


def test_coalescing_respects_window_capacity():
    """H heights with a batch capacity of W group into ceil(H/W) window
    bodies — and the scheduler's light lane (ISSUE 11) may MERGE those
    bodies' rows into even fewer device flushes, never more (the acceptance
    bound with a non-trivial ceiling)."""
    H, W = 8, 3
    blocks = lt.make_chain(H + 1)
    svc = make_service(blocks, max_heights_per_flush=W)

    # the coalescer's contract is same-tick submits join one batch, but
    # each request reaches submit through an executor hop
    # (validate_basic), so a gather burst can straddle loop ticks and
    # split a window — hold every job at the submit boundary until the
    # whole burst has arrived, making "a concurrent burst of H" literal
    orig_submit = svc.coalescer.submit
    gate = asyncio.Event()
    arrived = 0

    async def gated_submit(job):
        nonlocal arrived
        arrived += 1
        if arrived == H:
            gate.set()
        await gate.wait()
        return await orig_submit(job)

    svc.coalescer.submit = gated_submit

    async def go():
        await svc._ensure_anchor()
        f0 = total_flushes()
        await asyncio.gather(*[svc.verify_height(h) for h in range(2, H + 2)])
        return total_flushes() - f0

    flushes = run(go())
    svc.close()
    # job batching honors the capacity: a concurrent burst of H misses
    # fires exactly ceil(H/W) window bodies...
    assert svc.coalescer.windows_fired == math.ceil(H / W)
    # ...and the light lane coalesces their rows: at most one device flush
    # per window body, typically fewer (bodies landing inside one lane
    # window share a combined flush)
    assert 1 <= flushes <= math.ceil(H / W)
    assert svc.flushes == flushes


def test_cache_single_flight():
    """K concurrent requests for one uncached height: exactly ONE device
    flush, one provider fetch, K identical answers."""
    K = 8
    blocks = lt.make_chain(6)
    svc = make_service(blocks)

    async def go():
        await svc._ensure_anchor()
        calls0 = svc.provider.calls
        f0 = total_flushes()
        results = await asyncio.gather(*[svc.verify_height(5) for _ in range(K)])
        return total_flushes() - f0, svc.provider.calls - calls0, results

    flushes, fetches, results = run(go())
    svc.close()
    assert flushes == 1
    assert fetches == 1
    assert len({lb.hash() for lb, _src in results}) == 1
    assert svc.singleflight_waits == K - 1
    # repeat is a pure cache hit: no new flush, no fetch
    f1 = total_flushes()
    lb, src = run(svc.verify_height(5))
    assert src == "cache" and total_flushes() == f1
    assert svc.cache_hits >= 1


def test_single_flight_leader_cancellation_does_not_cascade():
    """A cancelled leader (its client disconnected mid-verification) must
    not fail the cohort: a follower re-leads and everyone else still gets
    the verified header."""

    class SlowProvider(MockProvider):
        async def light_block(self, height):
            if height is not None and height > 1:
                await asyncio.sleep(0.15)
            return await super().light_block(height)

    blocks = lt.make_chain(6)
    svc = LightService(
        lt.CHAIN_ID,
        SlowProvider(lt.CHAIN_ID, blocks),
        LightServiceConfig(coalesce_window=0.01),
        now_ns=lambda: lt.NOW,
    )

    async def go():
        await svc._ensure_anchor()
        leader = asyncio.create_task(svc.verify_height(4))
        await asyncio.sleep(0.03)  # leader holds the in-flight slot
        followers = [asyncio.create_task(svc.verify_height(4)) for _ in range(3)]
        await asyncio.sleep(0.03)
        leader.cancel()
        results = await asyncio.gather(*followers)
        assert all(lb.hash() == blocks[4].hash() for lb, _src in results)
        with pytest.raises(asyncio.CancelledError):
            await leader

    run(go())
    svc.close()


# -- fallback / structured errors --------------------------------------------


def test_bisection_fallback_on_valset_rotation():
    old = lt.make_keys(b"\x01", 4)
    new = lt.make_keys(b"\x02", 4)  # disjoint: zero voting overlap
    blocks = lt.make_chain(20, privs_by_height={10: new}, default_privs=old)
    svc = make_service(blocks)

    lb, src = run(svc.verify_height(20))
    svc.close()
    assert src == "bisection"
    assert lb.hash() == blocks[20].hash()
    assert svc.bisections == 1
    # the bisection's interim headers warmed the shared cache
    assert svc.store.size() > 2


def test_conflicting_header_and_not_found():
    blocks = lt.make_chain(5)
    svc = make_service(blocks)

    with pytest.raises(ErrConflictingHeader) as ei:
        run(svc.verify_height(3, expected_hash=b"\x00" * 32))
    assert ei.value.code == -32010
    assert ei.value.data["height"] == 3
    assert ei.value.data["verified_hash"] == blocks[3].hash().hex().upper()
    assert svc.conflicts == 1

    with pytest.raises(ErrHeightNotAvailable):
        run(svc.verify_height(99))
    with pytest.raises(ErrHeightNotAvailable):
        run(svc.verify_height(-1))
    svc.close()


def test_service_level_shedding():
    """max_pending misses in flight: the next MISS sheds (ErrLightOverloaded,
    the RPC layer's 429); cache hits are never shed."""

    class SlowProvider(MockProvider):
        async def light_block(self, height):
            if height is not None and height > 1:  # anchor fetch stays fast
                await asyncio.sleep(0.2)
            return await super().light_block(height)

    blocks = lt.make_chain(8)
    svc = LightService(
        lt.CHAIN_ID,
        SlowProvider(lt.CHAIN_ID, blocks),
        LightServiceConfig(coalesce_window=0.02, max_pending=1),
        now_ns=lambda: lt.NOW,
    )

    async def go():
        await svc._ensure_anchor()
        first = asyncio.create_task(svc.verify_height(5))
        await asyncio.sleep(0.05)  # the slow miss now occupies max_pending
        with pytest.raises(ErrLightOverloaded):
            await svc.verify_height(6)
        lb, _ = await first
        assert lb.hash() == blocks[5].hash()
        # cached height still served while another miss is in flight
        second = asyncio.create_task(svc.verify_height(7))
        await asyncio.sleep(0.05)
        lb2, src = await svc.verify_height(5)
        assert src == "cache"
        await second

    run(go())
    svc.close()
    assert svc.sheds == 1
    assert svc.outcomes.get("shed") == 1


# -- node e2e: RPC routes + admission under the PR 5 flood --------------------


def test_node_light_routes_under_flood(tmp_path):
    """A live single-validator node serves light_verify/light_block/
    light_status + /debug/light while a PR 5-style tx-admission flood runs:
    every light request is answered (verified or 429), consensus KEEPS
    COMMITTING (the vote path is never starved), no gate-exempt method is
    ever shed, and the light_verify_p99 SLO objective receives
    observations."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto.keys import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.client import LocalClient, RPCError
    from tendermint_tpu.rpc.server import SHEDDABLE_METHODS
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.root_dir = ""
        cfg.consensus.wal_path = str(tmp_path / "wal")
        cfg.light_service.coalesce_window = 0.01
        priv = FilePV(gen_ed25519(b"\x95" * 32))
        gen = GenesisDoc(
            chain_id="light-svc",
            validators=[GenesisValidator(priv.get_pub_key(), 10)],
        )
        node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
        node._start_crypto_prewarm = lambda: None
        await node.start()
        stop = threading.Event()

        def flooder(k):
            i = 0
            while not stop.is_set():
                try:
                    node.mempool.check_tx(b"lsf-%d-%d=x" % (k, i))
                except Exception:
                    pass
                i += 1

        threads = [
            threading.Thread(target=flooder, args=(k,), daemon=True)
            for k in range(3)
        ]
        try:
            await node.wait_for_height(4, timeout=60)
            client = LocalClient(node)
            h_start = node.block_store.height
            for t in threads:
                t.start()

            answered = shed = 0
            for round_ in range(3):
                target = node.block_store.height - 1
                for h in range(2, max(3, target + 1)):
                    try:
                        res = await client.call("light_verify", height=h)
                        assert res["light_client_verified"] is True
                        assert res["source"] in ("cache", "flush", "bisection")
                        answered += 1
                    except RPCError as e:
                        assert e.code == -32005  # 429: admission, not a crash
                        shed += 1
                await asyncio.sleep(0.15)
            assert answered > 0

            # the vote path was never starved: consensus kept committing
            # while the flood + light serving ran
            await node.wait_for_height(h_start + 2, timeout=60)

            # only gate-covered methods ever shed (votes/consensus RPC are
            # exempt by construction; pin it)
            shed_methods = {
                labels[0]
                for labels in node.metrics.rpc.shed_requests._values
            }
            assert shed_methods <= set(SHEDDABLE_METHODS)

            blk = await client.call("light_block", height=2)
            assert blk["validator_set"]["validators"]
            st = await client.call("light_status")
            assert st["trusted_span"]["last"] >= 2
            dbg = await client.call("debug_light")
            assert dbg["requests"] >= answered
            vs = await client.call("debug_verify_stats")
            assert vs["light"]["requests"] == dbg["requests"]
            idx = await client.call("debug_index")
            assert any(e["path"] == "/debug/light" for e in idx["endpoints"])

            if node.slo is not None:
                snap = node.slo.snapshot()
                assert snap["objectives"]["light_verify_p99"]["observations"] > 0
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)
            await node.stop()

    run(go())


def test_rpc_structured_refusals_without_node():
    """Disabled service and unparseable params are structured errors, not
    -32603 internal errors with stack traces."""
    from types import SimpleNamespace

    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.light.service import ErrBadRequest, ErrLightDisabled
    from tendermint_tpu.rpc.server import RPCServer

    cfg = test_config()
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    server = RPCServer(SimpleNamespace(config=cfg, metrics=None))

    with pytest.raises(ErrLightDisabled) as ei:
        run(server._light_status({}))
    assert ei.value.code == -32013

    with pytest.raises(ErrBadRequest) as ei:
        server._decode_hash_param({"hash": "zz"})
    assert ei.value.code == -32602
    assert server._decode_hash_param({}) is None


# -- satellites ---------------------------------------------------------------


def test_store_concurrent_readers_and_pruners():
    """LightStore under concurrent save/prune/read from many threads: no
    exceptions, heights stay sorted+unique, final occupancy == prune bound."""
    blocks = lt.make_chain(64)
    store = LightStore(MemDB())
    errors = []

    def writer(lo, hi):
        try:
            for h in range(lo, hi):
                store.save_light_block(blocks[h])
        except Exception as e:  # pragma: no cover - the assertion payload
            errors.append(e)

    def pruner():
        try:
            for _ in range(200):
                store.prune(24)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(300):
                hs = store.heights()
                assert hs == sorted(hs) and len(hs) == len(set(hs))
                store.latest_light_block()
                store.first_light_block()
                store.light_block_before(40)
                store.size()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = (
        [threading.Thread(target=writer, args=(1, 33)),
         threading.Thread(target=writer, args=(33, 65))]
        + [threading.Thread(target=pruner) for _ in range(2)]
        + [threading.Thread(target=reader) for _ in range(3)]
    )
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    store.prune(24)
    assert store.size() == 24
    assert store.heights() == sorted(store.heights())


def test_proxy_forwards_unverified_with_marker(tmp_path):
    """LightProxy satellite: a route outside the verified set is forwarded
    as-is with "light_client_verified": false on dict results; non-dict
    results pass through unmarked; verified routes never carry false."""
    import aiohttp

    from tendermint_tpu.light.proxy import LightProxy

    blocks = lt.make_chain(6)

    class StubBackend:
        def __init__(self):
            self.calls = []

        async def call(self, method, **params):
            self.calls.append((method, params))
            if method == "net_info":
                return {"n_peers": "3"}
            if method == "health":
                return {}
            if method == "num_unconfirmed_txs":
                return ["not-a-dict"]
            if method == "status":
                return {"node_info": {"network": lt.CHAIN_ID}}
            raise AssertionError(f"unexpected backend call {method}")

    backend = StubBackend()
    lc = Client(
        lt.CHAIN_ID,
        TrustOptions(lt.PERIOD, 1, blocks[1].hash()),
        MockProvider(lt.CHAIN_ID, blocks),
        [],
        LightStore(MemDB()),
    )

    async def go():
        # pin the clock so initialize() accepts the synthetic chain age
        import tendermint_tpu.light.client as client_mod

        orig_now = client_mod._now_ns
        client_mod._now_ns = lambda: lt.NOW
        proxy = LightProxy(lc, backend)
        try:
            await proxy.start()
            async with aiohttp.ClientSession() as sess:
                async def call(method, **params):
                    async with sess.post(
                        f"http://{proxy.addr}/",
                        json={"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": params},
                    ) as resp:
                        body = await resp.json()
                        assert "error" not in body, body
                        return body["result"]

                ni = await call("net_info")
                assert ni["light_client_verified"] is False
                assert ni["n_peers"] == "3"
                hl = await call("health")
                assert hl == {"light_client_verified": False}
                nd = await call("num_unconfirmed_txs")
                assert nd == ["not-a-dict"]  # non-dict: forwarded untouched
                st = await call("status")
                assert "light_client_verified" not in st  # verified route
                assert st["light_client"]["trusted_height"] >= 1
        finally:
            await proxy.stop()
            client_mod._now_ns = orig_now

    run(go())


def test_bench_light_serve_scenario_smoke():
    """The light_serve bench scenario emits the parseable datapoint the
    perf ledger keys on (speedup + throughput + latency percentiles)."""
    import json

    from bench import bench_light_serve

    res = bench_light_serve(heights=5, n_vals=4, clients=4, requests=40,
                            window=0.01)
    json.dumps(res)  # parseable
    for key in ("client_verifs_per_sec", "latency_ms", "speedup",
                "device_flushes", "cache_hits", "seed"):
        assert key in res, key
    assert res["requests"] == 40
    assert res["speedup"] > 0
    assert res["device_flushes"] >= 1
    assert set(res["latency_ms"]) == {"p50", "p99"}
