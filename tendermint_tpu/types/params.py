"""ConsensusParams (reference: types/params.go) — chain-level parameters the
app can adjust at runtime via EndBlock."""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs import protowire as pw

MAX_BLOCK_SIZE_BYTES = 104857600  # 100MB


@dataclass(frozen=True)
class BlockParams:
    max_bytes: int = 22020096  # 21MB (reference default)
    max_gas: int = -1

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.max_bytes)
        w.varint_field(2, self.max_gas)
        return w.bytes()


@dataclass(frozen=True)
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000
    max_bytes: int = 1048576

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.max_age_num_blocks)
        # duration message: seconds(1), nanos(2)
        sec, nanos = divmod(self.max_age_duration_ns, 1_000_000_000)
        d = pw.Writer()
        d.varint_field(1, sec)
        d.varint_field(2, nanos)
        w.message_field(2, d.bytes(), always=True)
        w.varint_field(3, self.max_bytes)
        return w.bytes()


@dataclass(frozen=True)
class ValidatorParams:
    pub_key_types: tuple = ("ed25519",)

    def encode(self) -> bytes:
        w = pw.Writer()
        for t in self.pub_key_types:
            w.string_field(1, t, emit_empty=True)
        return w.bytes()


@dataclass(frozen=True)
class VersionParams:
    app_version: int = 0

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.app_version)
        return w.bytes()


@dataclass(frozen=True)
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)

    def hash(self) -> bytes:
        """Hash of the subset (block+evidence) the reference hashes
        (reference: types/params.go HashConsensusParams)."""
        w = pw.Writer()
        w.varint_field(1, self.block.max_bytes)
        w.varint_field(2, self.block.max_gas)
        w.varint_field(3, self.evidence.max_age_num_blocks)
        w.varint_field(4, self.evidence.max_age_duration_ns)
        return tmhash.sum256(w.bytes())

    def validate_basic(self) -> None:
        if self.block.max_bytes <= 0 or self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError("block.MaxBytes out of range")
        if self.block.max_gas < -1:
            raise ValueError("block.MaxGas must be >= -1")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.MaxAgeNumBlocks must be positive")
        if self.evidence.max_age_duration_ns <= 0:
            raise ValueError("evidence.MaxAgeDuration must be positive")
        if not self.validator.pub_key_types:
            raise ValueError("len(validator.PubKeyTypes) must be > 0")

    def update(self, block=None, evidence=None, validator=None, version=None) -> "ConsensusParams":
        return ConsensusParams(
            block=block or self.block,
            evidence=evidence or self.evidence,
            validator=validator or self.validator,
            version=version or self.version,
        )


DEFAULT_CONSENSUS_PARAMS = ConsensusParams()
