"""LocalChaosNet: an in-process multi-validator net with chaos controls.

The ChaosEngine-facing adapter for soaks: owns N Nodes built by a caller
-supplied factory (so the test controls config — db backend, WAL paths,
plaintext transport), wires the full mesh, and implements the network/process
fault kinds (device kinds are delegated to a DeviceFaultInjector, which is
process-global like the crypto pipeline it faults).

Partitions are enforced at BOTH ends: every switch gets a connection filter
admitting only same-group peer ids (dials, inbound upgrades, and reconnect
attempts all consult it — p2p/switch.py), and existing cross-group links are
dropped. heal() clears the filters and re-dials the mesh, so liveness after
heal exercises the real dial/handshake path, not a kept-alive socket.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Dict, List, Optional, Sequence

from tendermint_tpu.chaos.device import DeviceFaultInjector
from tendermint_tpu.chaos.process import (
    corrupt_wal_tail,
    hard_kill,
    truncate_wal_tail,
)

logger = logging.getLogger("tendermint_tpu.chaos")


class LocalChaosNet:
    def __init__(
        self,
        make_node: Callable[[int], object],
        n: int,
        injector: Optional[DeviceFaultInjector] = None,
    ):
        self.make_node = make_node
        self.n = n
        self.nodes: List[Optional[object]] = [None] * n
        self.injector = injector or DeviceFaultInjector()
        self._groups: Optional[List[set]] = None
        self._id_to_index: Dict[str, int] = {}

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        self.injector.install()
        for i in range(self.n):
            await self._start_node(i)
        await self.dial_mesh()

    async def _start_node(self, i: int) -> None:
        node = self.make_node(i)
        self.nodes[i] = node
        # register + filter BEFORE the listener opens: a node restarted
        # during an active partition must never accept a cross-group
        # connection in the startup window (peers' filters pass unknown ids)
        self._id_to_index[node.node_key.id] = i
        if self._groups is not None:
            self._apply_filter(i)
        await node.start()

    async def dial_mesh(self) -> None:
        for a in self.live_nodes():
            for b in self.live_nodes():
                if a is b or a.switch.peers.has(b.node_key.id):
                    continue
                if not self._allowed(a, b.node_key.id):
                    continue
                try:
                    await a.switch.dial_peers_async(
                        [f"{b.node_key.id}@{b.p2p_addr}"], persistent=True
                    )
                except Exception:
                    logger.exception("chaos mesh dial failed")

    async def stop(self) -> None:
        self.injector.uninstall()
        for node in self.live_nodes():
            try:
                await node.stop()
            except Exception:
                pass

    def live_nodes(self) -> List[object]:
        return [n for n in self.nodes if n is not None]

    # -- device faults (schedule kinds) -------------------------------------

    def device_error(self, count: int) -> None:
        self.injector.arm_errors(count)

    def device_hang(self, seconds: float) -> None:
        self.injector.arm_hang(seconds)

    def shard_error(self, shard: int) -> None:
        """Next sharded dispatch fails at lane slice `shard` (ISSUE 19)."""
        self.injector.arm_shard_error(shard)

    def shard_hang(self, shard: int, seconds: float) -> None:
        """Next sharded dispatch straggles `seconds` at lane slice `shard`."""
        self.injector.arm_shard_hang(shard, seconds)

    def device_lost(self, device) -> None:
        """Mesh device dies: every dispatch including it raises and its
        health probes fail until device_revive. `device` is an index into
        the mesh's device list (or an explicit device string)."""
        self.injector.arm_device_lost(device)

    def device_revive(self, device=None) -> None:
        """Lost device's probes pass again; rejoin cycle can run. An index
        revives whatever device string it resolved to at dispatch time;
        None revives all."""
        self.injector.revive_device(device)

    # -- network faults ------------------------------------------------------

    def _group_of(self, i: int) -> Optional[set]:
        if self._groups is None:
            return None
        for g in self._groups:
            if i in g:
                return g
        return None

    def _allowed(self, node, peer_id: str) -> bool:
        if self._groups is None:
            return True
        me = self._id_to_index.get(node.node_key.id)
        other = self._id_to_index.get(peer_id)
        if me is None or other is None:
            return True
        g = self._group_of(me)
        return g is not None and other in g

    def _apply_filter(self, i: int) -> None:
        node = self.nodes[i]
        if node is None or node.switch is None:
            return
        if self._groups is None:
            node.switch.set_conn_filter(None)
        else:
            node.switch.set_conn_filter(
                lambda peer_id, _node=node: self._allowed(_node, peer_id)
            )

    async def partition(self, groups: Sequence[Sequence[int]]) -> None:
        """Split node indices into isolated groups; cross-group links drop
        and stay down (filters block dial/accept/reconnect) until heal()."""
        self._groups = [set(g) for g in groups]
        for i in range(self.n):
            self._apply_filter(i)
        for node in self.live_nodes():
            for peer in list(node.switch.peers.list()):
                if not self._allowed(node, peer.id):
                    await node.switch.disconnect_peer(peer.id, "chaos partition")

    async def heal(self) -> None:
        self._groups = None
        for i in range(self.n):
            self._apply_filter(i)
        await self.dial_mesh()

    # -- catch-up faults (ISSUE 12) ------------------------------------------

    def _serve_faults(self, target: int):
        """The target node's ServeFaults, installing one on first use.
        Crashed nodes return None (arming a dead server is a no-op, like
        restart() of a live node — a replayed schedule must not abort)."""
        node = self.nodes[target]
        if node is None:
            return None
        from tendermint_tpu.chaos.catchup import install

        sf = getattr(node, "blocksync_reactor", None) and node.blocksync_reactor.serve_faults
        return sf or install(node)

    def peer_stall(self, target: int, seconds: float) -> None:
        """Node `target` silently swallows block requests for `seconds`."""
        sf = self._serve_faults(target)
        if sf is not None:
            sf.arm_block_stall(seconds)

    def peer_lie(self, target: int, count: int) -> None:
        """Node `target` serves its next `count` blocks commit-tampered."""
        sf = self._serve_faults(target)
        if sf is not None:
            sf.arm_block_lies(count)

    def chunk_corrupt(self, target: int, count: int) -> None:
        """Node `target` serves its next `count` snapshot chunks bit-rotted."""
        sf = self._serve_faults(target)
        if sf is not None:
            sf.arm_chunk_corrupt(count)

    # -- adversarial faults (adversarial flush defense) ----------------------

    async def sig_poison(self, target: int, count: int) -> None:
        """Node `target` gossips `count` precheck-passing, verify-failing
        votes (chaos/byzantine.py poison_votes) — the signature-poisoning
        flood the provenance/quarantine defense must absorb. Crashed
        targets no-op (a replayed schedule must not abort)."""
        node = self.nodes[target]
        if node is None:
            return
        from tendermint_tpu.chaos.byzantine import poison_votes

        await poison_votes(node, count)

    # -- process faults ------------------------------------------------------

    async def crash(self, target: int, wal_fault: Optional[str] = None) -> None:
        node = self.nodes[target]
        if node is None:
            return
        wal_path = node.wal.path
        self._id_to_index.pop(node.node_key.id, None)
        self.nodes[target] = None
        await hard_kill(node)
        if wal_fault == "truncate":
            truncate_wal_tail(wal_path)
        elif wal_fault == "corrupt":
            corrupt_wal_tail(wal_path)

    async def restart(self, target: int) -> None:
        if self.nodes[target] is not None:
            return  # already up (e.g. a schedule replayed onto a live node)
        await self._start_node(target)
        await self.dial_mesh()

    # -- invariants ----------------------------------------------------------

    def min_height(self) -> int:
        live = self.live_nodes()
        return min((n.block_store.height for n in live), default=0)

    def max_height(self) -> int:
        return max((n.block_store.height for n in self.live_nodes()), default=0)

    def assert_safety(self) -> None:
        """No two nodes may have committed conflicting blocks at any height —
        THE BFT safety invariant, checked over every height any pair of live
        nodes share."""
        live = self.live_nodes()
        top = max((n.block_store.height for n in live), default=0)
        for h in range(1, top + 1):
            hashes = {}
            for node in live:
                if node.block_store.height < h:
                    continue
                b = node.block_store.load_block(h)
                if b is not None:
                    hashes[node.node_key.id[:8]] = b.hash().hex()
            if len(set(hashes.values())) > 1:
                raise AssertionError(
                    f"SAFETY VIOLATION at height {h}: conflicting commits {hashes}"
                )

    def committed_evidence(self) -> list:
        """All DuplicateVoteEvidence committed in any live node's chain."""
        out = []
        for node in self.live_nodes():
            for h in range(1, node.block_store.height + 1):
                b = node.block_store.load_block(h)
                if b is not None and len(b.evidence) > 0:
                    out.extend(b.evidence)
        return out
