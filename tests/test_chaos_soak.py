"""The 4-validator chaos soak (slow lane; acceptance criteria of the chaos
engine): a seeded schedule of partitions and crash/restarts — with WAL tail
damage — against a net containing one byzantine equivocator. The net must:

  * commit >= 20 heights with ZERO safety violations (no two nodes ever
    commit conflicting blocks at any height),
  * resume progress after the schedule ends (liveness after heal),
  * detect the equivocator and commit its DuplicateVoteEvidence,
  * and the fault schedule must replay bit-for-bit from its seed.

Runs over the plaintext transport + sqlite stores, so it works (and crash/
restart persists state) in minimal containers without the `cryptography`
wheel. Reproduce a run: TMTPU_CHAOS_SEED=<seed> pytest tests/test_chaos_soak.py
(docs/ROBUSTNESS.md has the full recipe)."""

import asyncio
import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

pytestmark = pytest.mark.slow

from tendermint_tpu.chaos import ChaosEngine, ChaosSchedule
from tendermint_tpu.chaos.byzantine import install_equivocator
from tendermint_tpu.chaos.harness import LocalChaosNet

from tests.test_chaos import make_plain_net

SEED = int(os.environ.get("TMTPU_CHAOS_SEED", "20260803"))
TARGET_HEIGHTS = 20


def _soak_schedule():
    kw = dict(
        episodes=5,
        kinds=("partition", "crash"),
        protected=(0,),  # never crash the equivocator: its misbehavior IS the test
        min_episode=2.0,
        max_episode=4.0,
        min_gap=1.0,
        max_gap=2.0,
        start_delay=2.0,
    )
    return ChaosSchedule.generate(SEED, 4, **kw), kw


def test_chaos_soak_partitions_crashes_equivocator(tmp_path):
    sched, kw = _soak_schedule()
    # acceptance: re-running with the same seed reproduces the same schedule
    assert sched == ChaosSchedule.generate(SEED, 4, **kw)
    assert sched.fingerprint() == ChaosSchedule.generate(SEED, 4, **kw).fingerprint()
    assert any(e.kind == "crash" for e in sched)
    assert any(e.kind == "partition" for e in sched)

    async def run():
        make_node = make_plain_net(4, tmp_path, chain="chaos-soak", db_backend="sqlite")
        net = LocalChaosNet(make_node, 4)
        await net.start()
        try:
            byz = net.nodes[0]
            byz_addr = byz.priv_validator.get_pub_key().address()
            install_equivocator(byz)
            start_h = net.max_height()
            engine = ChaosEngine(sched, net)
            task = engine.start()

            loop = asyncio.get_event_loop()
            deadline = loop.time() + 600.0

            def soak_done():
                return (
                    task.done()
                    and net.min_height() >= start_h + TARGET_HEIGHTS
                    and len(net.committed_evidence()) > 0
                )

            while not soak_done():
                if loop.time() > deadline:
                    raise AssertionError(
                        f"soak stalled: schedule_done={task.done()} heights="
                        f"{[n.block_store.height for n in net.live_nodes()]} "
                        f"evidence={len(net.committed_evidence())} "
                        f"engine_errors={engine.errors}"
                    )
                await asyncio.sleep(0.2)
            await task
            assert not engine.errors, engine.errors
            assert len(engine.applied) == len(sched)

            # liveness after heal: the whole net advances further
            assert all(n is not None for n in net.nodes), "a node never restarted"
            h0 = net.max_height()
            while not all(
                n.block_store.height >= h0 + 3 for n in net.live_nodes()
            ):
                if loop.time() > deadline:
                    raise AssertionError("no liveness after heal")
                await asyncio.sleep(0.2)

            # THE safety invariant, across every height any two nodes share
            net.assert_safety()

            # the equivocator's evidence landed in a committed block
            evs = net.committed_evidence()
            assert any(ev.vote_a.validator_address == byz_addr for ev in evs)
            for ev in evs:
                assert ev.vote_a.height == ev.vote_b.height
                assert ev.vote_a.validator_address == ev.vote_b.validator_address

            # chain observatory (ISSUE 8 acceptance): the soak emits a merged
            # fleet report whose proposal->commit waterfall covers ALL nodes
            # on at least one post-heal height
            from tendermint_tpu.tools import chain_observatory as obs

            dump_dir = str(tmp_path / "observatory")
            for n in net.live_nodes():
                obs.write_node_dump(n, dump_dir)
            report = obs.merge(obs.load_dumps(dump_dir))
            labels = {n.node_key.id[:10] for n in net.live_nodes()}
            covered = [
                rec for rec in report["heights"]
                if labels <= set(rec["nodes"])
                and all(rec["nodes"][l]["commit_ms"] is not None for l in labels)
            ]
            assert covered, (
                f"no height's waterfall covered all {len(labels)} nodes: "
                f"{[(r['height'], sorted(r['nodes'])) for r in report['heights']]}"
            )
            # real cross-node propagation evidence reached the merge
            assert report["peer_lag"], "no propagation aggregates in the report"
            (tmp_path / "observatory" / "chain_report.md").write_text(
                obs.render_markdown(report)
            )
        finally:
            await net.stop()

    asyncio.run(run())


def test_crash_restart_node_catches_up(tmp_path):
    """Focused process-fault soak: crash a node hard (WAL tail truncated),
    restart it, and require it to catch back up to the live chain — the
    handshake/blocksync/WAL-replay path under real damage."""

    async def run():
        make_node = make_plain_net(
            3, tmp_path, chain="crash-restart", db_backend="sqlite"
        )
        net = LocalChaosNet(make_node, 3)
        await net.start()
        try:
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 300.0
            while net.min_height() < 3:
                assert loop.time() < deadline, "net never reached height 3"
                await asyncio.sleep(0.1)

            await net.crash(2, wal_fault="truncate")
            assert net.nodes[2] is None
            # the survivors keep committing (2 of 3 validators = 2/3... NOT
            # enough for progress with 3 equal validators? 20*3 > 30*2 holds:
            # 60 == 60 is NOT strictly greater — a 2-of-3 net CANNOT commit.
            # So the dead node stalls the chain; the restart must revive it.
            h_at_crash = net.max_height()
            await asyncio.sleep(1.0)
            await net.restart(2)
            assert net.nodes[2] is not None

            while not (
                net.nodes[2].block_store.height >= h_at_crash + 2
                and net.min_height() >= h_at_crash + 2
            ):
                assert loop.time() < deadline, (
                    f"restarted node stuck at {net.nodes[2].block_store.height} "
                    f"(chain at {net.max_height()})"
                )
                await asyncio.sleep(0.2)
            net.assert_safety()
        finally:
            await net.stop()

    asyncio.run(run())
