"""Tx indexing (reference: state/txindex/indexer.go + kv/kv.go).

IndexerService subscribes to the event bus and indexes TxResults by hash,
height, and app-emitted composite keys for /tx_search."""

from __future__ import annotations

import asyncio
import json
import struct
from typing import List, Optional

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs.kvdb import KVDB
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.libs.pubsub import Query
from tendermint_tpu.types.event_bus import EVENT_TX, EventBus, query_for_event


class TxResult:
    def __init__(self, height: int, index: int, tx: bytes, code: int, data: bytes, log: str, events=None):
        self.height = height
        self.index = index
        self.tx = tx
        self.code = code
        self.data = data
        self.log = log
        self.events = events or []

    def to_json(self) -> str:
        return json.dumps(
            {
                "height": self.height,
                "index": self.index,
                "tx": self.tx.hex(),
                "code": self.code,
                "data": self.data.hex(),
                "log": self.log,
            }
        )

    @classmethod
    def from_json(cls, raw: str) -> "TxResult":
        o = json.loads(raw)
        return cls(o["height"], o["index"], bytes.fromhex(o["tx"]), o["code"], bytes.fromhex(o["data"]), o["log"])


class KVTxIndexer:
    def __init__(self, db: KVDB):
        self.db = db

    def index(self, result: TxResult, composite_keys: Optional[dict] = None) -> None:
        h = tmhash.sum256(result.tx)
        self.db.set(b"TX:hash:" + h, result.to_json().encode())
        self.db.set(
            b"TX:height:" + struct.pack(">q", result.height) + struct.pack(">I", result.index),
            h,
        )
        for key, values in (composite_keys or {}).items():
            for v in values:
                self.db.set(
                    b"TX:event:" + key.encode() + b"=" + v.encode() + b":" + h, h
                )

    def get(self, tx_hash: bytes) -> Optional[TxResult]:
        raw = self.db.get(b"TX:hash:" + tx_hash)
        return TxResult.from_json(raw.decode()) if raw else None

    def by_height(self, height: int) -> List[TxResult]:
        out = []
        for _, h in self.db.iterate_prefix(b"TX:height:" + struct.pack(">q", height)):
            r = self.get(h)
            if r:
                out.append(r)
        return out

    def search(self, key: str, value: str) -> List[TxResult]:
        out = []
        for _, h in self.db.iterate_prefix(b"TX:event:" + key.encode() + b"=" + value.encode() + b":"):
            r = self.get(h)
            if r:
                out.append(r)
        return out


class IndexerService(BaseService):
    """(reference: state/txindex/indexer_service.go; lifecycle via
    libs/service.BaseService like the reference's cmn.BaseService)"""

    def __init__(self, indexer: KVTxIndexer, event_bus: EventBus):
        super().__init__("IndexerService")
        self.indexer = indexer
        self.event_bus = event_bus
        self._task: Optional[asyncio.Task] = None
        self._sub = None

    async def on_start(self) -> None:
        self._sub = self.event_bus.subscribe("tx_index", query_for_event(EVENT_TX), out_capacity=1000)
        self._task = asyncio.create_task(self._run(), name="tx-indexer")

    async def _run(self) -> None:
        try:
            while True:
                msg = await self._sub.next()
                data = msg.data  # EventDataTx
                composite = {
                    k: v for k, v in msg.events.items() if k not in ("tm.event",)
                }
                self.indexer.index(
                    TxResult(
                        data.height,
                        data.index,
                        data.tx,
                        data.result.code,
                        data.result.data,
                        data.result.log,
                    ),
                    composite,
                )
        except (asyncio.CancelledError, RuntimeError):
            pass

    async def on_stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        try:
            self.event_bus.unsubscribe_all("tx_index")
        except Exception:
            pass
