"""Device-level fault injection for the batch-verify pipeline.

Installs into crypto/batch.py's `_device_fault(site)` hook, which every
device entry point calls (RLC submit, RLC result sync, the per-signature
kernel, the circuit breaker's health probe). Armed faults fire on the next
device calls regardless of site — exactly what a sick accelerator looks like
from the host: every dispatch fails or stalls, whichever kernel it carries.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple


class DeviceFaultError(RuntimeError):
    """The injected stand-in for a device/tunnel failure."""


class DeviceFaultInjector:
    """Count-armed fault source. Thread-safe: the consensus event loop, the
    prewarm thread, and the breaker's probe thread can all hit device entry
    points concurrently.

    arm_errors(k): the next k device calls raise DeviceFaultError.
    arm_hang(s):   the next device call sleeps s seconds first (a stall the
                   caller experiences as a slow flush — the breaker's
                   flush-deadline overrun path).
    persistent:    raise on EVERY call until heal() (a dead tunnel).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._errors_left = 0
        self._hangs: List[float] = []
        self._persistent = False
        self._clock = clock
        self.calls = 0  # total device-entry calls observed
        self.fired: List[Tuple[str, str]] = []  # (site, "error"|"hang")

    # -- arming -------------------------------------------------------------

    def arm_errors(self, count: int) -> None:
        with self._lock:
            self._errors_left += max(0, int(count))

    def arm_hang(self, seconds: float) -> None:
        with self._lock:
            self._hangs.append(float(seconds))

    def set_persistent(self, on: bool = True) -> None:
        with self._lock:
            self._persistent = bool(on)

    def heal(self) -> None:
        with self._lock:
            self._errors_left = 0
            self._hangs.clear()
            self._persistent = False

    # -- the hook (crypto/batch.set_device_fault_hook) ----------------------

    def __call__(self, site: str) -> None:
        with self._lock:
            self.calls += 1
            hang: Optional[float] = self._hangs.pop(0) if self._hangs else None
            fire_error = self._persistent or self._errors_left > 0
            if not self._persistent and self._errors_left > 0:
                self._errors_left -= 1
            if hang is not None:
                self.fired.append((site, "hang"))
            if fire_error:
                self.fired.append((site, "error"))
        if hang is not None:
            time.sleep(hang)  # the device call "stalls"
        if fire_error:
            raise DeviceFaultError(f"injected device fault at {site}")

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "DeviceFaultInjector":
        from tendermint_tpu.crypto import batch

        batch.set_device_fault_hook(self)
        return self

    def uninstall(self) -> None:
        from tendermint_tpu.crypto import batch

        batch.set_device_fault_hook(None)
