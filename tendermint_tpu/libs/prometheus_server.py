"""Standalone Prometheus exposition server.

The reference serves /metrics on its own listener bound to
`instrumentation.prometheus_listen_addr` (node/node.go:1105 startPrometheusServer),
independent of the RPC endpoint. This is that listener: a tiny aiohttp app
that renders the node's metrics Registry. The RPC server's /metrics route
(rpc/server.py) stays as a convenience alias.
"""

from __future__ import annotations

from typing import Optional

from aiohttp import web


class PrometheusServer:
    """Serves GET /metrics (and "/") with the text exposition format."""

    def __init__(self, registry, listen_addr: str):
        self.registry = registry
        host, _, port = listen_addr.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self.runner: Optional[web.AppRunner] = None

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/metrics", self._handle)
        app.router.add_get("/", self._handle)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, self.host, self.port)
        await site.start()
        # resolve the actual port (listen_addr may use :0 in tests)
        server = site._server
        if server is not None and server.sockets:
            self.port = server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self.runner is not None:
            await self.runner.cleanup()
            self.runner = None

    async def _handle(self, request: web.Request) -> web.Response:
        return web.Response(
            text=self.registry.expose(),
            content_type="text/plain",
            charset="utf-8",
        )
