"""State sync wire messages (reference: proto/tendermint/statesync/types.proto,
statesync/messages.go). Envelope: oneof field per variant, carried on the
snapshot channel 0x60 (SnapshotsRequest/Response) and chunk channel 0x61
(ChunkRequest/Response)."""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.libs import protowire as pw

# reference: statesync/reactor.go:18-20
SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

# reference: statesync/messages.go:16-17
SNAPSHOT_MSG_SIZE = 4 * 1024 * 1024
CHUNK_MSG_SIZE = 16 * 1024 * 1024


@dataclass(frozen=True)
class SnapshotsRequest:
    FIELD = 1

    def encode_body(self) -> bytes:
        return b""

    @classmethod
    def decode_body(cls, data: bytes) -> "SnapshotsRequest":
        return cls()


@dataclass(frozen=True)
class SnapshotsResponse:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes

    FIELD = 2

    def encode_body(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        w.varint_field(2, self.format)
        w.varint_field(3, self.chunks)
        w.bytes_field(4, self.hash)
        w.bytes_field(5, self.metadata)
        return w.bytes()

    @classmethod
    def decode_body(cls, data: bytes) -> "SnapshotsResponse":
        height = fmt = chunks = 0
        h = meta = b""
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
            elif f == 2:
                fmt = v
            elif f == 3:
                chunks = v
            elif f == 4:
                h = v
            elif f == 5:
                meta = v
        return cls(height, fmt, chunks, h, meta)

    def validate_basic(self) -> None:
        if self.height <= 0:
            raise ValueError("snapshot height must be positive")
        if self.chunks <= 0:
            raise ValueError("snapshot must have at least one chunk")
        if self.chunks > 1 << 20:
            raise ValueError("too many chunks")
        if not self.hash or len(self.hash) > 64:
            raise ValueError("bad snapshot hash")


@dataclass(frozen=True)
class ChunkRequest:
    height: int
    format: int
    index: int

    FIELD = 3

    def encode_body(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        w.varint_field(2, self.format)
        w.varint_field(3, self.index)
        return w.bytes()

    @classmethod
    def decode_body(cls, data: bytes) -> "ChunkRequest":
        height = fmt = index = 0
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
            elif f == 2:
                fmt = v
            elif f == 3:
                index = v
        return cls(height, fmt, index)


@dataclass(frozen=True)
class ChunkResponse:
    height: int
    format: int
    index: int
    chunk: bytes
    missing: bool = False

    FIELD = 4

    def encode_body(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        w.varint_field(2, self.format)
        w.varint_field(3, self.index)
        w.bytes_field(4, self.chunk)
        w.varint_field(5, 1 if self.missing else 0)
        return w.bytes()

    @classmethod
    def decode_body(cls, data: bytes) -> "ChunkResponse":
        height = fmt = index = 0
        chunk = b""
        missing = False
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
            elif f == 2:
                fmt = v
            elif f == 3:
                index = v
            elif f == 4:
                chunk = v
            elif f == 5:
                missing = bool(v)
        return cls(height, fmt, index, chunk, missing)


_TYPES = {c.FIELD: c for c in (SnapshotsRequest, SnapshotsResponse, ChunkRequest, ChunkResponse)}


def encode_message(msg) -> bytes:
    w = pw.Writer()
    w.message_field(msg.FIELD, msg.encode_body(), always=True)
    return w.bytes()


def decode_message(data: bytes):
    for f, _, v in pw.Reader(data):
        cls = _TYPES.get(f)
        if cls is not None:
            return cls.decode_body(v)
    raise ValueError("unknown statesync message")
