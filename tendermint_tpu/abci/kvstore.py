"""In-process example applications (reference: abci/example/kvstore, counter).

KVStoreApplication: key=value transactions, app hash = big-endian encoded tx
count (mirrors the reference's size-based app hash, abci/example/kvstore/kvstore.go:66).
PersistentKVStoreApplication adds validator-update txs ("val:pubkeyhex!power")
and height persistence for handshake/replay testing.
CounterApplication: serial nonce check (abci/example/counter/counter.go:11).
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs.kvdb import KVDB, MemDB

VALIDATOR_TX_PREFIX = b"val:"


class KVStoreApplication(abci.Application):
    def __init__(self, db: Optional[KVDB] = None):
        self.db = db or MemDB()
        self.size = int.from_bytes(self.db.get(b"__size__") or b"\x00", "big")
        self.height = int.from_bytes(self.db.get(b"__height__") or b"\x00", "big")
        self.app_hash = self.db.get(b"__apphash__") or b""
        self.staged: List[tuple] = []

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": self.size}),
            version="0.1.0",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if not req.tx:
            return abci.ResponseCheckTx(code=1, log="empty tx")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if b"=" in req.tx:
            key, value = req.tx.split(b"=", 1)
        else:
            key = value = req.tx
        self.staged.append((key, value))
        events = [
            abci.Event(
                type="app",
                attributes=[(b"creator", b"tendermint_tpu", True), (b"key", key, True)],
            )
        ]
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK, events=events)

    def commit(self) -> abci.ResponseCommit:
        for key, value in self.staged:
            self.db.set(b"kv/" + key, value)
            self.size += 1
        self.staged.clear()
        self.height += 1
        # app hash = encoded size (mirrors reference kvstore.go:113)
        self.app_hash = struct.pack(">Q", self.size)
        self.db.set(b"__size__", self.size.to_bytes(8, "big"))
        self.db.set(b"__height__", self.height.to_bytes(8, "big"))
        self.db.set(b"__apphash__", self.app_hash)
        return abci.ResponseCommit(data=self.app_hash)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "/store" or req.path == "":
            value = self.db.get(b"kv/" + req.data)
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK,
                key=req.data,
                value=value or b"",
                height=self.height,
                log="exists" if value is not None else "does not exist",
            )
        return abci.ResponseQuery(code=1, log=f"unknown path {req.path}")


class PersistentKVStoreApplication(KVStoreApplication):
    """Adds validator updates via "val:<pubkey_hex>!<power>" txs
    (reference: abci/example/kvstore/persistent_kvstore.go)."""

    def __init__(self, db: Optional[KVDB] = None):
        super().__init__(db)
        self.val_updates: List[abci.ValidatorUpdate] = []

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for v in req.validators:
            self._set_validator(v)
        return abci.ResponseInitChain()

    def _set_validator(self, v: abci.ValidatorUpdate) -> None:
        key = b"valkey/" + v.pub_key_bytes
        if v.power == 0:
            self.db.delete(key)
        else:
            self.db.set(key, str(v.power).encode())

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            body = req.tx[len(VALIDATOR_TX_PREFIX):]
            try:
                pubkey_hex, power_s = body.split(b"!", 1)
                pubkey = bytes.fromhex(pubkey_hex.decode())
                power = int(power_s)
            except Exception:
                return abci.ResponseDeliverTx(code=2, log="invalid validator tx")
            if len(pubkey) != 32 or power < 0:
                return abci.ResponseDeliverTx(code=2, log="invalid validator tx")
            update = abci.ValidatorUpdate("ed25519", pubkey, power)
            self.val_updates.append(update)
            self._set_validator(update)
            return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)
        return super().deliver_tx(req)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        updates, self.val_updates = self.val_updates, []
        return abci.ResponseEndBlock(validator_updates=updates)


class CounterApplication(abci.Application):
    """Serial-nonce app (reference: abci/example/counter/counter.go)."""

    def __init__(self, serial: bool = True):
        self.serial = serial
        self.tx_count = 0
        self.height = 0

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=f"txs:{self.tx_count}", last_block_height=self.height,
            last_block_app_hash=(
                struct.pack(">Q", self.tx_count) if self.height else b""
            ),
        )

    def _check_value(self, tx: bytes, expected: int) -> bool:
        if len(tx) > 8:
            return False
        value = int.from_bytes(tx, "big")
        return value == expected

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if self.serial and not self._check_value(req.tx, self.tx_count):
            return abci.ResponseCheckTx(code=2, log="invalid nonce")
        return abci.ResponseCheckTx()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if self.serial and not self._check_value(req.tx, self.tx_count):
            return abci.ResponseDeliverTx(code=2, log="invalid nonce")
        self.tx_count += 1
        return abci.ResponseDeliverTx()

    def commit(self) -> abci.ResponseCommit:
        self.height += 1
        if self.tx_count == 0:
            return abci.ResponseCommit()
        return abci.ResponseCommit(data=struct.pack(">Q", self.tx_count))
