"""Device-level fault injection for the batch-verify pipeline.

Installs into crypto/batch.py's `_device_fault(site)` hook, which every
device entry point calls (RLC submit, RLC result sync, the per-signature
kernel, the circuit breaker's health probe). Armed faults fire on the next
device calls regardless of site — exactly what a sick accelerator looks like
from the host: every dispatch fails or stalls, whichever kernel it carries.

Shard-targeted faults (ISSUE 19) additionally install into
parallel/sharded.py's shard-fault hook, which every SHARDED submit site
calls with the participating device list — so a chaos schedule can kill
exactly one lane slice of one mesh dispatch:

    shard_error {shard}          the next sharded dispatch raises a
                                 ShardFaultError attributed to that shard
    shard_hang  {shard, seconds} the next sharded dispatch stalls first
                                 (feeds the health model's stall scoring)
    device_lost {device}         EVERY dispatch that includes that device
                                 raises, and its health probes fail, until
                                 heal()/revive_device() — a preempted chip

The injector also registers a probe intercept with the mesh health manager
(parallel/health.py), so a "lost" device keeps failing its rejoin probes —
the full death/probation/rejoin cycle is drivable from one schedule.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class DeviceFaultError(RuntimeError):
    """The injected stand-in for a device/tunnel failure."""


class DeviceFaultInjector:
    """Count-armed fault source. Thread-safe: the consensus event loop, the
    prewarm thread, and the breaker's probe thread can all hit device entry
    points concurrently.

    arm_errors(k): the next k device calls raise DeviceFaultError.
    arm_hang(s):   the next device call sleeps s seconds first (a stall the
                   caller experiences as a slow flush — the breaker's
                   flush-deadline overrun path).
    persistent:    raise on EVERY call until heal() (a dead tunnel).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._errors_left = 0
        self._hangs: List[float] = []
        self._persistent = False
        self._clock = clock
        self.calls = 0  # total device-entry calls observed
        self.fired: List[Tuple[str, str]] = []  # (site, "error"|"hang")
        # Shard-targeted state (sharded.set_shard_fault_hook); a shard index
        # is a LANE SLICE of the mesh dispatch, a lost device is a STRING key
        # matched against the participating device list.
        self._shard_errors: List[int] = []  # one-shot, by shard index
        self._shard_hangs: List[Tuple[int, float]] = []  # (shard, seconds)
        self._lost_indices: List[int] = []  # pending: resolve at next dispatch
        self._lost_devices: set = set()  # resolved device strings
        self._lost_by_index: Dict[int, str] = {}  # index -> resolved string
        self.shard_calls = 0  # total sharded-submit-site calls observed

    # -- arming -------------------------------------------------------------

    def arm_errors(self, count: int) -> None:
        with self._lock:
            self._errors_left += max(0, int(count))

    def arm_hang(self, seconds: float) -> None:
        with self._lock:
            self._hangs.append(float(seconds))

    def set_persistent(self, on: bool = True) -> None:
        with self._lock:
            self._persistent = bool(on)

    def arm_shard_error(self, shard: int) -> None:
        """The next sharded dispatch raises, attributed to `shard` (a lane
        slice index into the participating device list)."""
        with self._lock:
            self._shard_errors.append(int(shard))

    def arm_shard_hang(self, shard: int, seconds: float) -> None:
        """The next sharded dispatch stalls `seconds` first — the health
        model sees a slow flush and scores a stall strike on `shard`."""
        with self._lock:
            self._shard_hangs.append((int(shard), float(seconds)))

    def arm_device_lost(self, device) -> None:
        """EVERY sharded dispatch including `device` raises, and its health
        probes fail, until heal()/revive_device(). `device` may be a device
        string (matched exactly) or an int index (resolved against the
        participating device list at the next dispatch)."""
        with self._lock:
            if isinstance(device, int):
                self._lost_indices.append(device)
            else:
                self._lost_devices.add(str(device))

    def revive_device(self, device=None) -> None:
        """Un-lose a device (or all, if None): its probes pass again, so the
        health model's rejoin cycle can run. Accepts the same index/string
        forms as arm_device_lost (an index revives whatever string it
        resolved to at dispatch time)."""
        with self._lock:
            if device is None:
                self._lost_indices.clear()
                self._lost_devices.clear()
                self._lost_by_index.clear()
            elif isinstance(device, int):
                if device in self._lost_indices:
                    self._lost_indices.remove(device)
                key = self._lost_by_index.pop(device, None)
                if key is not None:
                    self._lost_devices.discard(key)
            else:
                self._lost_devices.discard(str(device))
                self._lost_by_index = {
                    i: k for i, k in self._lost_by_index.items() if k != str(device)
                }

    def lost_devices(self) -> List[str]:
        with self._lock:
            return sorted(self._lost_devices)

    def heal(self) -> None:
        with self._lock:
            self._errors_left = 0
            self._hangs.clear()
            self._persistent = False
            self._shard_errors.clear()
            self._shard_hangs.clear()
            self._lost_indices.clear()
            self._lost_devices.clear()
            self._lost_by_index.clear()

    # -- the hook (crypto/batch.set_device_fault_hook) ----------------------

    def __call__(self, site: str) -> None:
        with self._lock:
            self.calls += 1
            hang: Optional[float] = self._hangs.pop(0) if self._hangs else None
            fire_error = self._persistent or self._errors_left > 0
            if not self._persistent and self._errors_left > 0:
                self._errors_left -= 1
            if hang is not None:
                self.fired.append((site, "hang"))
            if fire_error:
                self.fired.append((site, "error"))
        if hang is not None:
            time.sleep(hang)  # the device call "stalls"
        if fire_error:
            raise DeviceFaultError(f"injected device fault at {site}")

    # -- the shard hook (parallel/sharded.set_shard_fault_hook) -------------

    def shard_fault(self, site: str, devices) -> None:
        """Called by every SHARDED submit site with the participating device
        list. Raises sharded.ShardFaultError carrying the shard index and
        device string, so the health model attributes the fault to exactly
        one chip instead of probing the whole mesh."""
        from tendermint_tpu.parallel.sharded import ShardFaultError

        keys = [str(d) for d in devices]
        with self._lock:
            self.shard_calls += 1
            # Resolve index-armed losses against this dispatch's device list
            # (first sharded dispatch after arming names the victim).
            while self._lost_indices:
                idx = self._lost_indices.pop(0)
                if 0 <= idx < len(keys):
                    self._lost_devices.add(keys[idx])
                    self._lost_by_index[idx] = keys[idx]
            lost_here = [i for i, k in enumerate(keys) if k in self._lost_devices]
            shard_err: Optional[int] = (
                self._shard_errors.pop(0) if self._shard_errors else None
            )
            shard_hang: Optional[Tuple[int, float]] = (
                self._shard_hangs.pop(0) if self._shard_hangs else None
            )
            if lost_here:
                self.fired.append((site, f"device_lost:{keys[lost_here[0]]}"))
            if shard_hang is not None:
                self.fired.append((site, f"shard_hang:{shard_hang[0]}"))
            if shard_err is not None:
                self.fired.append((site, f"shard_error:{shard_err}"))
        if shard_hang is not None:
            time.sleep(shard_hang[1])  # one shard "straggles"
        if lost_here:
            i = lost_here[0]
            raise ShardFaultError(site, i, keys[i])
        if shard_err is not None:
            i = max(0, min(int(shard_err), len(keys) - 1)) if keys else 0
            dev = keys[i] if keys else f"shard{shard_err}"
            raise ShardFaultError(site, i, dev)

    def probe_intercept(self, key: str) -> None:
        """Installed into MESH_HEALTH: a lost device keeps failing its rejoin
        probes until revive_device()/heal() — probation is chaos-drivable."""
        with self._lock:
            lost = key in self._lost_devices
        if lost:
            raise DeviceFaultError(f"injected probe failure on lost device {key}")

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "DeviceFaultInjector":
        from tendermint_tpu.crypto import batch
        from tendermint_tpu.parallel import health, sharded

        batch.set_device_fault_hook(self)
        sharded.set_shard_fault_hook(self.shard_fault)
        health.MESH_HEALTH.set_probe_intercept(self.probe_intercept)
        return self

    def uninstall(self) -> None:
        from tendermint_tpu.crypto import batch
        from tendermint_tpu.parallel import health, sharded

        batch.set_device_fault_hook(None)
        sharded.set_shard_fault_hook(None)
        health.MESH_HEALTH.set_probe_intercept(None)
