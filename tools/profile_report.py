#!/usr/bin/env python
"""Standalone runner for the profiler-trace analyzer.

Renders a per-kernel / per-fused-stage time table from a libs/profiler.py
capture directory (or any jax profile dump); the implementation lives in
tendermint_tpu/tools/profile_report.py. Usage:

    python tools/profile_report.py <capture-dir-or-file> [--top N] [--json OUT]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from tendermint_tpu.tools.profile_report import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
