"""Chain observatory (ISSUE 8): cross-node trace propagation, skewed-clock
honesty, timeline cross-node fields, and the fleet merge.

Tier-1 throughout: the fixture-driven merge tests need no net at all, and
the end-to-end test runs a fast 4-node plaintext in-process net (same
harness as the chaos smoke) — real gossip, real trace stamps, real dumps,
one merged report covering every node."""

import asyncio
import json
import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.config.config import SLOConfig
from tendermint_tpu.consensus.messages import (
    HasVoteMessage,
    NewRoundStepMessage,
    TraceContext,
    decode_message,
    decode_message_traced,
    encode_message,
)
from tendermint_tpu.consensus.reactor import propagation_latency
from tendermint_tpu.consensus.timeline import (
    MAX_ORIGINS_PER_ROUND,
    MAX_ROUNDS_PER_HEIGHT,
    OVERFLOW_ORIGIN,
    ConsensusTimeline,
)
from tendermint_tpu.libs import metrics as M
from tendermint_tpu.libs.slo import SLOEngine
from tendermint_tpu.tools import chain_observatory as obs
from tendermint_tpu.types.basic import SignedMsgType

NODE_A = "aa" * 20
NODE_B = "bb" * 20
NODE_C = "cc" * 20


# ---------------------------------------------------------------------------
# wire format: TraceContext on the consensus envelope


def test_trace_context_roundtrip_and_forward():
    t = TraceContext(NODE_A, 1722700000.123456, 0)
    rt = TraceContext.decode(t.encode())
    assert rt.origin == NODE_A
    assert rt.hops == 0
    # wall clock rides as integer microseconds
    assert abs(rt.origin_ts - t.origin_ts) < 1e-5
    f = t.forwarded()
    assert (f.origin, f.hops) == (NODE_A, 1)
    assert abs(f.origin_ts - t.origin_ts) < 1e-12
    # encode is memoized per frozen instance
    assert t.encode() is t.encode()


def test_traced_envelope_backward_and_forward_compatible():
    """The trace suffix must be invisible to the legacy decoder (WAL
    replayer, old peers) and recoverable by the traced one; an untraced
    envelope decodes with trace None."""
    msg = NewRoundStepMessage(7, 0, 1, 3, -1)
    plain = encode_message(msg)
    traced = encode_message(msg, trace=TraceContext(NODE_B, 1722700001.5, 2))
    # traced envelope = plain envelope + appended trace field
    assert traced.startswith(plain)
    assert len(traced) > len(plain)
    # legacy decoder: same message, trace ignored
    assert decode_message(traced) == msg
    # traced decoder: both
    m2, tctx = decode_message_traced(traced)
    assert m2 == msg
    assert tctx.origin == NODE_B and tctx.hops == 2
    # untraced envelope through the traced decoder
    m3, none = decode_message_traced(plain)
    assert m3 == msg and none is None


def test_has_vote_batch_shares_one_trace_stamp():
    tr = TraceContext(NODE_A, 1722700002.0, 0)
    msgs = [
        HasVoteMessage(5, 0, SignedMsgType.PREVOTE, i) for i in range(3)
    ]
    payloads = [encode_message(m, trace=tr) for m in msgs]
    for p, m in zip(payloads, msgs):
        got, tctx = decode_message_traced(p)
        assert got == m
        assert tctx == tr


# ---------------------------------------------------------------------------
# skewed-clock honesty


def test_propagation_latency_never_negative_after_skew_correction():
    """A peer with a FAST clock stamps origin_ts in the future; without
    correction the raw latency is negative. The skew estimate restores the
    true latency, and residual error can never push the result below 0."""
    # origin's clock runs 2s ahead: it stamped t=102 when true time was 100;
    # we receive at 100.05 -> raw latency -1.95s
    recv, origin_ts = 100.05, 102.0
    # skew = remote - local = +2.0; corrected: 100.05 - 102.0 + 2.0 = 0.05
    assert propagation_latency(recv, origin_ts, 2.0) == pytest.approx(0.05)
    # no skew estimate (legacy peer): clamped, never negative
    assert propagation_latency(recv, origin_ts, None) == 0.0
    # over-correction (skew error past the true latency): still clamped
    assert propagation_latency(recv, origin_ts, 1.9) == 0.0
    # slow origin clock hides latency; correction restores it
    assert propagation_latency(100.5, 99.0, -1.0) == pytest.approx(0.5)


def test_skew_sample_min_rtt_wins_and_drift_tracks():
    """MConnection keeps the minimum-RTT sample (tightest ±RTT/2 bound) and
    only nudges by EWMA on worse-RTT samples so drift still tracks."""
    from tendermint_tpu.p2p.conn.connection import MConnection

    mc = object.__new__(MConnection)
    mc._skew_s = None
    mc._skew_rtt_s = None
    mc._skew_samples = 0

    # first sample: t0=10, t2=12.005, t3=10.01 -> offset = 12.005 - 10.005 = 2.0
    mc._record_skew_sample(10.0, 12.005, 10.01, rtt_s=0.01)
    assert mc.clock_skew() == pytest.approx(2.0)
    assert mc._skew_rtt_s == 0.01

    # worse-RTT sample with a wildly different offset: EWMA nudge only
    mc._record_skew_sample(20.0, 25.0, 20.5, rtt_s=0.5)  # offset 4.75
    assert 2.0 < mc.clock_skew() < 2.5
    assert mc._skew_rtt_s == 0.01  # kept bound unchanged

    # equal-or-better RTT: replaces outright
    mc._record_skew_sample(30.0, 32.1, 30.002, rtt_s=0.002)
    assert mc.clock_skew() == pytest.approx(32.1 - 30.001)
    assert mc._skew_samples == 3


# ---------------------------------------------------------------------------
# timeline cross-node fields


def test_timeline_proposal_first_seen_and_parts_fanout():
    tl = ConsensusTimeline()
    tl.record_proposal_propagation(5, 0, NODE_A, 0.040, hops=0, ts=100.0)
    # a duplicate receipt later must not overwrite first-seen
    tl.record_proposal_propagation(5, 0, NODE_B, 0.500, hops=1, ts=100.6)
    tl.record_block_part(5, 0, latency_s=0.002, ts=100.01)
    tl.record_block_part(5, 0, latency_s=0.020, ts=100.09)
    rec = tl.dump()[0]
    prop = rec["propagation"][0]
    assert prop["proposal_first_seen_ms"] == 40.0
    assert prop["proposal_origin"] == NODE_A
    assert prop["proposal_hops"] == 0
    assert prop["proposal_receipts"] == 2
    assert prop["parts"] == 2
    assert prop["parts_fanout_s"] == pytest.approx(0.08)
    # 2ms lands in the <=5ms bucket, 20ms in the <=25ms bucket
    assert prop["part_latency_ms"][1] == 1
    assert prop["part_latency_ms"][3] == 1


def test_timeline_vote_origin_histograms_and_cap():
    tl = ConsensusTimeline()
    tl.record_vote_origin(3, 0, "PREVOTE", NODE_A, latency_s=0.004)
    tl.record_vote_origin(3, 0, "PREVOTE", NODE_A, latency_s=0.300)
    tl.record_vote_origin(3, 0, "PRECOMMIT", NODE_B, latency_s=0.020)
    votes = tl.dump()[0]["votes"][0]
    a = votes["by_origin"][NODE_A]
    assert a["prevote"] == 2 and a["precommit"] == 0
    assert a["max_ms"] == 300.0
    assert sum(a["latency_ms"]) == 2
    assert votes["by_origin"][NODE_B]["precommit"] == 1

    # remote-controlled cardinality is capped into the overflow bucket
    tl2 = ConsensusTimeline()
    for i in range(MAX_ORIGINS_PER_ROUND + 10):
        tl2.record_vote_origin(1, 0, "PREVOTE", f"origin-{i:04d}", latency_s=0.001)
    by_origin = tl2.dump()[0]["votes"][0]["by_origin"]
    assert len(by_origin) == MAX_ORIGINS_PER_ROUND + 1
    assert by_origin[OVERFLOW_ORIGIN]["prevote"] == 10

    # round keys arrive from the wire before validation: capped per height
    tl3 = ConsensusTimeline()
    for r in range(MAX_ROUNDS_PER_HEIGHT + 10):
        tl3.record_vote_origin(1, r, "PREVOTE", NODE_A, latency_s=0.001)
        tl3.record_proposal_propagation(1, r, NODE_A, 0.01, ts=1.0)
        tl3.record_block_part(1, r, latency_s=0.01, ts=1.0)
    rec = tl3.dump()[0]
    assert len(rec["votes"]) == MAX_ROUNDS_PER_HEIGHT
    assert len(rec["propagation"]) == MAX_ROUNDS_PER_HEIGHT


def test_timeline_peer_stats_ranking_and_skew_accounting():
    tl = ConsensusTimeline()
    for _ in range(4):
        tl.record_hop(NODE_A, "vote", 0.002, skew_corrected=True)
    tl.record_hop(NODE_B, "vote", 0.250, skew_corrected=False)
    tl.record_hop(NODE_B, "proposal", 0.050, skew_corrected=True)
    stats = tl.peer_stats()
    # worst origin (by mean over all kinds) first
    assert list(stats) == [NODE_B, NODE_A]
    b = stats[NODE_B]
    assert b["kinds"]["vote"]["count"] == 1
    assert b["kinds"]["vote"]["mean_ms"] == 250.0
    assert b["skew_corrected"] == 1 and b["uncorrected"] == 1
    a = stats[NODE_A]
    assert a["kinds"]["vote"]["count"] == 4
    assert a["uncorrected"] == 0
    tl.clear()
    assert tl.peer_stats() == {}


# ---------------------------------------------------------------------------
# fleet merge from dump fixtures (offline mode — no net, no RPC)


def _slo_snapshot(tripped: bool) -> dict:
    cfg = SLOConfig(window_fast=10.0, window_slow=100.0, min_samples=3, target=0.9)
    eng = SLOEngine(cfg)
    seconds = 5.0 if tripped else 0.01
    for i in range(6):
        eng.observe("proposal_propagation", seconds, ts=100.0 + i)
    return eng.snapshot(now=107.0)


def _fixture_dump(node_id, *, t0, recv_lat, commit_off, proposer=None,
                  tripped=False) -> dict:
    """One node's observatory dump for heights 10..11, built through the
    REAL producers (ConsensusTimeline + SLOEngine) so the fixtures cannot
    drift from capture_node_dump's shape."""
    tl = ConsensusTimeline()
    for h in (10, 11):
        base = t0 + (h - 10) * 1.0
        tl.record_step(h, 0, "PROPOSE", ts=base)
        tl.record_proposal(h, 0, ts=base + recv_lat)
        if proposer is not None:
            tl.record_proposal_propagation(h, 0, proposer, recv_lat, hops=0, ts=base + recv_lat)
            tl.record_hop(proposer, "proposal", recv_lat, skew_corrected=True)
        tl.record_step(h, 0, "PREVOTE", ts=base + recv_lat + 0.01)
        tl.record_step(h, 0, "PRECOMMIT", ts=base + commit_off * 0.6)
        tl.record_step(h, 0, "COMMIT", ts=base + commit_off * 0.9)
        tl.record_commit(h, 0, txs=0, ts=base + commit_off)
    return {
        "observatory_dump": obs.DUMP_VERSION,
        "node_id": node_id,
        "moniker": f"n-{node_id[:4]}",
        "timeline": {
            "heights": tl.dump(),
            "propagation_peers": tl.peer_stats(),
        },
        "slo": _slo_snapshot(tripped),
    }


def _fixture_fleet(tripped=False):
    # A proposes; B is a fast receiver, C a slow one
    return [
        _fixture_dump(NODE_A, t0=200.0, recv_lat=0.0, commit_off=0.50,
                      tripped=tripped),
        _fixture_dump(NODE_B, t0=200.0, recv_lat=0.020, commit_off=0.52,
                      proposer=NODE_A),
        _fixture_dump(NODE_C, t0=200.0, recv_lat=0.200, commit_off=0.70,
                      proposer=NODE_A),
    ]


def test_merge_waterfall_proposer_and_slowest_link():
    report = obs.merge(_fixture_fleet())
    assert [n["node"] for n in report["nodes"]] == [
        NODE_A[:10], NODE_B[:10], NODE_C[:10]
    ]
    assert len(report["heights"]) == 2
    h10 = report["heights"][0]
    assert h10["height"] == 10
    # the proposer is attributed from the receivers' propagation origin
    assert h10["proposer"] == NODE_A[:10]
    rows = h10["nodes"]
    assert set(rows) == {NODE_A[:10], NODE_B[:10], NODE_C[:10]}
    # waterfall offsets are ms from the proposer's own proposal record
    assert rows[NODE_A[:10]]["proposal_recv_ms"] == 0.0
    assert rows[NODE_B[:10]]["proposal_recv_ms"] == pytest.approx(20.0)
    assert rows[NODE_C[:10]]["proposal_recv_ms"] == pytest.approx(200.0)
    assert rows[NODE_C[:10]]["commit_ms"] == pytest.approx(700.0)
    # every stage of the waterfall is populated for every node
    for row in rows.values():
        for key in ("prevote_quorum_ms", "precommit_quorum_ms", "commit_ms"):
            assert row[key] is not None
    assert h10["first_peer_receipt_ms"] == pytest.approx(20.0)
    assert h10["last_peer_receipt_ms"] == pytest.approx(200.0)
    assert h10["slowest_link"] is not None
    # peer lag ranking: NODE_A is the only traced origin, observed by B
    # (20ms proposal hops) and C (200ms), one per height — the merged mean
    # folds both observers' per-kind aggregates
    lag = report["peer_lag"][0]
    assert lag["origin"] == NODE_A[:10]
    assert lag["observers"] == 2
    assert lag["msgs"] == 4
    assert lag["mean_ms"] == pytest.approx(110.0)
    assert lag["max_ms"] == pytest.approx(200.0)
    # healthy fleet: no guard tripped
    assert report["slo_any_tripped"] is False
    verdicts = {(e["node"], e["objective"]): e for e in report["slo"]}
    assert verdicts[(NODE_A[:10], "proposal_propagation")]["verdict"] == "ok"


def test_merge_flags_tripped_slo_and_render():
    report = obs.merge(_fixture_fleet(tripped=True))
    assert report["slo_any_tripped"] is True
    tripped = [e for e in report["slo"] if e["tripped"]]
    assert tripped and tripped[0]["node"] == NODE_A[:10]
    assert tripped[0]["objective"] == "proposal_propagation"
    md = obs.render_markdown(report)
    assert "height 10" in md and "height 11" in md
    assert "ANY GUARD TRIPPED" in md
    assert "slowest link" in md
    assert NODE_A[:10] in md


def test_cli_offline_merge_and_check_exit_codes(tmp_path, capsys):
    """main() --dumps: reads observatory_*.json, writes chain_report.{json,md},
    exit 0 when budgets held, exit 2 under --check with a tripped guard, and
    a corrupt dump degrades to a load_error row instead of killing the run."""
    dump_dir = tmp_path / "dumps"
    dump_dir.mkdir()
    for i, doc in enumerate(_fixture_fleet()):
        (dump_dir / f"{obs.DUMP_PREFIX}{i}.json").write_text(json.dumps(doc))
    out = tmp_path / "report"
    rc = obs.main(["--dumps", str(dump_dir), "--out", str(out), "--check"])
    assert rc == 0
    report = json.loads((out / "chain_report.json").read_text())
    assert len(report["heights"]) == 2
    assert (out / "chain_report.md").read_text().startswith("# Chain observatory")

    # tripped fleet + --check -> exit 2; without --check -> exit 0
    for i, doc in enumerate(_fixture_fleet(tripped=True)):
        (dump_dir / f"{obs.DUMP_PREFIX}{i}.json").write_text(json.dumps(doc))
    assert obs.main(["--dumps", str(dump_dir), "--out", str(out), "--check"]) == 2
    assert obs.main(["--dumps", str(dump_dir), "--out", str(out)]) == 0

    # corrupt dump: survives as a load_error node row
    (dump_dir / f"{obs.DUMP_PREFIX}zz.json").write_text("{not json")
    assert obs.main(["--dumps", str(dump_dir), "--out", str(out)]) == 0
    report = json.loads((out / "chain_report.json").read_text())
    assert any(n.get("load_error") for n in report["nodes"])

    # empty dir: explicit failure, not an empty report
    empty = tmp_path / "empty"
    empty.mkdir()
    assert obs.main(["--dumps", str(empty), "--out", str(out)]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# end-to-end: a live 4-node net -> dumps -> one merged report


def test_observatory_e2e_4node_net(tmp_path):
    """The acceptance pipeline at tier-1 scale: a 4-validator plaintext net
    commits a few heights with trace stamps riding every gossiped message;
    each node's dump is captured in-process and merged into one report whose
    waterfall covers ALL nodes, with real propagation evidence and passing
    SLO verdicts — then injected over-budget propagation latency trips one
    node's guard and --check turns red."""
    from tests.test_chaos import make_plain_net, _wait_heights

    async def run():
        make_node = make_plain_net(4, tmp_path, chain="observatory-e2e")
        nodes = [make_node(i) for i in range(4)]
        for n in nodes:
            await n.start()
        try:
            for a in nodes:
                for b in nodes:
                    if a is not b and not a.switch.peers.has(b.node_key.id):
                        await a.switch.dial_peers_async(
                            [f"{b.node_key.id}@{b.p2p_addr}"], persistent=True
                        )

            class _NetView:
                def live_nodes(self):
                    return nodes

            await _wait_heights(
                _NetView(),
                lambda: all(n.block_store.height >= 3 for n in nodes),
            )
            dump_dir = tmp_path / "observatory"
            for n in nodes:
                obs.write_node_dump(n, str(dump_dir))
        finally:
            for n in nodes:
                await n.stop()
        return nodes

    nodes = asyncio.run(run())
    labels = {n.node_key.id[:10] for n in nodes}

    dump_dir = str(tmp_path / "observatory")
    dumps = obs.load_dumps(dump_dir)
    assert len(dumps) == 4
    report = obs.merge(dumps)
    assert not report["slo_any_tripped"], report["slo"]

    # the waterfall covers all 4 nodes on at least one committed height
    covered = [
        rec for rec in report["heights"]
        if set(rec["nodes"]) == labels
        and all(r["commit_ms"] is not None for r in rec["nodes"].values())
    ]
    assert covered, f"no height covered all nodes: {report['heights']}"
    rec = covered[-1]
    assert rec["proposer"] in labels
    # non-proposers saw the proposal through gossip: real propagation
    # evidence (first-seen latency + hop count) reached the merge
    traced = [
        r for label, r in rec["nodes"].items()
        if r["proposal_first_seen_ms"] is not None
    ]
    assert traced, rec
    assert all(r["proposal_hops"] is not None for r in traced)
    # per-origin vote/hop aggregates merged from every observer
    assert report["peer_lag"], "no propagation aggregates reached the report"
    assert {e["origin"] for e in report["peer_lag"]} <= labels | {"?"}

    # every node held its declared budgets on the clean run
    assert all(not e["tripped"] for e in report["slo"])

    # inject over-budget propagation latency into node0's engine (the
    # burn-rate guard proof against a REAL engine fed by this run), re-dump,
    # re-merge: the report flags it and --check exits 2
    victim = nodes[0]
    for _ in range(max(victim.slo.min_samples, 8)):
        victim.slo.observe("proposal_propagation", 99.0)
    assert victim.slo.evaluate()["proposal_propagation"]["tripped"]
    obs.write_node_dump(victim, dump_dir)
    rc = obs.main([
        "--dumps", dump_dir, "--out", str(tmp_path / "report"), "--check",
    ])
    assert rc == 2
    merged = json.loads(
        (tmp_path / "report" / "chain_report.json").read_text()
    )
    assert merged["slo_any_tripped"] is True
