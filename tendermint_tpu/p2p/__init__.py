"""P2P fabric: authenticated multiplexed connections, switch/reactor routing,
peer exchange (reference: p2p/)."""

from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor, MConnection
from tendermint_tpu.p2p.key import NodeKey, pubkey_to_id
from tendermint_tpu.p2p.node_info import NodeInfo, parse_addr
from tendermint_tpu.p2p.peer import Peer, PeerSet
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import MultiplexTransport

__all__ = [
    "ChannelDescriptor",
    "MConnection",
    "MultiplexTransport",
    "NodeInfo",
    "NodeKey",
    "Peer",
    "PeerSet",
    "Reactor",
    "Switch",
    "parse_addr",
    "pubkey_to_id",
]
