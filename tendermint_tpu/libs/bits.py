"""BitArray: vote/part presence tracking and gossip set-difference
(reference: libs/bits/bit_array.go).

Used by PartSet assembly tracking, consensus PeerState (which votes/parts a
peer has), and the gossip routines' pick-random-from-difference. asyncio is
single-threaded per loop, so no lock is needed (the reference's mutex guards
goroutine concurrency)."""

from __future__ import annotations

import random
from typing import List, Optional


class BitArray:
    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bits")
        self.bits = bits
        self._elems = bytearray((bits + 7) // 8)

    @classmethod
    def from_bools(cls, bools) -> "BitArray":
        ba = cls(len(bools))
        for i, b in enumerate(bools):
            if b:
                ba.set_index(i, True)
        return ba

    def size(self) -> int:
        return self.bits

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.bits:
            return False
        return bool(self._elems[i // 8] & (1 << (i % 8)))

    def set_index(self, i: int, v: bool) -> bool:
        if i < 0 or i >= self.bits:
            return False
        if v:
            self._elems[i // 8] |= 1 << (i % 8)
        else:
            self._elems[i // 8] &= ~(1 << (i % 8)) & 0xFF
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._elems = bytearray(self._elems)
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        out = BitArray(max(self.bits, other.bits))
        for i in range(len(out._elems)):
            a = self._elems[i] if i < len(self._elems) else 0
            b = other._elems[i] if i < len(other._elems) else 0
            out._elems[i] = a | b
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self.bits, other.bits))
        for i in range(len(out._elems)):
            out._elems[i] = self._elems[i] & other._elems[i]
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.bits)
        for i in range(len(out._elems)):
            out._elems[i] = ~self._elems[i] & 0xFF
        out._mask_tail()
        return out

    def _mask_tail(self) -> None:
        rem = self.bits % 8
        if rem and self._elems:
            self._elems[-1] &= (1 << rem) - 1

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (reference: bit_array.go Sub)."""
        out = self.copy()
        for i in range(min(len(out._elems), len(other._elems))):
            out._elems[i] &= ~other._elems[i] & 0xFF
        return out

    def is_empty(self) -> bool:
        return all(b == 0 for b in self._elems)

    def is_full(self) -> bool:
        if self.bits == 0:
            return True
        full = self.bits // 8
        if any(self._elems[i] != 0xFF for i in range(full)):
            return False
        rem = self.bits % 8
        if rem:
            return self._elems[full] == (1 << rem) - 1
        return True

    def pick_random(self) -> Optional[int]:
        """Random set bit index, or None (reference: bit_array.go PickRandom)."""
        ones = self.get_true_indices()
        if not ones:
            return None
        return random.choice(ones)

    def get_true_indices(self) -> List[int]:
        return [i for i in range(self.bits) if self.get_index(i)]

    def update(self, other: "BitArray") -> None:
        """Copy other's bits into self (sizes should match)."""
        n = min(len(self._elems), len(other._elems))
        self._elems[:n] = other._elems[:n]
        self._mask_tail()

    def to_bytes(self) -> bytes:
        return bytes(self._elems)

    @classmethod
    def from_bytes(cls, bits: int, data: bytes) -> "BitArray":
        ba = cls(bits)
        n = min(len(ba._elems), len(data))
        ba._elems[:n] = data[:n]
        ba._mask_tail()
        return ba

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitArray)
            and self.bits == other.bits
            and self._elems == other._elems
        )

    def __repr__(self) -> str:
        return "BA{" + "".join("x" if self.get_index(i) else "_" for i in range(self.bits)) + "}"
