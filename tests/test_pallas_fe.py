"""Differential tests for the Pallas fused point kernels (ops/pallas_fe.py).

The kernel BODY (row-list field/point math) is plain jnp code — validated
here directly against the pure-python reference on the CPU backend, at the
exact (S, 128) row shapes the kernels use. The pallas_call plumbing
(BlockSpec tiling, lane padding) is shape-only; its pack/unpack inverse is
tested host-side, and the compiled path is exercised on real TPU by the
MSM fast path (bench.py, tools/profile_msm.py). Mosaic interpret mode is
NOT used: interpreting the ~6k-op kernel body through XLA:CPU compiles for
minutes (measured)."""

import pytest

pytestmark = [pytest.mark.kernel, pytest.mark.slow]  # heavy one-time
# compiles: excluded from the tier-1 budget lane (-m 'not slow'); run
# explicitly via -m kernel

import numpy as np

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.ops import fe25519 as fe
from tendermint_tpu.ops import pallas_fe as pf
from tendermint_tpu.ops.ed25519_jax import Point

rng = np.random.default_rng(99)
S, L = 1, 128  # one row tile: 128 lanes


def to_rows(ints):
    """List of python ints -> row-list of (S, 128) arrays (lane i = ints[i],
    rest replicated from lane 0 to keep every lane a valid field element)."""
    limbs = np.stack([fe.from_int(x) for x in ints], axis=-1)  # (20, n)
    rows = []
    for i in range(pf.NL):
        buf = np.full((S, L), limbs[i, 0], dtype=np.int32)
        buf.flat[: len(ints)] = limbs[i]
        rows.append(buf)
    return [np.asarray(r) for r in rows]


def rows_to_int(rows, lane=0):
    return fe.to_int(np.asarray([np.asarray(r).flat[lane] for r in rows]))


def rand_fe(n):
    return [int.from_bytes(rng.bytes(32), "little") % fe.P for x in range(n)]


def test_row_field_ops_match_reference():
    xs, ys = rand_fe(8), rand_fe(8)
    rx, ry = to_rows(xs), to_rows(ys)
    for name, got_rows, want_fn in [
        ("mul", pf._rmul(rx, ry), lambda a, b: a * b % fe.P),
        ("add", pf._radd(rx, ry), lambda a, b: (a + b) % fe.P),
        ("sub", pf._rsub(rx, ry), lambda a, b: (a - b) % fe.P),
        ("square", pf._rsquare(rx), lambda a, b: a * a % fe.P),
        ("mul_small", pf._rmul_small(rx, 2), lambda a, b: 2 * a % fe.P),
        ("mul_const_d2", pf._rmul_const(rx, pf._D2), lambda a, b: a * fe.D2 % fe.P),
    ]:
        for i in range(8):
            assert rows_to_int(got_rows, i) == want_fn(xs[i], ys[i]), (name, i)


def test_row_mul_bounds_chain():
    """Chained muls stay in the carried representation (no int32 overflow):
    64 dependent multiplies match pow arithmetic."""
    x = rand_fe(1)[0]
    acc_rows = to_rows([x])
    acc = x
    for _ in range(64):
        acc_rows = pf._rmul(acc_rows, acc_rows)
        acc = acc * acc % fe.P
        for r in acc_rows:
            arr = np.asarray(r)
            assert arr.min() >= 0 and arr.max() < (1 << 14), "limb out of range"
    assert rows_to_int(acc_rows) == acc


def rand_points(n):
    return [
        ref.point_mul(int.from_bytes(rng.bytes(32), "little") % ref.L, ref.BASE)
        for _ in range(n)
    ]


def pt_to_rows(pts):
    return tuple(to_rows([p[c] for p in pts]) for c in range(4))


def rows_to_pt(coords, lane=0):
    return tuple(rows_to_int(r, lane) for r in coords)


def test_row_point_add_matches_reference():
    ps, qs = rand_points(6), rand_points(6)
    out = pf._padd_rows(pt_to_rows(ps), pt_to_rows(qs))
    for i in range(6):
        got = rows_to_pt(out, i)
        want = ref.point_add(ps[i], qs[i])
        assert ref.point_equal(got, want), i
        x, y, z, t = got
        assert (x * y - t * z) % ref.P == 0


def test_row_point_add_identity():
    ps = rand_points(2)
    ident = (0, 1, 1, 0)
    out = pf._padd_rows(pt_to_rows([ps[0], ident]), pt_to_rows([ident, ident]))
    assert ref.point_equal(rows_to_pt(out, 0), ps[0])
    assert ref.point_equal(rows_to_pt(out, 1), ident)


def test_row_point_double_matches_reference():
    ps = rand_points(5)
    out = pf._pdbl_rows(pt_to_rows(ps))
    for i in range(5):
        assert ref.point_equal(rows_to_pt(out, i), ref.point_double(ps[i]))


def test_row_point_double_chain_8():
    ps = rand_points(3)
    coords = pt_to_rows(ps)
    for _ in range(8):
        coords = pf._pdbl_rows(coords)
    for i in range(3):
        want = ps[i]
        for _ in range(8):
            want = ref.point_double(want)
        assert ref.point_equal(rows_to_pt(coords, i), want)


def test_pack_unpack_roundtrip():
    """_pack pads lanes to 128-multiples and tiles; _unpack inverts exactly —
    including non-multiple and multi-dim batch shapes."""
    for shape in [(9,), (128,), (130,), (2, 3), (32, 5)]:
        coords = [
            rng.integers(0, 1 << 13, (fe.NLIMBS, *shape)).astype(np.int32)
            for _ in range(4)
        ]
        p = Point(*coords)
        packed, bs, n = pf._pack(p)
        assert packed.shape[0] == 4 and packed.shape[3] == 128
        back = pf._unpack(np.asarray(packed), bs, n)
        for a, b in zip(p, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
