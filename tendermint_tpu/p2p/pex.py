"""PEX (peer exchange) reactor + address book.

reference: p2p/pex/pex_reactor.go:24 (channel 0x00, request/provide addrs,
ensure-peers routine, seed bootstrap), p2p/pex/addrbook.go:28-29,97-98,135-140
(new/old buckets, hashed placement, mark good/bad/attempt), p2p/pex/file.go
(JSON persistence).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor
from tendermint_tpu.p2p.node_info import parse_addr

logger = logging.getLogger("tendermint_tpu.pex")

PEX_CHANNEL = 0x00  # reference: p2p/pex/pex_reactor.go:33

# reference: p2p/pex/addrbook.go params
NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
BUCKET_SIZE = 64
# a peer must survive this long / attempts before promotion to "old"
OLD_AFTER_ATTEMPTS = 1

MAX_MSG_SIZE = 64 * 1024  # bounds a PexAddrs payload
MAX_ADDRS_PER_MSG = 100
MIN_REQUEST_INTERVAL = 5.0  # per-peer anti-spam (reference: ensurePeersPeriod/3)


@dataclass
class KnownAddress:
    """reference: p2p/pex/known_address.go."""

    addr: str  # "id@host:port"
    src: str  # peer id we learned it from
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    is_old: bool = False
    bucket: int = -1

    @property
    def id(self) -> str:
        return parse_addr(self.addr)[0]

    def to_json(self) -> dict:
        return {
            "addr": self.addr,
            "src": self.src,
            "attempts": self.attempts,
            "last_attempt": self.last_attempt,
            "last_success": self.last_success,
            "is_old": self.is_old,
        }

    @classmethod
    def from_json(cls, o: dict) -> "KnownAddress":
        return cls(
            addr=o["addr"],
            src=o.get("src", ""),
            attempts=o.get("attempts", 0),
            last_attempt=o.get("last_attempt", 0.0),
            last_success=o.get("last_success", 0.0),
            is_old=o.get("is_old", False),
        )


class AddrBook:
    """New/old-bucketed address book (reference: p2p/pex/addrbook.go:97).

    New addresses (heard about, never connected) live in buckets hashed by
    (source-group, addr-group); old addresses (connected at least once) in
    buckets hashed by addr-group. One entry per node id."""

    def __init__(self, file_path: Optional[str] = None, key: Optional[bytes] = None):
        self.file_path = file_path
        # random key so remote peers can't engineer bucket collisions
        # (reference: addrbook.go a.key)
        self.key = key or os.urandom(8)
        self._addrs: Dict[str, KnownAddress] = {}  # node id -> ka
        self._new_buckets: List[List[str]] = [[] for _ in range(NEW_BUCKET_COUNT)]
        self._old_buckets: List[List[str]] = [[] for _ in range(OLD_BUCKET_COUNT)]
        if file_path and os.path.exists(file_path):
            self._load()

    # -- bucket math --------------------------------------------------------

    def _bucket_for(self, ka: KnownAddress) -> int:
        _, host, _ = parse_addr(ka.addr)
        if ka.is_old:
            h = tmhash.sum256(self.key + host.encode())
            return int.from_bytes(h[:4], "big") % OLD_BUCKET_COUNT
        h = tmhash.sum256(self.key + ka.src.encode() + host.encode())
        return int.from_bytes(h[:4], "big") % NEW_BUCKET_COUNT

    def _buckets(self, ka: KnownAddress) -> List[List[str]]:
        return self._old_buckets if ka.is_old else self._new_buckets

    def _place(self, ka: KnownAddress) -> None:
        bucket = self._bucket_for(ka)
        blist = self._buckets(ka)[bucket]
        if ka.id in blist:
            ka.bucket = bucket
            return
        if len(blist) >= BUCKET_SIZE:
            # evict the stalest entry of the bucket (reference: pickOldest)
            stalest = min(blist, key=lambda i: self._addrs[i].last_attempt)
            blist.remove(stalest)
            self._addrs.pop(stalest, None)
        blist.append(ka.id)
        ka.bucket = bucket

    def _unplace(self, ka: KnownAddress) -> None:
        if ka.bucket >= 0:
            blist = self._buckets(ka)[ka.bucket]
            if ka.id in blist:
                blist.remove(ka.id)
        ka.bucket = -1

    # -- public API ---------------------------------------------------------

    def add_address(self, addr: str, src: str = "") -> bool:
        """Record a new address (reference: addrbook.go:135 AddAddress)."""
        try:
            node_id, host, port = parse_addr(addr)
        except (ValueError, TypeError):
            return False
        if not node_id or not (0 < port < 65536):
            return False
        if node_id in self._addrs:
            return False
        ka = KnownAddress(addr=addr, src=src)
        self._addrs[node_id] = ka
        self._place(ka)
        return True

    def remove_address(self, node_id: str) -> None:
        ka = self._addrs.pop(node_id, None)
        if ka is not None:
            self._unplace(ka)

    def mark_attempt(self, node_id: str) -> None:
        ka = self._addrs.get(node_id)
        if ka is not None:
            ka.attempts += 1
            ka.last_attempt = time.time()

    def mark_good(self, node_id: str) -> None:
        """Successful connection: promote to an old bucket
        (reference: addrbook.go MarkGood)."""
        ka = self._addrs.get(node_id)
        if ka is None:
            return
        ka.attempts = 0
        ka.last_success = time.time()
        ka.last_attempt = ka.last_success
        if not ka.is_old:
            self._unplace(ka)
            ka.is_old = True
            self._place(ka)

    def mark_bad(self, node_id: str) -> None:
        """reference: addrbook.go MarkBad — we simply drop it."""
        self.remove_address(node_id)

    def has(self, node_id: str) -> bool:
        return node_id in self._addrs

    def is_empty(self) -> bool:
        return not self._addrs

    def size(self) -> int:
        return len(self._addrs)

    def pick_address(self, new_bias_pct: int = 50) -> Optional[KnownAddress]:
        """Random address, biased between new/old (reference: PickAddress)."""
        news = [ka for ka in self._addrs.values() if not ka.is_old]
        olds = [ka for ka in self._addrs.values() if ka.is_old]
        pools = []
        if news:
            pools.append((new_bias_pct, news))
        if olds:
            pools.append((100 - new_bias_pct, olds))
        if not pools:
            return None
        total = sum(wt for wt, _ in pools)
        r = random.uniform(0, total)
        for wt, pool in pools:
            if r < wt:
                return random.choice(pool)
            r -= wt
        return random.choice(pools[-1][1])

    def get_selection(self, max_addrs: int = MAX_ADDRS_PER_MSG) -> List[str]:
        """Random selection for a PEX response (reference: GetSelection)."""
        addrs = [ka.addr for ka in self._addrs.values()]
        random.shuffle(addrs)
        return addrs[: min(max_addrs, max(len(addrs) * 23 // 100 + 1, 10))]

    # -- persistence (reference: p2p/pex/file.go) ---------------------------

    def save(self) -> None:
        if not self.file_path:
            return
        data = {
            "key": self.key.hex(),
            "addrs": [ka.to_json() for ka in self._addrs.values()],
        }
        tmp = self.file_path + ".tmp"
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.file_path)

    def _load(self) -> None:
        try:
            with open(self.file_path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            logger.warning("could not load addrbook %s", self.file_path)
            return
        try:
            self.key = bytes.fromhex(data.get("key", "")) or self.key
        except ValueError:
            logger.warning("addrbook key corrupt; regenerating")
        for o in data.get("addrs", []):
            # a single corrupt entry must not prevent node startup
            try:
                ka = KnownAddress.from_json(o)
                if ka.id and ka.id not in self._addrs:
                    self._addrs[ka.id] = ka
                    self._place(ka)
            except (KeyError, ValueError, TypeError) as e:
                logger.warning("skipping corrupt addrbook entry %r: %s", o, e)


# ---------------------------------------------------------------- wire msgs


def encode_pex_request() -> bytes:
    w = pw.Writer()
    w.message_field(1, b"", always=True)
    return w.bytes()


def encode_pex_addrs(addrs: List[str]) -> bytes:
    body = pw.Writer()
    for a in addrs[:MAX_ADDRS_PER_MSG]:
        body.string_field(1, a, emit_empty=True)
    w = pw.Writer()
    w.message_field(2, body.bytes(), always=True)
    return w.bytes()


def decode_pex_message(data: bytes):
    """Returns None for a request, or the list of addr strings."""
    if len(data) > MAX_MSG_SIZE:
        raise ValueError("pex message too large")
    for f, _, v in pw.Reader(data):
        if f == 1:
            return None
        if f == 2:
            addrs = []
            for ff, _, vv in pw.Reader(v):
                if ff == 1:
                    addrs.append(vv.decode("utf-8"))
            if len(addrs) > MAX_ADDRS_PER_MSG:
                raise ValueError("too many addrs in pex message")
            return addrs
    raise ValueError("empty pex message")


# ------------------------------------------------------------------ reactor


class PexReactor(Reactor):
    """reference: p2p/pex/pex_reactor.go:24."""

    def __init__(
        self,
        book: AddrBook,
        seeds: Optional[List[str]] = None,
        ensure_period: float = 30.0,
        max_outbound: int = 10,
        seed_mode: bool = False,
    ):
        super().__init__("PEX")
        self.book = book
        self.seeds = seeds or []
        self.ensure_period = ensure_period
        self.max_outbound = max_outbound
        self.seed_mode = seed_mode
        self._last_request: Dict[str, float] = {}  # peer id -> ts (anti-spam)
        self._last_sent: Dict[str, float] = {}  # our own request cadence
        self._requested: set = set()  # peers we asked (only they may reply)
        self._task: Optional[asyncio.Task] = None

    def get_channels(self) -> List[ChannelDescriptor]:
        # sheddable + small capacity: a pex message is a bounded address
        # list (reference: p2p/pex/pex_reactor.go maxMsgSize 64KB-ish)
        return [
            ChannelDescriptor(
                PEX_CHANNEL, priority=1, send_queue_capacity=10,
                recv_message_capacity=65536, sheddable=True,
            )
        ]

    async def start(self) -> None:
        self._task = asyncio.create_task(self._ensure_peers_routine(), name="pex-ensure")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        self.book.save()

    # -- peers --------------------------------------------------------------

    async def add_peer(self, peer) -> None:
        """reference: pex_reactor.go:180 AddPeer."""
        if peer.outbound:
            # outbound peers are proven good addresses
            self.book.add_address(f"{peer.id}@{peer.socket_addr}", src=peer.id)
            self.book.mark_good(peer.id)
            if self._need_more_peers():
                await self._request_addrs(peer)
        # inbound peers' self-reported listen addr is NOT trusted (the
        # reference only records it via the dial-back in seed mode)

    async def remove_peer(self, peer, reason) -> None:
        self._requested.discard(peer.id)
        self._last_request.pop(peer.id, None)
        self._last_sent.pop(peer.id, None)

    # -- receive ------------------------------------------------------------

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        try:
            addrs = decode_pex_message(msg_bytes)
        except ValueError as e:
            await self.switch.stop_peer_for_error(peer, e)
            return
        if addrs is None:  # PexRequest
            now = time.monotonic()
            last = self._last_request.get(peer.id)
            # the FIRST request after connect is always allowed; after that
            # anything under the interval is a flood (reference:
            # pex_reactor.go receiveRequest lastReceivedRequests)
            if last is not None and now - last < MIN_REQUEST_INTERVAL:
                await self.switch.stop_peer_for_error(peer, "pex request flood")
                return
            self._last_request[peer.id] = now
            await peer.send(PEX_CHANNEL, encode_pex_addrs(self.book.get_selection()))
            if self.seed_mode:
                # seeds hand out addresses and hang up to free slots for
                # other crawlers (reference: pex_reactor.go:308 seed flow)
                await asyncio.sleep(0.1)
                await self.switch.stop_peer_for_error(peer, "seed: served addrs")
        else:  # PexAddrs
            # unsolicited address dumps are an attack vector
            # (reference: pex_reactor.go:260 ReceiveAddrs requestsSent check)
            if peer.id not in self._requested:
                await self.switch.stop_peer_for_error(peer, "unsolicited pex addrs")
                return
            self._requested.discard(peer.id)
            for a in addrs:
                try:
                    node_id, _, _ = parse_addr(a)
                except (ValueError, TypeError):
                    continue
                if node_id and node_id != self.switch.node_info.node_id:
                    self.book.add_address(a, src=peer.id)

    async def _request_addrs(self, peer) -> None:
        """reference: pex_reactor.go:240 RequestAddrs. Rate-limited on OUR
        side too, so our own cadence never trips the peer's flood guard."""
        now = time.monotonic()
        if peer.id in self._requested:
            return
        if now - self._last_sent.get(peer.id, -1e9) < MIN_REQUEST_INTERVAL * 1.5:
            return
        self._last_sent[peer.id] = now
        self._requested.add(peer.id)
        await peer.send(PEX_CHANNEL, encode_pex_request())

    # -- ensure peers -------------------------------------------------------

    def _need_more_peers(self) -> int:
        out = sum(1 for p in self.switch.peers.list() if p.outbound)
        return max(0, self.max_outbound - out)

    async def _ensure_peers_routine(self) -> None:
        """Keep dialing until we have enough outbound peers
        (reference: pex_reactor.go:375 ensurePeersRoutine)."""
        # jittered start so a fleet doesn't thunder in step
        await asyncio.sleep(random.uniform(0, self.ensure_period / 10 + 0.01))
        while True:
            try:
                await self._ensure_peers()
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("ensure_peers failed")
            await asyncio.sleep(self.ensure_period)

    async def _ensure_peers(self) -> None:
        need = self._need_more_peers()
        if need <= 0:
            return
        if self.book.is_empty() and self.seeds:
            await self._dial_seeds()
            return
        tried = 0
        for _ in range(need * 3):
            if tried >= need:
                break
            ka = self.book.pick_address()
            if ka is None:
                break
            if self.switch.peers.has(ka.id) or ka.id == self.switch.node_info.node_id:
                continue
            # exponential backoff per failed attempt (reference: ka.isBad)
            if ka.attempts > 0 and time.time() - ka.last_attempt < min(
                30.0 * (2 ** min(ka.attempts, 6)), 3600
            ):
                continue
            tried += 1
            self.book.mark_attempt(ka.id)
            try:
                await self.switch.dial_peer(ka.addr)
                self.book.mark_good(ka.id)
            except Exception as e:
                logger.debug("pex dial %s failed: %s", ka.addr, e)
                if ka.attempts >= 5:
                    self.book.mark_bad(ka.id)
        # also ask a random connected peer for more addresses
        peers = self.switch.peers.list()
        if peers and self.book.size() < 2 * self.max_outbound:
            await self._request_addrs(random.choice(peers))

    async def _dial_seeds(self) -> None:
        """reference: pex_reactor.go:500 dialSeeds."""
        seeds = list(self.seeds)
        random.shuffle(seeds)
        for seed in seeds:
            try:
                peer = await self.switch.dial_peer(seed)
                if peer is not None:
                    await self._request_addrs(peer)
                    return
            except Exception as e:
                logger.info("seed dial %s failed: %s", seed, e)
