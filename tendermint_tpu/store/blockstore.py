"""BlockStore: blocks as meta + parts + commits in a kv-db
(reference: store/store.go:33).

Keys: H:<height> header/meta, P:<height>:<index> parts, C:<height> commit,
SC:<height> seen commit, plus base/height bookkeeping. Pruning mirrors
PruneBlocks (reference: store/store.go:228)."""

from __future__ import annotations

import struct
from typing import Optional

from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.libs.kvdb import KVDB
from tendermint_tpu.types.basic import BlockID, PartSetHeader
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.part_set import Part, PartSet


def _hkey(prefix: bytes, height: int) -> bytes:
    return prefix + struct.pack(">q", height)


class BlockStore:
    def __init__(self, db: KVDB):
        self.db = db

    # -- bookkeeping --------------------------------------------------------

    @property
    def base(self) -> int:
        raw = self.db.get(b"BS:base")
        return struct.unpack(">q", raw)[0] if raw else 0

    @property
    def height(self) -> int:
        raw = self.db.get(b"BS:height")
        return struct.unpack(">q", raw)[0] if raw else 0

    def size(self) -> int:
        h = self.height
        return 0 if h == 0 else h - self.base + 1

    # -- saving -------------------------------------------------------------

    def save_block(self, block: Block, parts: PartSet, seen_commit: Commit) -> None:
        """(reference: store/store.go:311 SaveBlock)"""
        if block is None:
            raise ValueError("BlockStore can only save a non-nil block")
        height = block.header.height
        expected = self.height + 1
        if self.height > 0 and height != expected:
            raise ValueError(f"BlockStore can only save contiguous blocks. Wanted {expected}, got {height}")
        if not parts.is_complete():
            raise ValueError("BlockStore can only save complete block part sets")

        sets = []
        block_id = BlockID(block.hash(), parts.header)
        meta = pw.Writer()
        meta.message_field(1, block_id.encode(), always=True)
        meta.varint_field(2, parts.total)
        sets.append((_hkey(b"BS:meta:", height), meta.bytes()))
        for i in range(parts.total):
            sets.append((_hkey(b"BS:part:", height) + struct.pack(">I", i), parts.get_part(i).encode()))
        sets.append((_hkey(b"BS:block:", height), block.encode()))
        sets.append((_hkey(b"BS:commit:", height - 1), block.last_commit.encode()))
        sets.append((_hkey(b"BS:seen_commit:", height), seen_commit.encode()))
        sets.append((b"BS:height", struct.pack(">q", height)))
        if self.base == 0:
            sets.append((b"BS:base", struct.pack(">q", height)))
        self.db.write_batch(sets)

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        self.db.set(_hkey(b"BS:seen_commit:", height), commit.encode())

    # -- loading ------------------------------------------------------------

    def load_block(self, height: int) -> Optional[Block]:
        raw = self.db.get(_hkey(b"BS:block:", height))
        return Block.decode(raw) if raw else None

    def load_block_meta(self, height: int) -> Optional[tuple]:
        """Returns (BlockID, total_parts) or None."""
        raw = self.db.get(_hkey(b"BS:meta:", height))
        if not raw:
            return None
        block_id = BlockID()
        total = 0
        for f, _, v in pw.Reader(raw):
            if f == 1:
                block_id = BlockID.decode(v)
            elif f == 2:
                total = v
        return block_id, total

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        raw = self.db.get(_hkey(b"BS:part:", height) + struct.pack(">I", index))
        return Part.decode(raw) if raw else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        """The commit FOR block at `height` (stored with block height+1)."""
        raw = self.db.get(_hkey(b"BS:commit:", height))
        return Commit.decode(raw) if raw else None

    def load_seen_commit(self, height: int) -> Optional[Commit]:
        raw = self.db.get(_hkey(b"BS:seen_commit:", height))
        return Commit.decode(raw) if raw else None

    def load_block_by_hash(self, block_hash: bytes) -> Optional[Block]:
        # Linear scan over metas would be slow; maintain a hash index lazily.
        raw = self.db.get(b"BS:hash:" + block_hash)
        if raw:
            return self.load_block(struct.unpack(">q", raw)[0])
        for h in range(self.base, self.height + 1):
            meta = self.load_block_meta(h)
            if meta and meta[0].hash == block_hash:
                self.db.set(b"BS:hash:" + block_hash, struct.pack(">q", h))
                return self.load_block(h)
        return None

    # -- pruning ------------------------------------------------------------

    def prune_blocks(self, retain_height: int) -> int:
        """Removes blocks below retain_height; returns number pruned
        (reference: store/store.go:228)."""
        if retain_height <= 0:
            raise ValueError("height must be greater than 0")
        if retain_height > self.height:
            raise ValueError("cannot prune beyond the latest height")
        base = self.base
        if retain_height < base:
            return 0
        pruned = 0
        deletes = []
        for h in range(base, retain_height):
            meta = self.load_block_meta(h)
            if meta is None:
                continue
            deletes.append(_hkey(b"BS:meta:", h))
            deletes.append(_hkey(b"BS:block:", h))
            deletes.append(_hkey(b"BS:commit:", h - 1))
            deletes.append(_hkey(b"BS:seen_commit:", h))
            for i in range(meta[1]):
                deletes.append(_hkey(b"BS:part:", h) + struct.pack(">I", i))
            pruned += 1
        self.db.write_batch([(b"BS:base", struct.pack(">q", retain_height))], deletes)
        return pruned
