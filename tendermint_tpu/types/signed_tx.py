"""Signed-transaction envelope: the wire format behind device-batched
CheckTx admission (crypto/scheduler.py admission lane).

The reference leaves tx authentication entirely to the application —
which is exactly why every CheckTx pays a serial, app-side signature
verify. The envelope makes the signature NODE-VISIBLE: the mempool can
decode it, batch-verify thousands of admissions in one device flush, and
hand the app the verdict (`RequestCheckTx.sig_precheck`) instead of the
work. Applications stay sovereign: an app may ignore the verdict and
re-verify, and txs that don't parse as envelopes flow through untouched
(`sig_precheck` stays NONE).

Layout (single ed25519 signer, versioned magic):

    b"stx1" | pubkey(32) | signature(64) | payload...

The signature covers a domain-separated message — `SIGN_PREFIX + payload`
— so a tx signature can never be replayed as a vote/proposal signature or
vice versa (those sign canonical protos with their own prefixes).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

MAGIC = b"stx1"
PUBKEY_LEN = 32
SIG_LEN = 64
HEADER_LEN = len(MAGIC) + PUBKEY_LEN + SIG_LEN

# domain separation: a signed-tx signature verifies ONLY as a signed-tx
SIGN_PREFIX = b"tendermint_tpu/signed-tx/v1\x00"


class SignedTx(NamedTuple):
    pubkey: bytes     # ed25519, 32 bytes
    signature: bytes  # 64 bytes
    payload: bytes    # the application-level tx body

    @property
    def sign_bytes(self) -> bytes:
        return SIGN_PREFIX + self.payload


def encode_signed_tx(priv, payload: bytes) -> bytes:
    """Wrap `payload` in a signed envelope under `priv` (crypto/keys
    PrivKey: needs .pub_key().bytes() and .sign())."""
    payload = bytes(payload)
    sig = priv.sign(SIGN_PREFIX + payload)
    return MAGIC + priv.pub_key().bytes() + bytes(sig) + payload


def decode_signed_tx(tx: bytes) -> Optional[SignedTx]:
    """Parse an envelope; None when `tx` is not one (wrong magic / too
    short) — the caller treats those as plain opaque txs."""
    if len(tx) < HEADER_LEN or tx[: len(MAGIC)] != MAGIC:
        return None
    off = len(MAGIC)
    pubkey = bytes(tx[off : off + PUBKEY_LEN])
    off += PUBKEY_LEN
    sig = bytes(tx[off : off + SIG_LEN])
    off += SIG_LEN
    return SignedTx(pubkey, sig, bytes(tx[off:]))


def verify_signed_tx(stx: SignedTx) -> bool:
    """Serial host verification of one envelope — the baseline the
    admission lane replaces (used by apps when no precheck verdict rode
    the request, and by the bench's serial arm)."""
    from tendermint_tpu.crypto.keys import Ed25519PubKey

    try:
        return Ed25519PubKey(stx.pubkey).verify(stx.sign_bytes, stx.signature)
    except ValueError:
        return False
