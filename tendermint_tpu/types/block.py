"""Block, Header, Commit, CommitSig, Data (reference: types/block.go).

Hashing follows the reference scheme: Header.Hash is the merkle root of the 14
proto-encoded header fields (reference: types/block.go Header.Hash +
types/encoding_helper.go cdcEncode — primitives are wrapped in single-field
proto messages); Data.Hash is the merkle root over SHA-256 tx hashes
(reference: types/tx.go Txs.Hash); Commit.Hash is the merkle root over
proto-encoded CommitSigs.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field, replace
from typing import List, Optional, Sequence

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.crypto.merkle import hash_from_byte_slices
from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.types.basic import (
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
    ts_seconds_nanos,
)
from tendermint_tpu.types import canonical
from tendermint_tpu.types.vote import Vote

MAX_HEADER_BYTES = 626


def _cdc_bytes(b: bytes) -> bytes:
    w = pw.Writer()
    w.bytes_field(1, b)
    return w.bytes()


def _cdc_string(s: str) -> bytes:
    w = pw.Writer()
    w.string_field(1, s)
    return w.bytes()


def _cdc_int64(v: int) -> bytes:
    w = pw.Writer()
    w.varint_field(1, v)
    return w.bytes()


def tx_hash(tx: bytes) -> bytes:
    return tmhash.sum256(tx)


def txs_hash(txs: Sequence[bytes]) -> bytes:
    return hash_from_byte_slices([tx_hash(tx) for tx in txs])


@dataclass(frozen=True)
class ConsensusVersion:
    """reference: proto/tendermint/version/types.proto Consensus."""

    block: int = 11  # BlockProtocol, reference: version/version.go
    app: int = 0

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.block)
        w.varint_field(2, self.app)
        return w.bytes()


@dataclass(frozen=True)
class Header:
    version: ConsensusVersion
    chain_id: str
    height: int
    time_ns: int
    last_block_id: BlockID
    last_commit_hash: bytes
    data_hash: bytes
    validators_hash: bytes
    next_validators_hash: bytes
    consensus_hash: bytes
    app_hash: bytes
    last_results_hash: bytes
    evidence_hash: bytes
    proposer_address: bytes

    def hash(self) -> bytes:
        """Merkle root over the proto-encoded fields (reference:
        types/block.go Header.Hash). Returns b"" if the header is incomplete."""
        if not self.validators_hash:
            return b""
        sec, nanos = ts_seconds_nanos(self.time_ns)
        fields = [
            self.version.encode(),
            _cdc_string(self.chain_id),
            _cdc_int64(self.height),
            pw.encode_timestamp(sec, nanos),
            self.last_block_id.encode(),
            _cdc_bytes(self.last_commit_hash),
            _cdc_bytes(self.data_hash),
            _cdc_bytes(self.validators_hash),
            _cdc_bytes(self.next_validators_hash),
            _cdc_bytes(self.consensus_hash),
            _cdc_bytes(self.app_hash),
            _cdc_bytes(self.last_results_hash),
            _cdc_bytes(self.evidence_hash),
            _cdc_bytes(self.proposer_address),
        ]
        return hash_from_byte_slices(fields)

    def validate_basic(self) -> None:
        if len(self.chain_id) > 50:
            raise ValueError("chainID is too long")
        if self.height < 0:
            raise ValueError("negative Header.Height")
        if self.height == 0:
            raise ValueError("zero Header.Height")
        self.last_block_id.validate_basic()
        for name in (
            "last_commit_hash",
            "data_hash",
            "evidence_hash",
            "last_results_hash",
            "validators_hash",
            "next_validators_hash",
            "consensus_hash",
        ):
            h = getattr(self, name)
            if h and len(h) != tmhash.SIZE:
                raise ValueError(f"wrong {name} size")
        if len(self.proposer_address) != tmhash.TRUNCATED_SIZE:
            raise ValueError("invalid ProposerAddress length")

    def encode(self) -> bytes:
        sec, nanos = ts_seconds_nanos(self.time_ns)
        w = pw.Writer()
        w.message_field(1, self.version.encode(), always=True)
        w.string_field(2, self.chain_id)
        w.varint_field(3, self.height)
        w.message_field(4, pw.encode_timestamp(sec, nanos), always=True)
        w.message_field(5, self.last_block_id.encode(), always=True)
        w.bytes_field(6, self.last_commit_hash)
        w.bytes_field(7, self.data_hash)
        w.bytes_field(8, self.validators_hash)
        w.bytes_field(9, self.next_validators_hash)
        w.bytes_field(10, self.consensus_hash)
        w.bytes_field(11, self.app_hash)
        w.bytes_field(12, self.last_results_hash)
        w.bytes_field(13, self.evidence_hash)
        w.bytes_field(14, self.proposer_address)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Header":
        kw = dict(
            version=ConsensusVersion(),
            chain_id="",
            height=0,
            time_ns=0,
            last_block_id=BlockID(),
            last_commit_hash=b"",
            data_hash=b"",
            validators_hash=b"",
            next_validators_hash=b"",
            consensus_hash=b"",
            app_hash=b"",
            last_results_hash=b"",
            evidence_hash=b"",
            proposer_address=b"",
        )
        for f, _, v in pw.Reader(data):
            if f == 1:
                blk = app = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        blk = vv
                    elif ff == 2:
                        app = vv
                kw["version"] = ConsensusVersion(blk, app)
            elif f == 2:
                kw["chain_id"] = v.decode("utf-8")
            elif f == 3:
                kw["height"] = pw.int64_from_varint(v)
            elif f == 4:
                sec = nanos = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        sec = pw.int64_from_varint(vv)
                    elif ff == 2:
                        nanos = pw.int64_from_varint(vv)
                kw["time_ns"] = sec * 1_000_000_000 + nanos
            elif f == 5:
                kw["last_block_id"] = BlockID.decode(v)
            elif f == 6:
                kw["last_commit_hash"] = v
            elif f == 7:
                kw["data_hash"] = v
            elif f == 8:
                kw["validators_hash"] = v
            elif f == 9:
                kw["next_validators_hash"] = v
            elif f == 10:
                kw["consensus_hash"] = v
            elif f == 11:
                kw["app_hash"] = v
            elif f == 12:
                kw["last_results_hash"] = v
            elif f == 13:
                kw["evidence_hash"] = v
            elif f == 14:
                kw["proposer_address"] = v
        return cls(**kw)


@dataclass(frozen=True)
class CommitSig:
    block_id_flag: BlockIDFlag
    validator_address: bytes = b""
    timestamp_ns: int = 0
    signature: bytes = b""

    @classmethod
    def absent_sig(cls) -> "CommitSig":
        return cls(block_id_flag=BlockIDFlag.ABSENT)

    def absent(self) -> bool:
        return self.block_id_flag == BlockIDFlag.ABSENT

    def for_block(self) -> bool:
        return self.block_id_flag == BlockIDFlag.COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """(reference: types/block.go:638-651)"""
        if self.block_id_flag == BlockIDFlag.COMMIT:
            return commit_block_id
        return BlockID()

    def validate_basic(self) -> None:
        if self.block_id_flag not in (BlockIDFlag.ABSENT, BlockIDFlag.COMMIT, BlockIDFlag.NIL):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.absent():
            if self.validator_address:
                raise ValueError("validator address is present for absent CommitSig")
            if self.signature:
                raise ValueError("signature is present for absent CommitSig")
        else:
            if len(self.validator_address) != tmhash.TRUNCATED_SIZE:
                raise ValueError("expected ValidatorAddress size to be 20 bytes")
            if not self.signature:
                raise ValueError("signature is missing")
            # 96 = compressed-G2 BLS signature (docs/BLS.md); 64 otherwise
            if len(self.signature) > 96:
                raise ValueError("signature is too big")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, int(self.block_id_flag))
        w.bytes_field(2, self.validator_address)
        sec, nanos = ts_seconds_nanos(self.timestamp_ns)
        w.message_field(3, pw.encode_timestamp(sec, nanos), always=True)
        w.bytes_field(4, self.signature)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "CommitSig":
        flag = BlockIDFlag.ABSENT
        addr = b""
        ts = 0
        sig = b""
        for f, _, v in pw.Reader(data):
            if f == 1:
                flag = BlockIDFlag(v)
            elif f == 2:
                addr = v
            elif f == 3:
                sec = nanos = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        sec = pw.int64_from_varint(vv)
                    elif ff == 2:
                        nanos = pw.int64_from_varint(vv)
                ts = sec * 1_000_000_000 + nanos
            elif f == 4:
                sig = v
        return cls(flag, addr, ts, sig)


@dataclass(frozen=True)
class Commit:
    height: int
    round: int
    block_id: BlockID
    signatures: tuple

    def __post_init__(self):
        object.__setattr__(self, "signatures", tuple(self.signatures))

    def size(self) -> int:
        return len(self.signatures)

    def get_vote(self, val_idx: int) -> Vote:
        """(reference: types/block.go:770-782)"""
        cs = self.signatures[val_idx]
        return Vote(
            type=SignedMsgType.PRECOMMIT,
            height=self.height,
            round=self.round,
            block_id=cs.block_id(self.block_id),
            timestamp_ns=cs.timestamp_ns,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
        )

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        cs = self.signatures[val_idx]
        return canonical.vote_sign_bytes(
            chain_id,
            SignedMsgType.PRECOMMIT,
            self.height,
            self.round,
            cs.block_id(self.block_id),
            cs.timestamp_ns,
        )

    def vote_sign_bytes_many(self, chain_id: str, val_idxs) -> list:
        """Batched vote_sign_bytes over many signature indices — the O(N)
        commit-verification paths build all their messages in one pass
        (canonical.vote_sign_bytes_many; profiled ~10x the per-row builder)."""
        return canonical.vote_sign_bytes_many(
            chain_id,
            SignedMsgType.PRECOMMIT,
            self.height,
            self.round,
            (
                (self.signatures[i].block_id(self.block_id), self.signatures[i].timestamp_ns)
                for i in val_idxs
            ),
        )

    def hash(self) -> bytes:
        return hash_from_byte_slices([cs.encode() for cs in self.signatures])

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for cs in self.signatures:
                cs.validate_basic()

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        w.varint_field(2, self.round)
        w.message_field(3, self.block_id.encode(), always=True)
        for cs in self.signatures:
            w.message_field(4, cs.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Commit":
        height = round_ = 0
        block_id = BlockID()
        sigs: List[CommitSig] = []
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
            elif f == 2:
                round_ = pw.int64_from_varint(v)
            elif f == 3:
                block_id = BlockID.decode(v)
            elif f == 4:
                sigs.append(CommitSig.decode(v))
        return cls(height, round_, block_id, tuple(sigs))


EMPTY_COMMIT = Commit(height=0, round=0, block_id=BlockID(), signatures=())


@dataclass(frozen=True)
class AggregateCommit:
    """A commit carried as ONE aggregate BLS signature + a signer bitmap.

    The aggregation-enabling rule (docs/BLS.md): every BLS validator signs
    the SAME canonical precommit bytes — the commit's single canonical
    `timestamp_ns` below replaces the per-validator vote timestamps of the
    plain Commit (the per-signature path keeps them; only aggregation
    requires message equality). A 10k-validator commit shrinks from
    ~640 KB of per-validator signatures to 96 bytes + a 1.25 KB bitmap,
    which is what multiplies the light-serving capacity (ROADMAP item 4).

    `signers` is a little-endian bit-per-validator-index bitmap over the
    validator set the commit is verified against."""

    height: int
    round: int
    block_id: BlockID
    timestamp_ns: int
    signers: bytes
    agg_signature: bytes

    def signer_indices(self) -> List[int]:
        out = []
        for byte_i, b in enumerate(self.signers):
            while b:
                bit = b & -b
                out.append(byte_i * 8 + bit.bit_length() - 1)
                b ^= bit
        return out

    def has_signer(self, idx: int) -> bool:
        byte_i = idx // 8
        return byte_i < len(self.signers) and bool(
            self.signers[byte_i] >> (idx % 8) & 1
        )

    @staticmethod
    def bitmap_of(indices: Sequence[int], n_vals: int) -> bytes:
        bm = bytearray((n_vals + 7) // 8)
        for i in indices:
            if not 0 <= i < n_vals:
                raise ValueError(f"signer index {i} out of range")
            bm[i // 8] |= 1 << (i % 8)
        return bytes(bm)

    def sign_bytes(self, chain_id: str) -> bytes:
        """The ONE canonical message every signer signed."""
        return canonical.vote_sign_bytes(
            chain_id,
            SignedMsgType.PRECOMMIT,
            self.height,
            self.round,
            self.block_id,
            self.timestamp_ns,
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1 and self.block_id.is_zero():
            raise ValueError("aggregate commit cannot be for nil block")
        if len(self.agg_signature) != 96:
            raise ValueError("aggregate signature must be 96 bytes")
        if not any(self.signers):
            raise ValueError("empty signer bitmap")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        w.varint_field(2, self.round)
        w.message_field(3, self.block_id.encode(), always=True)
        sec, nanos = ts_seconds_nanos(self.timestamp_ns)
        w.message_field(4, pw.encode_timestamp(sec, nanos), always=True)
        w.bytes_field(5, self.signers)
        w.bytes_field(6, self.agg_signature)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "AggregateCommit":
        height = round_ = ts = 0
        block_id = BlockID()
        signers = sig = b""
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
            elif f == 2:
                round_ = pw.int64_from_varint(v)
            elif f == 3:
                block_id = BlockID.decode(v)
            elif f == 4:
                sec = nanos = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        sec = pw.int64_from_varint(vv)
                    elif ff == 2:
                        nanos = pw.int64_from_varint(vv)
                ts = sec * 1_000_000_000 + nanos
            elif f == 5:
                signers = v
            elif f == 6:
                sig = v
        return cls(height, round_, block_id, ts, signers, sig)


@dataclass(frozen=True)
class Block:
    header: Header
    txs: tuple
    evidence: tuple
    last_commit: Commit

    def __post_init__(self):
        object.__setattr__(self, "txs", tuple(self.txs))
        object.__setattr__(self, "evidence", tuple(self.evidence))

    def hash(self) -> bytes:
        return self.header.hash()

    def data_hash(self) -> bytes:
        return txs_hash(self.txs)

    def validate_basic(self) -> None:
        self.header.validate_basic()
        self.last_commit.validate_basic()
        if self.header.height > 1 and self.last_commit.size() == 0:
            raise ValueError("nil LastCommit")
        if self.header.last_commit_hash != self.last_commit.hash():
            raise ValueError("wrong Header.LastCommitHash")
        if self.header.data_hash != self.data_hash():
            raise ValueError("wrong Header.DataHash")
        ev_hash = hash_from_byte_slices([e.hash() for e in self.evidence])
        if self.header.evidence_hash != ev_hash:
            raise ValueError("wrong Header.EvidenceHash")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.message_field(1, self.header.encode(), always=True)
        data = pw.Writer()
        for tx in self.txs:
            data.bytes_field(1, tx, emit_empty=True)
        w.message_field(2, data.bytes(), always=True)
        ev = pw.Writer()
        for e in self.evidence:
            ev.message_field(1, e.encode(), always=True)
        w.message_field(3, ev.bytes(), always=True)
        w.message_field(4, self.last_commit.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        from tendermint_tpu.types.evidence import decode_evidence

        header = None
        txs: List[bytes] = []
        evidence = []
        last_commit = EMPTY_COMMIT
        for f, _, v in pw.Reader(data):
            if f == 1:
                header = Header.decode(v)
            elif f == 2:
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        txs.append(vv)
            elif f == 3:
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        evidence.append(decode_evidence(vv))
            elif f == 4:
                last_commit = Commit.decode(v)
        if header is None:
            raise ValueError("block missing header")
        return cls(header, tuple(txs), tuple(evidence), last_commit)
