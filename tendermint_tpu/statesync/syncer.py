"""Syncer: restores state machine snapshots via ABCI + verifies via light client.

reference: statesync/syncer.go — syncer (:38), AddSnapshot (:78), SyncAny
(:130), Sync (:217), offerSnapshot (:276), applyChunks (:312), fetchChunks
(:369), requestChunk (:402), verifyApp (:423).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Dict, Optional, Set, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.state.sm_state import State
from tendermint_tpu.statesync.checkpoint import RestoreCheckpoint
from tendermint_tpu.statesync.chunks import Chunk, ChunkQueue, ChunkQueueClosed
from tendermint_tpu.statesync.snapshots import Snapshot, SnapshotPool
from tendermint_tpu.statesync.stateprovider import StateProvider
from tendermint_tpu.types.block import Commit

logger = logging.getLogger("tendermint_tpu.statesync")

# reference: statesync/syncer.go:21-35. CHUNK_TIMEOUT is only the
# no-config default: the node path passes [statesync] chunk_request_timeout
# through StatesyncReactor.sync (node/node.py _run_state_sync).
CHUNK_TIMEOUT = 2 * 60.0
MIN_SNAPSHOT_PEERS = 1
# retry-ladder defaults (the [statesync] chunk_retries / chunk_backoff
# knobs override these on the node path)
CHUNK_RETRIES = 8
CHUNK_BACKOFF = 0.25
CHUNK_BACKOFF_MAX = 30.0


class SyncError(Exception):
    pass


class ErrNoSnapshots(SyncError):
    """reference: statesync/syncer.go errNoSnapshots."""


class ErrAbort(SyncError):
    """App returned ABORT (reference: errAbort)."""


class ErrRejectSnapshot(SyncError):
    pass


class ErrRejectFormat(SyncError):
    pass


class ErrRejectSender(SyncError):
    pass


class ErrVerifyFailed(SyncError):
    """App hash or height mismatch after restore (reference: errVerifyFailed)."""


class ErrChunkFetchFailed(SyncError):
    """A chunk exhausted its retry budget (timeouts across peers, or no
    snapshot peers left). The structured terminus of the retry ladder: the
    snapshot is rejected, and when no snapshot remains sync_any raises
    ErrNoSnapshots — which the node turns into the blocksync-from-genesis
    fallback (ISSUE 12)."""


class Syncer:
    """reference: statesync/syncer.go:38.

    request_chunk(peer_id, height, format, index) is an async callback into
    the reactor; conn_snapshot/conn_query are ABCI clients (snapshot + query
    connections of the 4-conn proxy)."""

    def __init__(
        self,
        state_provider: StateProvider,
        conn_snapshot,
        conn_query,
        request_chunk: Callable,
        chunk_fetchers: int = 4,
        chunk_timeout: float = CHUNK_TIMEOUT,
        metrics=None,
        chunk_retries: int = CHUNK_RETRIES,
        chunk_backoff: float = CHUNK_BACKOFF,
        punish_peer: Optional[Callable] = None,
        checkpoint: Optional[RestoreCheckpoint] = None,
    ):
        self.state_provider = state_provider
        self.conn_snapshot = conn_snapshot
        self.conn_query = conn_query
        self.request_chunk = request_chunk
        self.chunk_fetchers = chunk_fetchers
        self.chunk_timeout = chunk_timeout
        self.metrics = metrics  # StateSyncMetrics or None
        # retry ladder: every chunk gets chunk_retries re-requests with
        # exponential backoff (chunk_backoff * 2^k), each routed to a
        # different peer than the last when one exists
        self.chunk_retries = int(chunk_retries)
        self.chunk_backoff = float(chunk_backoff)
        # punish_peer(peer_id, reason) -> awaitable: behaviour report into
        # the trust scorer (reactor wiring); None = no punishment side channel
        self.punish_peer = punish_peer
        self.checkpoint = checkpoint or RestoreCheckpoint(None)
        self.snapshots = SnapshotPool()
        self.chunk_queue: Optional[ChunkQueue] = None
        self._processing: Optional[Snapshot] = None
        self._chunk_attempts: Dict[int, int] = {}
        self._last_sender: Dict[int, str] = {}
        self._applied: Set[int] = set()

    # ---------------------------------------------------------------- intake

    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        """reference: syncer.go:78 AddSnapshot."""
        added = self.snapshots.add(peer_id, snapshot)
        if added:
            if self.metrics is not None:
                self.metrics.snapshots_discovered_total.inc()
            logger.info(
                "discovered snapshot height=%d format=%d chunks=%d from %s",
                snapshot.height, snapshot.format, snapshot.chunks, peer_id[:10],
            )
        return added

    def add_chunk(self, chunk: Chunk) -> bool:
        """reference: syncer.go:110 AddChunk."""
        q = self.chunk_queue
        if q is None or self._processing is None:
            return False
        if chunk.height != self._processing.height or chunk.format != self._processing.format:
            return False
        return q.add(chunk)

    def remove_peer(self, peer_id: str) -> None:
        self.snapshots.remove_peer(peer_id)

    # ------------------------------------------------------------------ sync

    async def sync_any(self, discovery_time: float) -> Tuple[State, Commit]:
        """Try snapshots best-first until one restores
        (reference: syncer.go:130 SyncAny)."""
        if discovery_time > 0:
            logger.info("discovering snapshots for %.1fs", discovery_time)
            await asyncio.sleep(discovery_time)
        while True:
            snapshot = self.snapshots.best()
            if snapshot is None:
                raise ErrNoSnapshots("no viable snapshots found")
            try:
                return await self.sync(snapshot)
            except ErrRejectSnapshot:
                logger.info("snapshot height=%d rejected; trying next", snapshot.height)
                self.snapshots.reject(snapshot)
                self.checkpoint.clear()
            except ErrRejectFormat:
                logger.info("snapshot format %d rejected; trying next", snapshot.format)
                self.snapshots.reject_format(snapshot.format)
                self.checkpoint.clear()
            except ErrRejectSender:
                logger.info("snapshot senders rejected; trying next")
                for peer_id in self.snapshots.get_peers(snapshot):
                    self.snapshots.reject_peer(peer_id)
                self.snapshots.reject(snapshot)
                self.checkpoint.clear()
            except ErrChunkFetchFailed as e:
                logger.warning(
                    "snapshot height=%d abandoned: %s; trying next",
                    snapshot.height, e,
                )
                self.snapshots.reject(snapshot)
                self.checkpoint.clear()
            except ErrVerifyFailed:
                logger.warning("snapshot height=%d failed verification; trying next", snapshot.height)
                self.snapshots.reject(snapshot)
                # the checkpointed applied-set proved unreliable (the app's
                # side of those applies evidently did not survive): clear so
                # the next attempt starts fresh
                self.checkpoint.clear()
            finally:
                if self.chunk_queue is not None:
                    self.chunk_queue.close()
                self.chunk_queue = None
                self._processing = None

    async def sync(self, snapshot: Snapshot) -> Tuple[State, Commit]:
        """Restore one snapshot (reference: syncer.go:217 Sync)."""
        # fetch the trusted app hash BEFORE offering (reference: :226).
        # A provider failure here is a property of THIS snapshot (e.g. the
        # newest snapshot's height+2 light verification needs blocks the
        # chain hasn't committed yet) — reject it and let sync_any try the
        # next-best one instead of killing the whole state sync
        try:
            app_hash = await self.state_provider.app_hash(snapshot.height)
        except asyncio.CancelledError:
            raise
        except SyncError:
            raise
        except Exception as e:
            raise ErrVerifyFailed(
                f"state provider failed for snapshot height "
                f"{snapshot.height}: {e}"
            ) from e
        snapshot = Snapshot(
            snapshot.height, snapshot.format, snapshot.chunks,
            snapshot.hash, snapshot.metadata, trusted_app_hash=app_hash,
        )
        self._processing = snapshot
        self.chunk_queue = ChunkQueue(snapshot)
        self._chunk_attempts = {}
        self._last_sender = {}
        self._applied = set()
        if self.metrics is not None:
            self.metrics.snapshot_height.set(snapshot.height)
            self.metrics.snapshot_chunks_total.set(snapshot.chunks)

        await self._offer_snapshot(snapshot)

        # crash-resume (ISSUE 12): the snapshot was re-offered above; skip
        # the chunks a previous life already applied
        resumed = self.checkpoint.load(snapshot)
        if resumed:
            for index in sorted(resumed):
                self.chunk_queue.mark_applied(index)
            self._applied = set(resumed)
            if self.metrics is not None:
                self.metrics.resume_events_total.inc()
            logger.info(
                "resuming snapshot restore at height %d: %d/%d chunks "
                "already applied before the crash",
                snapshot.height, len(resumed), snapshot.chunks,
            )

        fetchers = [
            asyncio.create_task(self._fetch_chunks(), name=f"ss-fetch-{i}")
            for i in range(self.chunk_fetchers)
        ]
        # concurrently: build verified state via light client + apply chunks;
        # gather surfaces the FIRST failure immediately so a dead light
        # client aborts the sync instead of waiting out slow chunk peers
        state_task = asyncio.create_task(self.state_provider.state(snapshot.height))
        commit_task = asyncio.create_task(self.state_provider.commit(snapshot.height))
        apply_task = asyncio.create_task(self._apply_chunks(self.chunk_queue))
        try:
            _, state, commit = await asyncio.gather(apply_task, state_task, commit_task)
        except BaseException as e:
            for t in (apply_task, state_task, commit_task):
                t.cancel()
            if not isinstance(e, (SyncError, asyncio.CancelledError)):
                # light-provider/transport failures are snapshot-scoped too:
                # reject this snapshot, try the next (sync_any's ladder)
                raise ErrVerifyFailed(
                    f"state/commit verification failed for snapshot height "
                    f"{snapshot.height}: {e}"
                ) from e
            raise
        finally:
            for f in fetchers:
                f.cancel()

        await self._verify_app(snapshot, state)
        self.checkpoint.clear()
        logger.info("snapshot restored at height %d", snapshot.height)
        return state, commit

    async def _offer_snapshot(self, snapshot: Snapshot) -> None:
        """reference: syncer.go:276 offerSnapshot."""
        resp = self.conn_snapshot.offer_snapshot(
            abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(
                    height=snapshot.height,
                    format=snapshot.format,
                    chunks=snapshot.chunks,
                    hash=snapshot.hash,
                    metadata=snapshot.metadata,
                ),
                app_hash=snapshot.trusted_app_hash,
            )
        )
        r = resp.result
        if r == abci.OFFER_SNAPSHOT_ACCEPT:
            logger.info("snapshot height=%d format=%d accepted", snapshot.height, snapshot.format)
        elif r == abci.OFFER_SNAPSHOT_ABORT:
            raise ErrAbort("app aborted state sync")
        elif r == abci.OFFER_SNAPSHOT_REJECT:
            raise ErrRejectSnapshot("app rejected snapshot")
        elif r == abci.OFFER_SNAPSHOT_REJECT_FORMAT:
            raise ErrRejectFormat("app rejected format")
        elif r == abci.OFFER_SNAPSHOT_REJECT_SENDER:
            raise ErrRejectSender("app rejected senders")
        else:
            raise SyncError(f"unknown OfferSnapshot result {r}")

    def _bump_attempts(self, index: int, q: ChunkQueue, reason: str) -> bool:
        """Count one failed fetch attempt; True while the retry budget
        holds, False after failing the queue (ladder exhausted)."""
        n = self._chunk_attempts.get(index, 0) + 1
        self._chunk_attempts[index] = n
        if n > self.chunk_retries:
            q.fail(ErrChunkFetchFailed(
                f"chunk {index}: {reason} after {n - 1} retries"
            ))
            return False
        return True

    async def _fetch_chunks(self) -> None:
        """One fetcher worker (reference: syncer.go:369 fetchChunks), with
        the ISSUE 12 retry ladder: exponential backoff per attempt, each
        re-request routed to a different peer than the last when one
        exists, budget capped at chunk_retries before the snapshot is
        abandoned through ChunkQueue.fail."""
        import random

        q = self.chunk_queue
        snapshot = self._processing
        try:
            while True:
                index = q.allocate()
                if index is None:
                    if q.done():
                        return
                    await asyncio.sleep(0.05)
                    continue
                attempt = self._chunk_attempts.get(index, 0)
                if attempt > 0:
                    if self.metrics is not None:
                        self.metrics.chunk_retries_total.inc()
                    await asyncio.sleep(min(
                        self.chunk_backoff * (2 ** (attempt - 1)),
                        CHUNK_BACKOFF_MAX,
                    ))
                peers = self.snapshots.get_peers(snapshot)
                if not peers:
                    # all snapshot peers gone/rejected: bounded patience
                    # through the same budget, then the structured failure
                    if not self._bump_attempts(index, q, "no snapshot peers"):
                        return
                    q.retry(index)
                    await asyncio.sleep(self.chunk_backoff)
                    continue
                # random peer per request so a silent-but-connected peer
                # can't pin a chunk forever (reference: syncer.go:402) —
                # but never the SAME peer twice in a row when another exists
                avoid = self._last_sender.get(index)
                candidates = [p for p in peers if p != avoid] or peers
                peer_id = random.choice(candidates)
                self._last_sender[index] = peer_id
                await self.request_chunk(peer_id, snapshot.height, snapshot.format, index)
                # wait for it to arrive; retry on timeout (reference: :390)
                deadline = asyncio.get_event_loop().time() + self.chunk_timeout
                while not q.has(index) and index not in q._returned:
                    if asyncio.get_event_loop().time() > deadline:
                        if not self._bump_attempts(index, q, "fetch timeout"):
                            return
                        q.retry(index)
                        break
                    await asyncio.sleep(0.05)
        except (asyncio.CancelledError, ChunkQueueClosed):
            pass

    async def _punish(self, peer_id: str, reason: str) -> None:
        if not peer_id or self.punish_peer is None:
            return
        try:
            await self.punish_peer(peer_id, reason)
        except Exception:
            logger.exception("punishing statesync peer %s failed", peer_id[:10])

    async def _apply_chunks(self, q: ChunkQueue) -> None:
        """reference: syncer.go:312 applyChunks, plus ISSUE 12: corrupt
        chunks punish their sender and re-queue (from a different peer —
        the fetcher's avoid-last-sender routing), and every ACCEPT is
        checkpointed so a crash mid-restore resumes past it."""
        while True:
            if q.done():
                return  # crash-resume may have marked every chunk applied
            chunk = await q.next()
            resp = self.conn_snapshot.apply_snapshot_chunk(
                abci.RequestApplySnapshotChunk(
                    index=chunk.index, chunk=chunk.chunk, sender=chunk.sender
                )
            )
            # punishment lists apply regardless of result (reference: :330)
            for peer_id in resp.reject_senders:
                self.snapshots.reject_peer(peer_id)
                q.discard_sender(peer_id)
                await self._punish(peer_id, "app rejected snapshot sender")
            for index in resp.refetch_chunks:
                q.retry(index)
                self._applied.discard(index)
            if resp.refetch_chunks:
                # keep the on-disk applied-set honest: a crash before the
                # refetched chunk lands must not resume past it
                self.checkpoint.save(self._processing, self._applied)

            r = resp.result
            if r == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT:
                if self.metrics is not None:
                    self.metrics.chunks_applied_total.inc()
                self._applied.add(chunk.index)
                self.checkpoint.save(self._processing, self._applied)
                if q.done():
                    return
            elif r == abci.APPLY_SNAPSHOT_CHUNK_ABORT:
                raise ErrAbort("app aborted chunk apply")
            elif r == abci.APPLY_SNAPSHOT_CHUNK_RETRY:
                # the app refused the bytes (corrupt/torn chunk): punish the
                # sender and re-queue; the fetcher's backoff + peer-switch
                # ladder sources the refetch elsewhere
                if self.metrics is not None:
                    self.metrics.bad_chunks_total.inc()
                await self._punish(chunk.sender, "corrupt snapshot chunk")
                # corrupt serves consume the same retry budget as timeouts:
                # a net where EVERY peer serves corrupt bytes must abandon
                # the snapshot, not loop forever
                self._bump_attempts(chunk.index, q, "corrupt chunk")
                q.retry(chunk.index)
            elif r == abci.APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT:
                q.retry_all()
                self._applied.clear()
                self.checkpoint.save(self._processing, self._applied)
            elif r == abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT:
                raise ErrRejectSnapshot("app rejected snapshot during chunk apply")
            else:
                raise SyncError(f"unknown ApplySnapshotChunk result {r}")

    async def _verify_app(self, snapshot: Snapshot, state: State) -> None:
        """The app must now report the trusted hash/height
        (reference: syncer.go:423 verifyApp)."""
        resp = self.conn_query.info(abci.RequestInfo())
        if resp.last_block_app_hash != snapshot.trusted_app_hash:
            raise ErrVerifyFailed(
                f"app hash mismatch: expected {snapshot.trusted_app_hash.hex()}, "
                f"got {resp.last_block_app_hash.hex()}"
            )
        if resp.last_block_height != snapshot.height:
            raise ErrVerifyFailed(
                f"app height mismatch: expected {snapshot.height}, "
                f"got {resp.last_block_height}"
            )
