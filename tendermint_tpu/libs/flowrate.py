"""Flow rate monitoring/limiting (reference: libs/flowrate/flowrate.go).

An EWMA byte-rate monitor with an async limiter: MConnection calls
`await limit(n, rate)` around sends/recvs; returns immediately while under
the rate, sleeps just enough when over it."""

from __future__ import annotations

import asyncio
import time


class Monitor:
    def __init__(self, sample_period: float = 0.1, window: float = 1.0):
        self.start = time.monotonic()
        self.total = 0
        self.rate_avg = 0.0  # EWMA bytes/sec
        self._window = window
        self._last = self.start
        self._acc = 0
        self._tokens = 0.0  # token bucket for limit(); capped at 1 window
        self._tokens_ts = self.start

    def update(self, n: int) -> None:
        now = time.monotonic()
        self.total += n
        self._acc += n
        dt = now - self._last
        if dt >= self._window:
            inst = self._acc / dt
            alpha = 0.5
            self.rate_avg = inst if self.rate_avg == 0 else (alpha * inst + (1 - alpha) * self.rate_avg)
            self._acc = 0
            self._last = now

    def status_rate(self) -> float:
        """Current average rate estimate in bytes/sec."""
        now = time.monotonic()
        dt = now - self._last
        if dt >= self._window and dt > 0:
            inst = self._acc / dt
            return 0.5 * inst + 0.5 * self.rate_avg
        return self.rate_avg

    async def limit(self, n: int, rate: int) -> None:
        """Account n bytes; sleep as needed to keep the rate under `rate`
        bytes/sec. True token bucket with burst capped at one window — idle
        time does NOT bank unbounded credit (a peer that idles an hour then
        floods is limited immediately)."""
        self.update(n)
        if rate <= 0:
            return
        now = time.monotonic()
        burst = rate * self._window
        self._tokens = min(burst, self._tokens + rate * (now - self._tokens_ts))
        self._tokens_ts = now
        self._tokens -= n
        if self._tokens < 0:
            await asyncio.sleep(min(-self._tokens / rate, 1.0))
