"""Chunk queue for the snapshot being restored.

reference: statesync/chunks.go — chunk (:20), chunkQueue (:27), Add (:85),
Allocate (:117), Next (:193), Retry (:221), DiscardSender (:160).

The reference spools chunks to temp files; chunks here are small enough for
the in-memory dict (the ABCI chunk-size cap is 16MB either way).
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Set

from tendermint_tpu.statesync.snapshots import Snapshot


class ChunkQueueClosed(Exception):
    pass


class Chunk:
    __slots__ = ("height", "format", "index", "chunk", "sender")

    def __init__(self, height: int, format: int, index: int, chunk: bytes, sender: str):
        self.height = height
        self.format = format
        self.index = index
        self.chunk = chunk
        self.sender = sender


class ChunkQueue:
    """reference: statesync/chunks.go:27."""

    def __init__(self, snapshot: Snapshot):
        self.snapshot = snapshot
        self._chunks: Dict[int, Chunk] = {}
        self._allocated: Set[int] = set()
        self._returned: Set[int] = set()
        self._next_return = 0
        self._event = asyncio.Event()
        self.closed = False
        self._failure: Optional[BaseException] = None

    def allocate(self) -> Optional[int]:
        """Hand out an unallocated chunk index for fetching, or None when all
        are allocated (reference: :117 Allocate)."""
        if self.closed:
            raise ChunkQueueClosed
        for i in range(self.snapshot.chunks):
            if (
                i not in self._allocated
                and i not in self._chunks
                and i not in self._returned  # crash-resume: already applied
            ):
                self._allocated.add(i)
                return i
        return None

    def add(self, chunk: Chunk) -> bool:
        """Store a fetched chunk; True if new (reference: :85 Add)."""
        if self.closed:
            return False
        if not (0 <= chunk.index < self.snapshot.chunks):
            raise ValueError(f"chunk index {chunk.index} out of range")
        if chunk.index in self._chunks:
            return False
        self._chunks[chunk.index] = chunk
        self._allocated.discard(chunk.index)
        self._event.set()
        return True

    def has(self, index: int) -> bool:
        return index in self._chunks

    async def next(self) -> Chunk:
        """Blocking, in-order retrieval for the applier
        (reference: :193 Next). Indices already returned (and not since
        retried) are skipped, so a retry() of an early chunk re-delivers just
        that chunk and then resumes where the applier left off."""
        while True:
            if self._failure is not None:
                raise self._failure
            if self.closed:
                raise ChunkQueueClosed
            while self._next_return in self._returned:
                self._next_return += 1
            c = self._chunks.get(self._next_return)
            if c is not None:
                self._returned.add(self._next_return)
                self._next_return += 1
                return c
            self._event.clear()
            await self._event.wait()

    def fail(self, exc: BaseException) -> None:
        """A fetcher exhausted its retry budget: wake the applier with the
        error instead of letting it wait forever on a chunk that will never
        arrive (the structured terminus of the retry ladder — the syncer
        rejects the snapshot and sync_any moves on / falls back)."""
        if self._failure is None:
            self._failure = exc
        self._event.set()

    def mark_applied(self, index: int) -> None:
        """Resume support (ISSUE 12): mark a chunk as already returned AND
        applied in a previous life, so neither the fetchers nor the applier
        touch it after a crash-resume re-offer."""
        if 0 <= index < self.snapshot.chunks:
            self._returned.add(index)
            self._allocated.discard(index)
            self._event.set()

    def retry(self, index: int) -> None:
        """Make a chunk (re)fetchable and (re)returnable
        (reference: :221 Retry)."""
        self._chunks.pop(index, None)
        self._allocated.discard(index)
        self._returned.discard(index)
        self._next_return = min(self._next_return, index)
        self._event.set()

    def retry_all(self) -> None:
        for i in range(self.snapshot.chunks):
            self.retry(i)

    def discard_sender(self, peer_id: str) -> None:
        """Drop unreturned chunks from a bad sender (reference: :160)."""
        for i, c in list(self._chunks.items()):
            if c.sender == peer_id and i not in self._returned:
                self.retry(i)

    def get_sender(self, index: int) -> str:
        c = self._chunks.get(index)
        return c.sender if c else ""

    def done(self) -> bool:
        return len(self._returned) == self.snapshot.chunks

    def close(self) -> None:
        self.closed = True
        self._event.set()
