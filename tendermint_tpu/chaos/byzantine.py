"""Byzantine behaviors for chaos soaks (reference test model:
consensus/byzantine_test.go:35).

`install_equivocator` swaps a node's prevote behavior via the hook the state
machine exposes for exactly this (cs_state.do_prevote): each round it signs
the honest prevote AND a conflicting prevote for a fabricated BlockID with
the RAW key (a byzantine validator ignores the double-sign guard), then
gossips the conflict. A fabricated hash can never equal the honest prevote,
so EVERY round produces a detectable equivocation — the honest nodes must
turn it into DuplicateVoteEvidence and commit it.

`poison_votes` is the signature-poisoning flood (adversarial flush defense,
crypto/provenance.py): the target gossips `count` votes whose signatures are
REAL ed25519 signatures — valid point encoding, s < L, so they sail through
the cheap host precheck — but signed over the WRONG bytes, so they fail the
device batch verify and force RLC recovery flushes on every honest receiver.
Each vote carries a distinct fabricated BlockID so the deferred vote-set
dedup cannot collapse the flood. The defense under test: receivers' suspicion
scorers must quarantine the poisoning peer (rerouting its rows to the
scheduler's quarantine lane) and then punish it through the p2p trust scorer.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time


def install_equivocator(node) -> None:
    from tendermint_tpu.consensus.messages import VoteMessage, encode_message
    from tendermint_tpu.consensus.reactor import VOTE_CHANNEL
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.vote import Vote

    cs = node.consensus
    orig_do_prevote = cs._default_do_prevote

    def byz_do_prevote(height: int, round_: int) -> None:
        orig_do_prevote(height, round_)
        rs = cs.rs
        addr = node.priv_validator.get_pub_key().address()
        idx, _ = rs.validators.get_by_address(addr)
        if idx < 0:
            return
        vote = Vote(
            type=SignedMsgType.PREVOTE,
            height=height,
            round=round_,
            block_id=BlockID(b"\x42" * 32, PartSetHeader(1, b"\x42" * 32)),
            timestamp_ns=time.time_ns(),
            validator_address=addr,
            validator_index=idx,
        )
        sig = node.priv_validator.priv_key.sign(vote.sign_bytes(cs.state.chain_id))
        vote = dataclasses.replace(vote, signature=sig)

        async def gossip():
            try:
                await node.switch.broadcast(
                    VOTE_CHANNEL, encode_message(VoteMessage(vote))
                )
            except Exception:
                pass  # a dying switch mid-chaos must not kill the loop

        asyncio.ensure_future(gossip())

    cs.do_prevote = byz_do_prevote


async def poison_votes(node, count: int) -> int:
    """Gossip `count` precheck-passing but verify-failing votes from `node`
    (module docstring). Returns how many were actually broadcast."""
    from tendermint_tpu.consensus.messages import VoteMessage, encode_message
    from tendermint_tpu.consensus.reactor import VOTE_CHANNEL
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.vote import Vote

    cs = node.consensus
    rs = cs.rs
    addr = node.priv_validator.get_pub_key().address()
    idx, _ = rs.validators.get_by_address(addr)
    if idx < 0 or node.switch is None:
        return 0
    sent = 0
    for i in range(max(0, int(count))):
        # distinct fabricated BlockID per vote: the deferred vote-set dedup
        # keys on (validator, block, signature), so a repeated id would
        # collapse the flood to one row
        tag = bytes([0x50 + (i % 0xA0)]) + i.to_bytes(4, "big") + b"\x51" * 27
        vote = Vote(
            type=SignedMsgType.PREVOTE,
            height=rs.height,
            round=rs.round,
            block_id=BlockID(tag, PartSetHeader(1, tag)),
            timestamp_ns=time.time_ns(),
            validator_address=addr,
            validator_index=idx,
        )
        # the poison: a REAL signature (passes precheck) over bytes that are
        # NOT this vote's sign bytes (fails verification)
        sig = node.priv_validator.priv_key.sign(
            b"tmtpu-sig-poison:" + i.to_bytes(4, "big")
        )
        vote = dataclasses.replace(vote, signature=sig)
        try:
            await node.switch.broadcast(
                VOTE_CHANNEL, encode_message(VoteMessage(vote))
            )
            sent += 1
        except Exception:
            pass  # a dying switch mid-chaos must not kill the flood loop
    return sent
