"""Stall forensics (libs/forensics.py): heartbeat ring write/read, watchdog
capture with a deliberately hung child process, and the chaos-hang
integration at the crypto/batch device entry points — the pipeline that
turns the next MULTICHIP rc-124 into a diagnosis instead of a bare -1."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from tendermint_tpu.libs import forensics as F

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _unconfigured_after():
    yield
    F.configure(None)


def test_heartbeat_write_read_roundtrip(tmp_path):
    hb = F.Heartbeat(str(tmp_path / "hb.bin"), slots=8)
    for i in range(3):
        hb.beat(f"phase{i}")
    beats = F.Heartbeat.read(hb.path)
    assert [b["phase"] for b in beats] == ["phase0", "phase1", "phase2"]
    assert [b["seq"] for b in beats] == [1, 2, 3]
    assert all(b["pid"] == os.getpid() for b in beats)
    assert all(b["age_s"] < 60 for b in beats)


def test_heartbeat_ring_wraps_keeping_newest(tmp_path):
    hb = F.Heartbeat(str(tmp_path / "hb.bin"), slots=4)
    for i in range(10):
        hb.beat(f"p{i}")
    beats = F.Heartbeat.read(hb.path)
    assert [b["phase"] for b in beats] == ["p6", "p7", "p8", "p9"]
    assert F.Heartbeat.read(hb.path, limit=2)[-1]["phase"] == "p9"


def test_heartbeat_sequence_survives_reopen(tmp_path):
    """A restarted process continues the sequence instead of erasing the
    pre-crash tail an investigator may still want."""
    p = str(tmp_path / "hb.bin")
    F.Heartbeat(p, slots=8).beat("before-crash")
    F.Heartbeat(p, slots=8).beat("after-restart")
    assert [b["phase"] for b in F.Heartbeat.read(p)] == [
        "before-crash", "after-restart"
    ]


def test_heartbeat_read_rejects_foreign_file(tmp_path):
    p = tmp_path / "not_hb.bin"
    p.write_bytes(b"definitely not a heartbeat ring" * 4)
    with pytest.raises(ValueError):
        F.Heartbeat.read(str(p))


def test_module_beat_is_noop_until_configured(tmp_path):
    F.configure(None)
    assert not F.enabled() and F.heartbeat_path() is None
    F.beat("anything")  # must not raise
    path = F.configure(str(tmp_path))
    assert F.enabled() and path == F.heartbeat_path()
    F.beat("rlc_submit")
    assert F.Heartbeat.read(path)[-1]["phase"] == "rlc_submit"


def test_configure_sweeps_stale_heartbeats_from_dead_pids(tmp_path):
    """Node start must not leave one heartbeat corpse per crashed pid
    (ISSUE 8 satellite): configure() sweeps rings whose pid is dead, keeps
    OUR ring and any live process's, ignores non-heartbeat files."""
    import subprocess

    # a pid that is certainly dead: a waited-on child (not yet recycled)
    child = subprocess.Popen(["true"])
    child.wait()
    dead = tmp_path / f"heartbeat_{child.pid}.bin"
    dead.write_bytes(b"stale ring")
    mine = tmp_path / f"heartbeat_{os.getpid()}.bin"
    mine.write_bytes(b"live ring")
    bystander = tmp_path / "not_a_heartbeat.bin"
    bystander.write_bytes(b"keep me")

    removed = F.sweep_stale_heartbeats(str(tmp_path))
    assert str(dead) in removed
    assert not dead.exists()
    assert mine.exists() and bystander.exists()

    # configure() sweeps too (the node-start path) and creates our ring
    dead.write_bytes(b"stale again")
    path = F.configure(str(tmp_path))
    assert not dead.exists()
    assert os.path.exists(path)


def test_capture_names_wedged_phase(tmp_path):
    F.configure(str(tmp_path))
    F.beat("rlc_submit")
    F.beat("rlc_finish")
    path = F.capture("unit test", kind="manual", probe_devices=False)
    assert os.path.basename(path).startswith("FORENSICS_")
    with open(path) as f:
        doc = json.load(f)
    assert doc["wedged_phase"] == "rlc_finish"  # the newest heartbeat
    assert doc["kind"] == "manual" and doc["reason"] == "unit test"
    assert doc["heartbeat"][-1]["phase"] == "rlc_finish"
    assert "thread" in doc["threads"].lower()  # faulthandler stack dump
    assert doc["breaker"]  # snapshot (or an error string — never absent)
    assert doc["jax"] == {"skipped": True}
    assert path in F.find_captures(str(tmp_path))
    assert F.find_captures(str(tmp_path), since_ts=time.time() + 60) == []


def test_two_captures_same_second_do_not_collide(tmp_path):
    F.configure(str(tmp_path))
    p1 = F.capture("first", probe_devices=False)
    p2 = F.capture("second", probe_devices=False)
    assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)


def test_watchdog_fires_and_cancel_suppresses(tmp_path):
    fired = threading.Event()
    wd = F.Watchdog(
        0.2, "unit hang", out_dir=str(tmp_path), on_fire=lambda w: fired.set()
    ).start()
    assert fired.wait(20)
    assert wd.fired and wd.capture_path and os.path.exists(wd.capture_path)
    with open(wd.capture_path) as f:
        assert json.load(f)["kind"] == "watchdog"

    wd2 = F.Watchdog(0.3, "cancelled", out_dir=str(tmp_path))
    with wd2:
        pass
    time.sleep(0.5)
    assert not wd2.fired


def test_hung_child_process_yields_forensics(tmp_path):
    """The BENCH_r05 shape, end to end: a child wedges with its main thread
    asleep in C; its watchdog THREAD still captures a FORENSICS_*.json
    naming the wedged phase, and the parent (us) reads the diagnosis from
    outside while the child is still hung."""
    child = tmp_path / "hang_child.py"
    child.write_text(
        "import sys, time\n"
        f"sys.path.insert(0, {ROOT!r})\n"
        "from tendermint_tpu.libs import forensics as F\n"
        f"F.configure({str(tmp_path)!r})\n"
        "F.beat('mesh_rlc_submit')\n"
        "F.Watchdog(0.3, 'child wedged in mesh_rlc_submit').start()\n"
        "time.sleep(600)\n"
    )
    t0 = time.time()
    proc = subprocess.Popen([sys.executable, str(child)])
    try:
        deadline = time.time() + 60
        captures = []
        while time.time() < deadline:
            captures = F.find_captures(str(tmp_path), since_ts=t0 - 1)
            if captures:
                break
            time.sleep(0.25)
        assert captures, "hung child produced no FORENSICS_*.json"
        assert proc.poll() is None, "child must still be hung while we read"
        with open(captures[-1]) as f:
            doc = json.load(f)
        assert doc["wedged_phase"] == "mesh_rlc_submit"
        assert doc["kind"] == "watchdog"
        assert doc["pid"] == proc.pid
        # the heartbeat ring is independently readable from outside too
        hb_files = [n for n in os.listdir(tmp_path) if n.startswith("heartbeat_")]
        assert hb_files
        beats = F.Heartbeat.read(str(tmp_path / hb_files[0]))
        assert beats[-1]["phase"] == "mesh_rlc_submit"
    finally:
        proc.kill()
        proc.wait(30)


def test_chaos_hang_hook_produces_forensics(tmp_path):
    """Acceptance loop for the fault-injected hung flush: the PR 4 chaos
    hang hook stalls a device entry point AFTER _device_fault stamped its
    heartbeat, so the armed watchdog's capture names the wedged phase."""
    from tendermint_tpu.chaos.device import DeviceFaultInjector
    from tendermint_tpu.crypto import batch as B

    F.configure(str(tmp_path))
    inj = DeviceFaultInjector()
    inj.arm_hang(1.5)
    B.set_device_fault_hook(inj)
    fired = threading.Event()
    wd = F.Watchdog(
        0.3, "flush wedged under chaos hang",
        out_dir=str(tmp_path), on_fire=lambda w: fired.set(),
    ).start()
    try:
        B._device_fault("rlc_submit")  # beats, then hangs in the hook
    finally:
        B.set_device_fault_hook(None)
        wd.cancel()
    assert fired.wait(20)
    assert inj.fired == [("rlc_submit", "hang")]
    with open(wd.capture_path) as f:
        doc = json.load(f)
    assert doc["wedged_phase"] == "rlc_submit"
    assert doc["kind"] == "watchdog"


def test_bench_forensics_for_kill_attaches_capture(tmp_path, monkeypatch):
    """bench.py's parent-side hook: a hard-deadline kill report carries the
    FORENSICS files the child left and the wedged phase from the newest."""
    import bench

    monkeypatch.setenv("TMTPU_FORENSICS_DIR", str(tmp_path))
    t0 = time.time() - 5
    F.configure(str(tmp_path))
    F.beat("mesh_persig_submit")
    F.capture("pre-kill", kind="watchdog", probe_devices=False)
    out = bench._forensics_for_kill(t0)
    assert out["forensics"]
    assert out["wedged_phase"] == "mesh_persig_submit"
    assert out["forensics_kind"] == "watchdog"
    # nothing newer than the window: nothing attached
    assert bench._forensics_for_kill(time.time() + 60) == {}


def test_env_default_configures_in_fresh_process(tmp_path):
    """TMTPU_FORENSICS_DIR alone (no configure() call) enables the
    heartbeat, mirroring TMTPU_TRACE — how bench children and operators
    opt in without code."""
    code = (
        "from tendermint_tpu.libs import forensics as F\n"
        "assert F.enabled(), 'env default must configure forensics'\n"
        "F.beat('probe')\n"
        "print(F.heartbeat_path())\n"
    )
    env = dict(os.environ, TMTPU_FORENSICS_DIR=str(tmp_path))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    hb_path = out.stdout.strip()
    assert hb_path.startswith(str(tmp_path))
    assert F.Heartbeat.read(hb_path)[-1]["phase"] == "probe"
