/* Native sr25519 (schnorrkel) verification: Schnorr over ristretto255 with
 * merlin transcript binding (reference: crypto/sr25519/pubkey.go:34 verifies
 * via go-schnorrkel). This mirrors the repo's from-scratch Python
 * implementation (crypto/sr25519.py + crypto/merlin.py, both written from the
 * public ristretto255 / Merlin / STROBE specifications) and is
 * differentially tested against it bit-for-bit (tests/test_native.py).
 *
 * Why native: the Python verifier costs ~5 ms/signature (bigint point_mul),
 * which both throttled the host path for mixed ed25519+sr25519 validator
 * sets and made the mixed-set benchmark baseline indefensibly slow. This C
 * path runs one verification in ~100 us single-threaded, so the benchmark's
 * host baseline is an honest native-speed verifier the framework itself
 * ships, and host-routed sr25519 rows stop dominating mixed batches.
 *
 * Field arithmetic: 4x64-bit limbs, __uint128_t products, loose (< 2^256)
 * representation with 2^256 === 38 (mod p) folding; canonical freeze only at
 * encode/compare boundaries. Curve constants are generated at build time
 * from their definitions (gen_constants.py), not copied from any
 * implementation. Verification is variable-time: public inputs only.
 */

#include <pthread.h>
#include <stdint.h>
#include <string.h>

#include "ed25519_constants.h" /* generated: FE_D, FE_D2, FE_SQRT_M1, ... */

typedef unsigned __int128 u128;

/* from batchhost.c (same shared object): X (8 limbs) mod L -> 4 limbs */
void tm_mod_l_512(const uint64_t *x, uint64_t *r);

/* ------------------------------------------------------------------ */
/* fe25519: arithmetic mod p = 2^255 - 19, 4x64 limbs, loose < 2^256   */

typedef struct {
  uint64_t v[4];
} fe;

static void fe_copy(fe *r, const fe *a) { memcpy(r->v, a->v, 32); }

static void fe_from_limbs(fe *r, const uint64_t *l) { memcpy(r->v, l, 32); }

static void fe_from_bytes(fe *r, const uint8_t b[32]) {
  for (int i = 0; i < 4; i++) {
    uint64_t w = 0;
    for (int j = 7; j >= 0; j--) w = (w << 8) | b[8 * i + j];
    r->v[i] = w;
  }
}

/* fold a 1-limb carry c: value += c * 38 (2^256 === 38 mod p) */
static void fe_fold(fe *r, uint64_t c) {
  u128 t = (u128)r->v[0] + (u128)c * 38;
  r->v[0] = (uint64_t)t;
  uint64_t carry = (uint64_t)(t >> 64);
  for (int i = 1; i < 4 && carry; i++) {
    t = (u128)r->v[i] + carry;
    r->v[i] = (uint64_t)t;
    carry = (uint64_t)(t >> 64);
  }
  /* carry can only be nonzero again if the value was ~2^256; one more
   * 38-fold is bounded and terminates */
  if (carry) fe_fold(r, carry);
}

static void fe_add(fe *r, const fe *a, const fe *b) {
  uint64_t carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)a->v[i] + b->v[i] + carry;
    r->v[i] = (uint64_t)t;
    carry = (uint64_t)(t >> 64);
  }
  fe_fold(r, carry);
}

/* r = a - b (mod p), computed as a + 4p - b to stay non-negative */
static void fe_sub(fe *r, const fe *a, const fe *b) {
  uint64_t t[5];
  uint64_t carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 s = (u128)a->v[i] + FE_4P[i] + carry;
    t[i] = (uint64_t)s;
    carry = (uint64_t)(s >> 64);
  }
  t[4] = FE_4P[4] + carry;
  uint64_t borrow = 0;
  for (int i = 0; i < 4; i++) {
    uint64_t bi = b->v[i] + borrow;
    uint64_t nb = (bi < borrow) || (t[i] < bi);
    t[i] -= bi;
    borrow = nb;
  }
  t[4] -= borrow;
  memcpy(r->v, t, 32);
  fe_fold(r, t[4]);
}

static void fe_mul(fe *r, const fe *a, const fe *b) {
  uint64_t lo[4] = {0, 0, 0, 0}, hi[4] = {0, 0, 0, 0};
  uint64_t w[8] = {0};
  for (int i = 0; i < 4; i++) {
    uint64_t carry = 0;
    for (int j = 0; j < 4; j++) {
      u128 t = (u128)a->v[i] * b->v[j] + w[i + j] + carry;
      w[i + j] = (uint64_t)t;
      carry = (uint64_t)(t >> 64);
    }
    w[i + 4] += carry;
  }
  memcpy(lo, w, 32);
  memcpy(hi, w + 4, 32);
  /* r = lo + 38*hi */
  uint64_t carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)hi[i] * 38 + lo[i] + carry;
    r->v[i] = (uint64_t)t;
    carry = (uint64_t)(t >> 64);
  }
  fe_fold(r, carry);
}

static void fe_sqr(fe *r, const fe *a) { fe_mul(r, a, a); }

static void fe_zero(fe *r) { memset(r->v, 0, 32); }

static void fe_one(fe *r) {
  fe_zero(r);
  r->v[0] = 1;
}

static void fe_neg(fe *r, const fe *a) {
  fe z;
  fe_zero(&z);
  fe_sub(r, &z, a);
}

/* canonical reduce into [0, p) */
static void fe_freeze(fe *r) {
  /* value < 2^256: subtract p at most a few times */
  for (int k = 0; k < 3; k++) {
    int ge = 0;
    for (int i = 3; i >= 0; i--) {
      if (r->v[i] != FE_P[i]) {
        ge = r->v[i] > FE_P[i];
        goto decided;
      }
    }
    ge = 1;
  decided:
    if (!ge) break;
    uint64_t borrow = 0;
    for (int i = 0; i < 4; i++) {
      uint64_t bi = FE_P[i] + borrow;
      uint64_t nb = (bi < borrow) || (r->v[i] < bi);
      r->v[i] -= bi;
      borrow = nb;
    }
  }
}

static void fe_to_bytes(uint8_t b[32], const fe *a) {
  fe t;
  fe_copy(&t, a);
  fe_freeze(&t);
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) b[8 * i + j] = (uint8_t)(t.v[i] >> (8 * j));
}

static int fe_is_negative(const fe *a) {
  fe t;
  fe_copy(&t, a);
  fe_freeze(&t);
  return (int)(t.v[0] & 1);
}

static int fe_eq(const fe *a, const fe *b) {
  uint8_t ba[32], bb[32];
  fe_to_bytes(ba, a);
  fe_to_bytes(bb, b);
  return memcmp(ba, bb, 32) == 0;
}

static void fe_cond_neg(fe *r, int neg) {
  if (neg) {
    fe t;
    fe_neg(&t, r);
    fe_copy(r, &t);
  }
}

static void fe_abs(fe *r) { fe_cond_neg(r, fe_is_negative(r)); }

/* r = a^((p-5)/8), square-and-multiply over the generated exponent */
static void fe_pow_p58(fe *r, const fe *a) {
  fe acc;
  fe_one(&acc);
  for (int bit = 252; bit >= 0; bit--) {
    fe_sqr(&acc, &acc);
    if ((FE_EXP_P58[bit >> 3] >> (bit & 7)) & 1) fe_mul(&acc, &acc, a);
  }
  fe_copy(r, &acc);
}

/* (was_square, sqrt(u/v) or sqrt(i*u/v)), non-negative
 * (ristretto255 spec SQRT_RATIO_M1; mirrors crypto/sr25519.py) */
static int fe_sqrt_ratio_m1(fe *out, const fe *u, const fe *v) {
  fe v3, v7, p, r, check, i, u_neg, u_neg_i;
  fe_sqr(&v3, v);
  fe_mul(&v3, &v3, v); /* v^3 */
  fe_sqr(&v7, &v3);
  fe_mul(&v7, &v7, v); /* v^7 */
  fe_mul(&p, u, &v7);
  fe_pow_p58(&p, &p);
  fe_mul(&r, u, &v3);
  fe_mul(&r, &r, &p); /* r = u * v^3 * (u*v^7)^((p-5)/8) */
  fe_sqr(&check, &r);
  fe_mul(&check, &check, v); /* check = v * r^2 */
  fe_from_limbs(&i, FE_SQRT_M1);
  fe_neg(&u_neg, u);
  fe_mul(&u_neg_i, &u_neg, &i);
  int correct = fe_eq(&check, u);
  int flipped = fe_eq(&check, &u_neg);
  int flipped_i = fe_eq(&check, &u_neg_i);
  if (flipped || flipped_i) fe_mul(&r, &r, &i);
  fe_abs(&r);
  fe_copy(out, &r);
  return correct || flipped;
}

/* ------------------------------------------------------------------ */
/* Edwards points, extended coordinates (a = -1)                       */

typedef struct {
  fe x, y, z, t;
} pt;

/* unified add-2008-hwcd-3 (mirrors crypto/ed25519_ref.point_add) */
static void pt_add(pt *r, const pt *p, const pt *q) {
  fe a, b, c, d, e, f, g, h, t1, t2;
  fe_sub(&t1, &p->y, &p->x);
  fe_sub(&t2, &q->y, &q->x);
  fe_mul(&a, &t1, &t2);
  fe_add(&t1, &p->y, &p->x);
  fe_add(&t2, &q->y, &q->x);
  fe_mul(&b, &t1, &t2);
  fe_from_limbs(&c, FE_D2);
  fe_mul(&c, &c, &p->t);
  fe_mul(&c, &c, &q->t);
  fe_mul(&d, &p->z, &q->z);
  fe_add(&d, &d, &d);
  fe_sub(&e, &b, &a);
  fe_sub(&f, &d, &c);
  fe_add(&g, &d, &c);
  fe_add(&h, &b, &a);
  fe_mul(&r->x, &e, &f);
  fe_mul(&r->y, &g, &h);
  fe_mul(&r->z, &f, &g);
  fe_mul(&r->t, &e, &h);
}

/* dble-2008-hwcd (mirrors crypto/ed25519_ref.point_double) */
static void pt_double(pt *r, const pt *p) {
  fe a, b, c, e, f, g, h, t1;
  fe_sqr(&a, &p->x);
  fe_sqr(&b, &p->y);
  fe_sqr(&c, &p->z);
  fe_add(&c, &c, &c);
  fe_add(&h, &a, &b);
  fe_add(&t1, &p->x, &p->y);
  fe_sqr(&t1, &t1);
  fe_sub(&e, &h, &t1);
  fe_sub(&g, &a, &b);
  fe_add(&f, &c, &g);
  fe_mul(&r->x, &e, &f);
  fe_mul(&r->y, &g, &h);
  fe_mul(&r->z, &f, &g);
  fe_mul(&r->t, &e, &h);
}

static void pt_identity(pt *r) {
  fe_zero(&r->x);
  fe_one(&r->y);
  fe_one(&r->z);
  fe_zero(&r->t);
}

static void pt_neg(pt *r, const pt *p) {
  fe_neg(&r->x, &p->x);
  fe_copy(&r->y, &p->y);
  fe_copy(&r->z, &p->z);
  fe_neg(&r->t, &p->t);
}

/* r = s*B + k*Q, vartime Strauss–Shamir; s, k: 32-byte LE scalars */
static void pt_double_scalar_mul_base(pt *r, const uint8_t s[32], const pt *q,
                                      const uint8_t k[32]) {
  pt base, table[3];
  fe_from_limbs(&base.x, FE_BASE_X);
  fe_from_limbs(&base.y, FE_BASE_Y);
  fe_one(&base.z);
  fe_from_limbs(&base.t, FE_BASE_T);
  table[0] = base; /* 01: B */
  table[1] = *q;   /* 10: Q */
  pt_add(&table[2], &base, q); /* 11 */
  pt acc;
  pt_identity(&acc);
  int started = 0;
  for (int bit = 255; bit >= 0; bit--) {
    if (started) pt_double(&acc, &acc);
    int sb = (s[bit >> 3] >> (bit & 7)) & 1;
    int kb = (k[bit >> 3] >> (bit & 7)) & 1;
    int idx = sb | (kb << 1);
    if (idx) {
      if (!started) {
        acc = table[idx - 1];
        started = 1;
      } else {
        pt_add(&acc, &acc, &table[idx - 1]);
      }
    }
  }
  if (!started) pt_identity(&acc);
  *r = acc;
}

/* ------------------------------------------------------------------ */
/* ristretto255 decode / encode (mirror crypto/sr25519.py)             */

static int ristretto_decode(pt *out, const uint8_t data[32]) {
  fe s;
  fe_from_bytes(&s, data);
  /* reject non-canonical or negative s (via canonical re-encode compare) */
  {
    uint8_t canon[32];
    fe_to_bytes(canon, &s);
    if (memcmp(canon, data, 32) != 0) return 0;
    if (canon[0] & 1) return 0;
  }
  fe ss, u1, u2, u2s, v, one, d, t1, invsqrt, den_x, den_y, x, y, t;
  fe_one(&one);
  fe_sqr(&ss, &s);
  fe_sub(&u1, &one, &ss);
  fe_add(&u2, &one, &ss);
  fe_sqr(&u2s, &u2);
  fe_from_limbs(&d, FE_D);
  fe_sqr(&t1, &u1);
  fe_mul(&t1, &t1, &d);
  fe_neg(&t1, &t1);
  fe_sub(&v, &t1, &u2s); /* a*d*u1^2 - u2^2, a = -1 */
  fe vu;
  fe_mul(&vu, &v, &u2s);
  int was_square = fe_sqrt_ratio_m1(&invsqrt, &one, &vu);
  fe_mul(&den_x, &invsqrt, &u2);
  fe_mul(&den_y, &invsqrt, &den_x);
  fe_mul(&den_y, &den_y, &v);
  fe_add(&x, &s, &s);
  fe_mul(&x, &x, &den_x);
  fe_abs(&x);
  fe_mul(&y, &u1, &den_y);
  fe_mul(&t, &x, &y);
  if (!was_square || fe_is_negative(&t)) return 0;
  {
    uint8_t yb[32];
    fe_to_bytes(yb, &y);
    int zero = 1;
    for (int i = 0; i < 32; i++) zero &= yb[i] == 0;
    if (zero) return 0;
  }
  fe_copy(&out->x, &x);
  fe_copy(&out->y, &y);
  fe_one(&out->z);
  fe_copy(&out->t, &t);
  return 1;
}

static void ristretto_encode(uint8_t out[32], const pt *p) {
  fe u1, u2, t1, t2, invsqrt, den1, den2, z_inv, one, ix, iy, den_inv, x, y, s;
  fe_copy(&x, &p->x);
  fe_copy(&y, &p->y);
  fe_add(&t1, &p->z, &y);
  fe_sub(&t2, &p->z, &y);
  fe_mul(&u1, &t1, &t2);
  fe_mul(&u2, &x, &y);
  fe_one(&one);
  fe_sqr(&t1, &u2);
  fe_mul(&t1, &t1, &u1);
  fe_sqrt_ratio_m1(&invsqrt, &one, &t1);
  fe_mul(&den1, &invsqrt, &u1);
  fe_mul(&den2, &invsqrt, &u2);
  fe_mul(&z_inv, &den1, &den2);
  fe_mul(&z_inv, &z_inv, &p->t);
  fe_mul(&t1, &p->t, &z_inv);
  if (fe_is_negative(&t1)) {
    fe sqrt_m1, iad;
    fe_from_limbs(&sqrt_m1, FE_SQRT_M1);
    fe_mul(&ix, &x, &sqrt_m1);
    fe_mul(&iy, &y, &sqrt_m1);
    fe_copy(&x, &iy);
    fe_copy(&y, &ix);
    fe_from_limbs(&iad, FE_INVSQRT_A_MINUS_D);
    fe_mul(&den_inv, &den1, &iad);
  } else {
    fe_copy(&den_inv, &den2);
  }
  fe_mul(&t1, &x, &z_inv);
  if (fe_is_negative(&t1)) fe_neg(&y, &y);
  fe_sub(&t1, &p->z, &y);
  fe_mul(&s, &den_inv, &t1);
  fe_abs(&s);
  fe_to_bytes(out, &s);
}

/* ------------------------------------------------------------------ */
/* keccak-f[1600] + STROBE-128 + merlin (mirror crypto/merlin.py)      */

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};
static const int KECCAK_ROT[5][5] = {{0, 36, 3, 41, 18},
                                     {1, 44, 10, 45, 2},
                                     {62, 6, 43, 15, 61},
                                     {28, 55, 25, 21, 56},
                                     {27, 20, 39, 8, 14}};

static inline uint64_t rotl64(uint64_t v, int n) {
  return n ? (v << n) | (v >> (64 - n)) : v;
}

static void keccak_f1600(uint8_t st8[200]) {
  uint64_t a[5][5];
  for (int x = 0; x < 5; x++)
    for (int y = 0; y < 5; y++) {
      uint64_t w = 0;
      const uint8_t *p = st8 + 8 * (x + 5 * y);
      for (int j = 7; j >= 0; j--) w = (w << 8) | p[j];
      a[x][y] = w;
    }
  for (int rnd = 0; rnd < 24; rnd++) {
    uint64_t c[5], d[5], b[5][5];
    for (int x = 0; x < 5; x++)
      c[x] = a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) a[x][y] ^= d[x];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y][(2 * x + 3 * y) % 5] = rotl64(a[x][y], KECCAK_ROT[x][y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        a[x][y] = b[x][y] ^ (~b[(x + 1) % 5][y] & b[(x + 2) % 5][y]);
    a[0][0] ^= KECCAK_RC[rnd];
  }
  for (int x = 0; x < 5; x++)
    for (int y = 0; y < 5; y++) {
      uint8_t *p = st8 + 8 * (x + 5 * y);
      uint64_t w = a[x][y];
      for (int j = 0; j < 8; j++) p[j] = (uint8_t)(w >> (8 * j));
    }
}

#define STROBE_R 166
#define FLAG_I 1
#define FLAG_A 2
#define FLAG_C 4
#define FLAG_M 16
#define FLAG_K 32

typedef struct {
  uint8_t st[200];
  int pos, pos_begin;
} strobe;

static void strobe_run_f(strobe *s) {
  s->st[s->pos] ^= (uint8_t)s->pos_begin;
  s->st[s->pos + 1] ^= 0x04;
  s->st[STROBE_R + 1] ^= 0x80;
  keccak_f1600(s->st);
  s->pos = 0;
  s->pos_begin = 0;
}

static void strobe_absorb(strobe *s, const uint8_t *data, size_t n) {
  for (size_t i = 0; i < n; i++) {
    s->st[s->pos] ^= data[i];
    if (++s->pos == STROBE_R) strobe_run_f(s);
  }
}

static void strobe_squeeze(strobe *s, uint8_t *out, size_t n) {
  for (size_t i = 0; i < n; i++) {
    out[i] = s->st[s->pos];
    s->st[s->pos] = 0;
    if (++s->pos == STROBE_R) strobe_run_f(s);
  }
}

static void strobe_begin_op(strobe *s, uint8_t flags) {
  uint8_t hdr[2] = {(uint8_t)s->pos_begin, flags};
  s->pos_begin = s->pos + 1;
  strobe_absorb(s, hdr, 2);
  if ((flags & (FLAG_C | FLAG_K)) && s->pos != 0) strobe_run_f(s);
}

static void strobe_meta_ad(strobe *s, const uint8_t *d, size_t n, int more) {
  if (!more) strobe_begin_op(s, FLAG_M | FLAG_A);
  strobe_absorb(s, d, n);
}

static void strobe_ad(strobe *s, const uint8_t *d, size_t n) {
  strobe_begin_op(s, FLAG_A);
  strobe_absorb(s, d, n);
}

static void strobe_prf(strobe *s, uint8_t *out, size_t n) {
  strobe_begin_op(s, FLAG_I | FLAG_A | FLAG_C);
  strobe_squeeze(s, out, n);
}

static void strobe_init(strobe *s, const uint8_t *label, size_t n) {
  memset(s->st, 0, 200);
  const uint8_t hdr[6] = {1, STROBE_R + 2, 1, 0, 1, 96};
  memcpy(s->st, hdr, 6);
  memcpy(s->st + 6, "STROBEv1.0.2", 12);
  keccak_f1600(s->st);
  s->pos = 0;
  s->pos_begin = 0;
  strobe_meta_ad(s, label, n, 0);
}

/* merlin transcript append_message / challenge_bytes */
static void merlin_append(strobe *s, const char *label, const uint8_t *msg,
                          size_t n) {
  uint8_t len4[4] = {(uint8_t)n, (uint8_t)(n >> 8), (uint8_t)(n >> 16),
                     (uint8_t)(n >> 24)};
  strobe_meta_ad(s, (const uint8_t *)label, strlen(label), 0);
  strobe_meta_ad(s, len4, 4, 1);
  strobe_ad(s, msg, n);
}

static void merlin_challenge(strobe *s, const char *label, uint8_t *out,
                             size_t n) {
  uint8_t len4[4] = {(uint8_t)n, (uint8_t)(n >> 8), (uint8_t)(n >> 16),
                     (uint8_t)(n >> 24)};
  strobe_meta_ad(s, (const uint8_t *)label, strlen(label), 0);
  strobe_meta_ad(s, len4, 4, 1);
  strobe_prf(s, out, n);
}

/* ------------------------------------------------------------------ */
/* schnorrkel verification                                             */

/* 1 if ok, 0 otherwise (mirrors crypto/sr25519.sr25519_verify) */
int tm_sr25519_verify_one(const uint8_t pk[32], const uint8_t *msg,
                          int64_t msg_len, const uint8_t sig[64]) {
  if (!(sig[63] & 0x80)) return 0; /* schnorrkel marker bit */
  uint8_t s_bytes[32];
  memcpy(s_bytes, sig + 32, 32);
  s_bytes[31] &= 0x7F;
  /* s < L (little-endian compare) */
  for (int i = 31; i >= 0; i--) {
    if (s_bytes[i] != SC_L_BYTES[i]) {
      if (s_bytes[i] > SC_L_BYTES[i]) return 0;
      break;
    }
    if (i == 0) return 0; /* s == L */
  }
  pt A, R;
  if (!ristretto_decode(&A, pk)) return 0;
  if (!ristretto_decode(&R, sig)) return 0;
  /* transcript: SigningContext("substrate") -> Schnorr-sig protocol */
  strobe t;
  strobe_init(&t, (const uint8_t *)"Merlin v1.0", 11);
  merlin_append(&t, "dom-sep", (const uint8_t *)"SigningContext", 14);
  merlin_append(&t, "", (const uint8_t *)"substrate", 9);
  merlin_append(&t, "sign-bytes", msg, (size_t)msg_len);
  merlin_append(&t, "proto-name", (const uint8_t *)"Schnorr-sig", 11);
  merlin_append(&t, "sign:pk", pk, 32);
  merlin_append(&t, "sign:R", sig, 32);
  uint8_t wide[64];
  merlin_challenge(&t, "sign:c", wide, 64);
  uint64_t w8[8], k4[4];
  for (int i = 0; i < 8; i++) {
    uint64_t w = 0;
    for (int j = 7; j >= 0; j--) w = (w << 8) | wide[8 * i + j];
    w8[i] = w;
  }
  tm_mod_l_512(w8, k4);
  uint8_t k_bytes[32];
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) k_bytes[8 * i + j] = (uint8_t)(k4[i] >> (8 * j));
  /* R == s*B - k*A */
  pt negA, rhs;
  pt_neg(&negA, &A);
  pt_double_scalar_mul_base(&rhs, s_bytes, &negA, k_bytes);
  uint8_t enc[32];
  ristretto_encode(enc, &rhs);
  return memcmp(enc, sig, 32) == 0;
}

typedef struct {
  const uint8_t *pks, *msgs, *sigs;
  const int64_t *moffs;
  int64_t lo, hi;
  uint8_t *out;
} sr_job;

static void *sr_worker(void *arg) {
  sr_job *j = (sr_job *)arg;
  for (int64_t i = j->lo; i < j->hi; i++) {
    j->out[i] = (uint8_t)tm_sr25519_verify_one(
        j->pks + 32 * i, j->msgs + j->moffs[i], j->moffs[i + 1] - j->moffs[i],
        j->sigs + 64 * i);
  }
  return 0;
}

void tm_sr25519_verify_batch(const uint8_t *pks, const uint8_t *msgs,
                             const int64_t *moffs, const uint8_t *sigs,
                             int64_t n, uint8_t *out, int nthreads) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  if ((int64_t)nthreads > n) nthreads = (int)(n ? n : 1);
  sr_job jobs[16];
  pthread_t tids[16];
  int64_t per = (n + nthreads - 1) / nthreads;
  int used = 0;
  for (int t = 0; t < nthreads; t++) {
    int64_t lo = t * per, hi = lo + per > n ? n : lo + per;
    if (lo >= hi) break;
    jobs[t] = (sr_job){pks, msgs, sigs, moffs, lo, hi, out};
    used = t + 1;
  }
  for (int t = 0; t + 1 < used; t++) pthread_create(&tids[t], 0, sr_worker, &jobs[t]);
  if (used) sr_worker(&jobs[used - 1]);
  for (int t = 0; t + 1 < used; t++) pthread_join(tids[t], 0);
}
