"""Command-line interface (reference: cmd/tendermint/main.go:15-32 and
cmd/tendermint/commands/*).

Subcommands: init, start, testnet, show-node-id, show-validator,
gen-validator, unsafe-reset-all, light, version.

Run as `python -m tendermint_tpu.cli <cmd>` (module entry in cli/__main__.py).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import shutil
import signal
import sys
import time

from tendermint_tpu.config.config import Config
from tendermint_tpu.config.toml import load_config, save_config

VERSION = "0.2.0"

logger = logging.getLogger("tendermint_tpu.cli")


def default_home() -> str:
    return os.environ.get("TMTPU_HOME", os.path.expanduser("~/.tendermint_tpu"))


def _config_path(home: str) -> str:
    return os.path.join(home, "config", "config.toml")


def parse_hostport(addr: str, what: str = "address") -> tuple:
    """'tcp://host:port' / 'host:port' -> (host, port) with a usage-grade
    error. An empty host (e.g. 'tcp://:8888') defaults to 127.0.0.1."""
    bare = addr.replace("tcp://", "")
    host, sep, port_s = bare.rpartition(":")
    if not sep or not port_s.isdigit():
        raise SystemExit(f"{what} must look like tcp://host:port, got {addr!r}")
    return host or "127.0.0.1", int(port_s)


def load_home(home: str) -> Config:
    path = _config_path(home)
    cfg = load_config(path) if os.path.exists(path) else Config()
    cfg.root_dir = home
    return cfg


# ------------------------------------------------------------------ init


def init_files(home: str, chain_id: str = "", seed: bytes | None = None,
               overwrite: bool = False) -> dict:
    """Create config dir tree + keys + genesis
    (reference: cmd/tendermint/commands/init.go)."""
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    cfg = Config()
    cfg.root_dir = home
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)

    cfg_path = _config_path(home)
    if overwrite or not os.path.exists(cfg_path):
        save_config(cfg, cfg_path)

    key_file = cfg.path(cfg.base.priv_validator_key_file)
    state_file = cfg.path(cfg.base.priv_validator_state_file)
    if overwrite or not os.path.exists(key_file):
        pv = FilePV.generate(key_file, state_file, seed=seed)
    else:
        pv = FilePV.load(key_file, state_file)

    node_key = NodeKey.load_or_gen(cfg.path(cfg.base.node_key_file))

    gen_path = cfg.genesis_path()
    if overwrite or not os.path.exists(gen_path):
        gen = GenesisDoc(
            chain_id=chain_id or f"test-chain-{os.urandom(3).hex()}",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pv.get_pub_key(), 10)],
        )
        gen.validate_and_complete()
        with open(gen_path, "w") as f:
            f.write(gen.to_json())
    return {
        "home": home,
        "node_id": node_key.id,
        "validator_address": pv.get_pub_key().address().hex().upper(),
    }


# ------------------------------------------------------------------ start


def run_node(home: str) -> None:
    """reference: cmd/tendermint/commands/run_node.go:100."""
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc

    cfg = load_home(home)
    from tendermint_tpu.libs import log as tmlog

    tmlog.setup(cfg.base.log_level)
    with open(cfg.genesis_path()) as f:
        gen = GenesisDoc.from_json(f.read())
    pv = None
    if not cfg.base.priv_validator_addr:
        pv = FilePV.load(
            cfg.path(cfg.base.priv_validator_key_file),
            cfg.path(cfg.base.priv_validator_state_file),
        )
    node = Node(cfg, gen, priv_validator=pv)

    async def main():
        stop = asyncio.Event()
        loop = asyncio.get_event_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await node.start()
        print(f"node {node.node_key.id if node.node_key else ''} started; "
              f"chain {gen.chain_id}; ^C to stop", flush=True)
        await stop.wait()
        await node.stop()

    asyncio.run(main())


# ----------------------------------------------------------------- replay


def run_replay(home: str, console: bool = False) -> None:
    """Replay the WAL of the in-progress height through a fresh consensus
    state, printing the round state after every message — interactively in
    console mode (reference: consensus/replay_file.go:1 RunReplayFile +
    cmd/tendermint/commands/replay.go:1).

    Console commands: n/next [N] step, rs dump round state, q quit,
    back restart from the beginning."""
    import asyncio

    from tendermint_tpu.consensus.wal import MsgInfo, TimeoutInfo
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc

    class _NullWAL:
        """Replay must never mutate the WAL it reads (the reference's
        RunReplayFile runs with a nil WAL): every consensus step would
        otherwise append EventRoundState/EndHeight frames to the live log."""

        def write(self, *_a, **_k):
            pass

        write_sync = write
        write_end_height = write
        flush_and_sync = write
        close = write

        def search_for_end_height(self, *_a, **_k):
            return None

    def build():
        cfg = load_home(home)
        with open(cfg.genesis_path()) as f:
            gen = GenesisDoc.from_json(f.read())
        pv = None
        if not cfg.base.priv_validator_addr:
            pv = FilePV.load(
                cfg.path(cfg.base.priv_validator_key_file),
                cfg.path(cfg.base.priv_validator_state_file),
            )
        node = Node(cfg, gen, priv_validator=pv)
        cs = node.consensus
        msgs = cs.wal.search_for_end_height(cs.rs.height - 1) or []
        cs.wal.close()
        cs.wal = _NullWAL()
        return node, cs, msgs

    async def replay():
        node, cs, msgs = build()
        cs.replay_mode = True
        print(f"replaying {len(msgs)} WAL messages for height {cs.rs.height}")
        print(json.dumps(cs.rs.round_state_summary()))
        i = 0

        def step_one():
            nonlocal i
            msg = msgs[i]
            if isinstance(msg, MsgInfo):
                label = type(msg.msg).__name__
                cs._handle_msg(msg)
            elif isinstance(msg, TimeoutInfo):
                label = f"Timeout({msg.step})"
                cs._handle_timeout(msg)
            else:
                label = type(msg).__name__
            i += 1
            print(f"[{i}/{len(msgs)}] {label} -> "
                  f"H={cs.rs.height} R={cs.rs.round} S={cs.rs.step.name}")

        if not console:
            while i < len(msgs):
                step_one()
        else:
            print("console: n [count] = step, rs = round state, q = quit")
            while True:
                try:
                    line = input(f"replay [{i}/{len(msgs)}]> ").strip()
                except EOFError:
                    break
                if line in ("q", "quit"):
                    break
                if line in ("rs",):
                    print(json.dumps(cs.rs.round_state_summary(), indent=1))
                    continue
                if line.startswith(("n", "next")) or line == "":
                    parts = line.split()
                    if len(parts) > 1 and not parts[1].isdigit():
                        print("commands: n [count], rs, q")
                        continue
                    count = int(parts[1]) if len(parts) > 1 else 1
                    for _ in range(count):
                        if i >= len(msgs):
                            print("end of WAL")
                            break
                        step_one()
                    continue
                print("commands: n [count], rs, q")
        print(json.dumps(cs.rs.round_state_summary()))

    asyncio.run(replay())


# ---------------------------------------------------------------- testnet


def make_testnet(output_dir: str, n_validators: int, chain_id: str = "",
                 starting_port: int = 26656, populate_persistent_peers: bool = True) -> list:
    """N validator config dirs sharing one genesis
    (reference: cmd/tendermint/commands/testnet.go)."""
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    chain_id = chain_id or f"chain-{os.urandom(3).hex()}"
    nodes = []
    for i in range(n_validators):
        home = os.path.join(output_dir, f"node{i}")
        cfg = Config()
        cfg.root_dir = home
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pv = FilePV.generate(
            cfg.path(cfg.base.priv_validator_key_file),
            cfg.path(cfg.base.priv_validator_state_file),
        )
        node_key = NodeKey.load_or_gen(cfg.path(cfg.base.node_key_file))
        nodes.append((home, cfg, pv, node_key, starting_port + 2 * i))

    gen = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator(pv.get_pub_key(), 10, name=f"node{i}")
            for i, (_, _, pv, _, _) in enumerate(nodes)
        ],
    )
    gen.validate_and_complete()
    gen_json = gen.to_json()

    peers = ",".join(
        f"{nk.id}@127.0.0.1:{port}" for (_, _, _, nk, port) in nodes
    )
    out = []
    for i, (home, cfg, pv, nk, port) in enumerate(nodes):
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{port}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{port + 1}"
        if populate_persistent_peers:
            cfg.p2p.persistent_peers = ",".join(
                p for p in peers.split(",") if not p.startswith(nk.id)
            )
        save_config(cfg, _config_path(home))
        with open(cfg.genesis_path(), "w") as f:
            f.write(gen_json)
        out.append({"home": home, "node_id": nk.id, "p2p": cfg.p2p.laddr, "rpc": cfg.rpc.laddr})
    return out


# --------------------------------------------------------------- localnet


def run_localnet(output_dir: str, n_validators: int, chain_id: str,
                 starting_port: int, blocks: int) -> None:
    """Generate a testnet and run every node as a subprocess until all reach
    `blocks` (the reference's networks/local docker-compose story, as plain
    processes)."""
    import subprocess
    import urllib.request

    if os.path.isdir(output_dir) and os.listdir(output_dir):
        raise SystemExit(
            f"output dir {output_dir!r} is not empty — localnet always starts "
            "from a fresh testnet (delete it or pick another --output-dir)"
        )
    make_testnet(output_dir, n_validators, chain_id, starting_port)
    homes = sorted(
        os.path.join(output_dir, d)
        for d in os.listdir(output_dir)
        if d.startswith("node")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.cli", "--home", h, "start"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        for h in homes
    ]

    def height(rpc_laddr: str) -> int:
        url = "http://" + rpc_laddr.replace("tcp://", "")
        req = urllib.request.Request(
            url,
            json.dumps({"jsonrpc": "2.0", "id": 1, "method": "status", "params": {}}).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=2) as resp:
            st = json.load(resp)
        return int(st["result"]["sync_info"]["latest_block_height"])

    try:
        rpcs = [load_home(h).rpc.laddr for h in homes]
        deadline = time.time() + 60 + 10 * blocks
        heights = [0] * len(homes)
        while time.time() < deadline:
            for i, r in enumerate(rpcs):
                try:
                    heights[i] = height(r)
                except Exception:
                    pass
            print(json.dumps({"heights": heights}), flush=True)
            if all(h >= blocks for h in heights):
                print(json.dumps({"localnet": "ok", "heights": heights}))
                return
            time.sleep(1.0)
        raise SystemExit(f"localnet did not reach height {blocks}: {heights}")
    finally:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()


# --------------------------------------------------------- signer-harness


def run_signer_harness(addr: str, chain_id: str) -> None:
    """Acceptance checks against a remote signer
    (reference: tools/tm-signer-harness — ping, pubkey, vote/proposal signing,
    double-sign refusal).

    The signer must have FRESH sign state (like the reference harness, which
    loads disposable key/state files): the checks sign at low heights and the
    double-sign probe advances the signer's watermark. NEVER point this at a
    production validator's signer."""
    from tendermint_tpu.crypto import tmhash
    from tendermint_tpu.privval.file_pv import DoubleSignError
    from tendermint_tpu.privval.remote import SignerClient
    from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from tendermint_tpu.types.proposal import Proposal
    from tendermint_tpu.types.vote import Vote

    host, port = parse_hostport(addr, "--addr")
    client = SignerClient(host, port)
    results = {}

    def vote(h, tag, t=SignedMsgType.PREVOTE):
        bh = tmhash.sum256(tag)
        return Vote(type=t, height=h, round=0,
                    block_id=BlockID(bh, PartSetHeader(1, tmhash.sum256(bh))),
                    timestamp_ns=time.time_ns(), validator_address=b"\x01" * 20,
                    validator_index=0)

    try:
        client.ping()
        results["ping"] = "ok"
        pub = client.get_pub_key()
        results["pubkey"] = pub.bytes().hex()

        try:
            signed = client.sign_vote(chain_id, vote(1, b"a"))
        except DoubleSignError:
            print(json.dumps({
                "passed": False,
                "results": {**results, "sign_vote": "signer state is not fresh "
                            "(height 1 already signed) — use a disposable signer"},
            }))
            raise SystemExit(1)
        results["sign_vote"] = (
            "ok" if pub.verify(signed.sign_bytes(chain_id), signed.signature)
            else "BAD SIGNATURE"
        )

        try:
            client.sign_vote(chain_id, vote(1, b"b"))
            results["double_sign_guard"] = "FAILED: equivocation signed"
        except DoubleSignError:
            results["double_sign_guard"] = "ok"

        bh = tmhash.sum256(b"p")
        prop = Proposal(type=SignedMsgType.PROPOSAL, height=2, round=0,
                        pol_round=-1, block_id=BlockID(bh, PartSetHeader(1, tmhash.sum256(bh))),
                        timestamp_ns=time.time_ns())
        sp = client.sign_proposal(chain_id, prop)
        results["sign_proposal"] = "ok" if pub.verify(sp.sign_bytes(chain_id), sp.signature) else "BAD SIGNATURE"
    except (ConnectionError, OSError) as e:
        print(json.dumps({"passed": False, "results": {**results, "error": str(e)}}))
        raise SystemExit(1)
    finally:
        client.close()
    ok = all(v == "ok" or k == "pubkey" for k, v in results.items())
    print(json.dumps({"passed": ok, "results": results}))
    if not ok:
        raise SystemExit(1)


# ------------------------------------------------------------------ debug


def debug_dump(home: str, rpc_url: str, output: str) -> None:
    """Capture node state + config + WAL into a zip
    (reference: cmd/tendermint/commands/debug/dump.go:117-125)."""
    import zipfile

    cfg = load_home(home)
    with zipfile.ZipFile(output, "w", zipfile.ZIP_DEFLATED) as z:
        if rpc_url:
            from tendermint_tpu.rpc.client import HTTPClient

            async def fetch():
                client = HTTPClient(rpc_url)
                try:
                    for method in (
                        "status",
                        "net_info",
                        "dump_consensus_state",
                        # stack/heap profiles (pprof analogs; need rpc.unsafe)
                        "unsafe_dump_stacks",
                        "unsafe_dump_heap",
                    ):
                        try:
                            res = await client.call(method)
                            z.writestr(f"{method}.json", json.dumps(res, indent=2))
                        except Exception as e:
                            z.writestr(f"{method}.error.txt", str(e))
                finally:
                    await client.close()

            asyncio.run(fetch())
        for rel in ("config/config.toml", "config/genesis.json"):
            path = cfg.path(rel)
            if os.path.exists(path):
                z.write(path, rel)
        wal_dir = cfg.path(cfg.consensus.wal_path)
        if os.path.isdir(wal_dir):
            for fn in sorted(os.listdir(wal_dir)):
                z.write(os.path.join(wal_dir, fn), f"wal/{fn}")
        elif os.path.isfile(wal_dir):
            z.write(wal_dir, "wal/" + os.path.basename(wal_dir))


# ------------------------------------------------------------------ light


def run_light(chain_id: str, primary: str, witnesses: list, trust_height: int,
              trust_hash: str, home: str, height: int | None,
              laddr: str = "") -> None:
    """Verify a header via the light client against live RPC endpoints; with
    --laddr, keep running as a verifying RPC proxy
    (reference: cmd/tendermint/commands/lite.go `tendermint light` +
    light/proxy/proxy.go)."""
    from tendermint_tpu.libs.kvdb import SQLiteDB
    from tendermint_tpu.light import Client, HTTPProvider, LightStore, TrustOptions
    from tendermint_tpu.rpc.client import HTTPClient
    from tendermint_tpu.types.basic import NANOS

    async def main():
        clients = [HTTPClient(primary)] + [HTTPClient(w) for w in witnesses]
        providers = [HTTPProvider(chain_id, c) for c in clients]
        os.makedirs(home, exist_ok=True)
        store = LightStore(SQLiteDB(os.path.join(home, "light.db")))
        lc = Client(
            chain_id,
            TrustOptions(7 * 24 * 3600 * NANOS, trust_height, bytes.fromhex(trust_hash)),
            providers[0],
            providers[1:],
            store,
        )
        try:
            if laddr:
                from tendermint_tpu.light.proxy import LightProxy

                host, port = parse_hostport(
                    laddr if ":" in laddr.replace("tcp://", "") else laddr + ":0",
                    "--laddr",
                )
                proxy = LightProxy(lc, clients[0], host, port)
                await proxy.start()
                print(json.dumps({"proxy": proxy.addr}), flush=True)
                stop = asyncio.Event()
                loop = asyncio.get_event_loop()
                for sig in (signal.SIGINT, signal.SIGTERM):
                    try:
                        loop.add_signal_handler(sig, stop.set)
                    except NotImplementedError:
                        pass
                await stop.wait()
                await proxy.stop()
                return
            await lc.initialize()
            lb = (
                await lc.verify_light_block_at_height(height)
                if height
                else await lc.update()
            )
            if lb is None:
                lb = store.latest_light_block()
            print(json.dumps({
                "height": lb.height,
                "hash": lb.hash().hex().upper(),
                "app_hash": lb.header.app_hash.hex().upper(),
                "trusted_heights": store.heights()[-10:],
            }))
        finally:
            for c in clients:
                await c.close()

    asyncio.run(main())


# ------------------------------------------------------------------- main


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tendermint-tpu", description=__doc__)
    p.add_argument("--home", default=default_home(), help="node home directory")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("init", help="create config dir, keys, and genesis")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--overwrite", action="store_true")

    sub.add_parser("start", help="run the node")

    sp = sub.add_parser("testnet", help="generate N validator config dirs")
    sp.add_argument("--v", type=int, default=4, help="number of validators")
    sp.add_argument("--output-dir", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", type=int, default=26656)

    sub.add_parser("show-node-id", help="print the p2p node id")
    sub.add_parser("show-validator", help="print the validator pubkey")
    sub.add_parser("gen-validator", help="print a fresh validator key (JSON)")
    sub.add_parser("unsafe-reset-all", help="wipe data dir, keep config + keys")
    sub.add_parser("version", help="print version")

    sp = sub.add_parser("localnet", help="generate + run an N-validator localnet as subprocesses")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--output-dir", default="./localnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", type=int, default=26656)
    sp.add_argument("--blocks", type=int, default=5, help="run until every node reaches this height")

    sp = sub.add_parser("signer-harness", help="acceptance checks against a remote signer")
    sp.add_argument("--addr", required=True, help="signer address, e.g. tcp://127.0.0.1:26659")
    sp.add_argument("--chain-id", default="harness-chain")

    sub.add_parser("replay", help="replay the last height's WAL through consensus")
    sub.add_parser("replay-console", help="interactive WAL replay (n/rs/q)")

    sp = sub.add_parser(
        "wal-inspect",
        help="post-mortem: rebuild the consensus timeline (heights/rounds/steps, "
             "vote arrival, EndHeight gaps) from a WAL, offline and read-only",
    )
    sp.add_argument(
        "--wal", default="",
        help="WAL head file; defaults to the home's consensus.wal_path",
    )
    sp.add_argument("--limit", type=int, default=None,
                    help="only the most recent N heights")

    sp = sub.add_parser(
        "probe-upnp", help="probe the local NAT for UPnP port-mapping support"
    )
    sp.add_argument("--port", type=int, default=26656)
    sp.add_argument("--timeout", type=float, default=3.0)

    sp = sub.add_parser(
        "debug", help="capture a debug dump (node state over RPC + config + WAL) into a zip"
    )
    sp.add_argument("--rpc", default="", help="RPC URL of the running node (optional)")
    sp.add_argument("--output", default="debug_dump.zip")

    sp = sub.add_parser(
        "load-test",
        help="tx load generator: spam a running net over RPC, report send + commit "
             "throughput plus chain-side block-interval/step-duration summaries "
             "scraped from /metrics (chain_metrics; null if not served)",
    )
    sp.add_argument(
        "--endpoints", default="http://127.0.0.1:26657",
        help="comma-separated RPC base URLs",
    )
    sp.add_argument("--rate", type=float, default=200.0, help="aggregate target tx/s")
    sp.add_argument("--duration", type=float, default=10.0, help="send window seconds")
    sp.add_argument("--connections", type=int, default=2, help="workers per endpoint")
    sp.add_argument("--tx-size", type=int, default=64, help="tx bytes (unique prefix + pad)")
    sp.add_argument("--method", default="async", choices=("async", "sync"))
    sp.add_argument("--settle", type=float, default=2.0,
                    help="post-send wait before counting committed txs")
    sp.add_argument("--signed", action="store_true",
                    help="wrap every tx in a signed-tx envelope (one key "
                         "per worker) — exercises device-batched CheckTx "
                         "admission against a signed_kvstore app")

    sp = sub.add_parser(
        "abci", help="abci-cli console: drive an ABCI app (conformance tool)"
    )
    sp.add_argument(
        "--app", default="kvstore",
        help="kvstore | persistent_kvstore | counter | counter:noserial | tcp://host:port",
    )
    sp.add_argument(
        "batch_file", nargs="?", default=None,
        help="command script (one command per line); stdin console if omitted",
    )

    sp = sub.add_parser("light", help="light client: verify headers over RPC")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True, help="primary RPC URL")
    sp.add_argument("--witness", action="append", default=[], help="witness RPC URL")
    sp.add_argument("--trust-height", type=int, required=True)
    sp.add_argument("--trust-hash", required=True)
    sp.add_argument("--height", type=int, default=None)
    sp.add_argument("--laddr", default="", help="run a verifying RPC proxy on this address")

    args = p.parse_args(argv)

    if args.cmd == "init":
        info = init_files(args.home, args.chain_id, overwrite=args.overwrite)
        print(json.dumps(info))
    elif args.cmd == "start":
        run_node(args.home)
    elif args.cmd == "testnet":
        out = make_testnet(args.output_dir, args.v, args.chain_id, args.starting_port)
        print(json.dumps(out))
    elif args.cmd == "show-node-id":
        from tendermint_tpu.p2p.key import NodeKey

        cfg = load_home(args.home)
        print(NodeKey.load_or_gen(cfg.path(cfg.base.node_key_file)).id)
    elif args.cmd == "show-validator":
        from tendermint_tpu.privval.file_pv import FilePV

        cfg = load_home(args.home)
        pv = FilePV.load(
            cfg.path(cfg.base.priv_validator_key_file),
            cfg.path(cfg.base.priv_validator_state_file),
        )
        from tendermint_tpu.libs import amino_json

        print(amino_json.marshal(pv.get_pub_key()))
    elif args.cmd == "gen-validator":
        from tendermint_tpu.crypto.keys import gen_ed25519

        priv = gen_ed25519()
        pub = priv.pub_key()
        print(json.dumps({
            "address": pub.address().hex().upper(),
            "pub_key": pub.bytes().hex(),
            "priv_key": priv.bytes().hex(),
        }))
    elif args.cmd == "unsafe-reset-all":
        cfg = load_home(args.home)
        data_dir = cfg.path("data")
        if os.path.isdir(data_dir):
            shutil.rmtree(data_dir)
        os.makedirs(data_dir, exist_ok=True)
        # reset the privval sign state but KEEP the key
        state_file = cfg.path(cfg.base.priv_validator_state_file)
        if os.path.exists(state_file):
            os.unlink(state_file)
        print(json.dumps({"reset": args.home}))
    elif args.cmd == "localnet":
        run_localnet(args.output_dir, args.v, args.chain_id, args.starting_port, args.blocks)
    elif args.cmd == "signer-harness":
        run_signer_harness(args.addr, args.chain_id)
    elif args.cmd == "replay":
        run_replay(args.home, console=False)
    elif args.cmd == "replay-console":
        run_replay(args.home, console=True)
    elif args.cmd == "wal-inspect":
        from tendermint_tpu.tools.wal_inspect import inspect_wal

        wal_path = args.wal
        if not wal_path:
            cfg = load_home(args.home)
            wal_path = (
                cfg.consensus.wal_path
                if os.path.isabs(cfg.consensus.wal_path)
                else cfg.path(cfg.consensus.wal_path)
            )
        if not os.path.exists(wal_path):
            raise SystemExit(f"WAL not found: {wal_path!r} (pass --wal)")
        print(json.dumps(inspect_wal(wal_path, limit=args.limit), indent=1))
    elif args.cmd == "probe-upnp":
        # (reference: cmd/tendermint/commands/probe_upnp.go)
        from tendermint_tpu.p2p.upnp import UPNPError, probe

        try:
            caps = asyncio.run(
                probe(int_port=args.port, ext_port=args.port, timeout=args.timeout)
            )
            print(json.dumps(caps))
        except UPNPError as e:
            print(json.dumps({"upnp": False, "error": str(e)}))
    elif args.cmd == "debug":
        debug_dump(args.home, args.rpc, args.output)
        print(json.dumps({"dump": args.output}))
    elif args.cmd == "load-test":
        # in-tree equivalent of the external tm-load-test harness the
        # reference README delegates to (reference: README.md:153-155)
        from tendermint_tpu.tools.loadtest import run_load

        report = asyncio.run(
            run_load(
                [e.strip() for e in args.endpoints.split(",") if e.strip()],
                rate=args.rate,
                duration=args.duration,
                connections=args.connections,
                tx_size=args.tx_size,
                method=args.method,
                settle=args.settle,
                signed=args.signed,
            )
        )
        print(json.dumps(report))
    elif args.cmd == "abci":
        from tendermint_tpu.cli.abci_console import main as abci_main

        abci_main(args.app, args.batch_file)
    elif args.cmd == "version":
        print(VERSION)
    elif args.cmd == "light":
        run_light(
            args.chain_id, args.primary, args.witness,
            args.trust_height, args.trust_hash, args.home, args.height,
            laddr=args.laddr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
