"""ABCI wire codec for the socket transport (reference:
proto/tendermint/abci/types.proto, abci/client/socket_client.go framing).

Requests/responses are protowire messages inside a Request/Response oneof
envelope, length-delimited on the socket (reference: libs/protoio). Field
numbers follow the v0.34 proto. Nested rich objects (block Header,
ConsensusParams) are carried as their own encoded submessages; the decode
side surfaces them as raw bytes (apps that need them decode with the types
layer) — the in-process local client keeps the live objects and never touches
this codec."""

from __future__ import annotations

from dataclasses import fields as dc_fields
from typing import Callable, Dict, List, Tuple

from tendermint_tpu.abci import types as a
from tendermint_tpu.libs import protowire as pw

# ---------------------------------------------------------------------------
# leaf encoders
# ---------------------------------------------------------------------------


def _enc_event(ev: a.Event) -> bytes:
    w = pw.Writer()
    w.string_field(1, ev.type)
    for key, value, index in ev.attributes:
        aw = pw.Writer()
        aw.bytes_field(1, key)
        aw.bytes_field(2, value)
        aw.varint_field(3, 1 if index else 0)
        w.message_field(2, aw.bytes(), always=True)
    return w.bytes()


def _dec_event(data: bytes) -> a.Event:
    ev = a.Event()
    for f, _, v in pw.Reader(data):
        if f == 1:
            ev.type = v.decode()
        elif f == 2:
            key = value = b""
            index = False
            for ff, _, vv in pw.Reader(v):
                if ff == 1:
                    key = vv
                elif ff == 2:
                    value = vv
                elif ff == 3:
                    index = bool(vv)
            ev.attributes.append((key, value, index))
    return ev


def _enc_valupdate(u: a.ValidatorUpdate) -> bytes:
    w = pw.Writer()
    pk = pw.Writer()
    # PublicKey oneof: 1=ed25519 bytes, 2=sr25519 bytes
    pk.bytes_field(1 if u.pub_key_type == "ed25519" else 2, u.pub_key_bytes, emit_empty=True)
    w.message_field(1, pk.bytes(), always=True)
    w.varint_field(2, u.power)
    return w.bytes()


def _dec_valupdate(data: bytes) -> a.ValidatorUpdate:
    ktype, kbytes, power = "ed25519", b"", 0
    for f, _, v in pw.Reader(data):
        if f == 1:
            for ff, _, vv in pw.Reader(v):
                if ff == 1:
                    ktype, kbytes = "ed25519", vv
                elif ff == 2:
                    ktype, kbytes = "sr25519", vv
        elif f == 2:
            power = pw.int64_from_varint(v)
    return a.ValidatorUpdate(ktype, kbytes, power)


def _enc_lci(l: a.LastCommitInfo) -> bytes:
    w = pw.Writer()
    w.varint_field(1, l.round)
    for addr, power, signed in l.votes:
        vw = pw.Writer()
        valw = pw.Writer()
        valw.bytes_field(1, addr)
        valw.varint_field(3, power)
        vw.message_field(1, valw.bytes(), always=True)
        vw.varint_field(2, 1 if signed else 0)
        w.message_field(2, vw.bytes(), always=True)
    return w.bytes()


def _dec_lci(data: bytes) -> a.LastCommitInfo:
    out = a.LastCommitInfo()
    for f, _, v in pw.Reader(data):
        if f == 1:
            out.round = pw.int64_from_varint(v)
        elif f == 2:
            addr, power, signed = b"", 0, False
            for ff, _, vv in pw.Reader(v):
                if ff == 1:
                    for g, _, gv in pw.Reader(vv):
                        if g == 1:
                            addr = gv
                        elif g == 3:
                            power = pw.int64_from_varint(gv)
                elif ff == 2:
                    signed = bool(vv)
            out.votes.append((addr, power, signed))
    return out


def _enc_evidence(e: a.EvidenceABCI) -> bytes:
    w = pw.Writer()
    w.varint_field(1, e.type)
    vw = pw.Writer()
    vw.bytes_field(1, e.validator_address)
    vw.varint_field(3, e.validator_power)
    w.message_field(2, vw.bytes(), always=True)
    w.varint_field(3, e.height)
    w.varint_field(4, e.time_ns)
    w.varint_field(5, e.total_voting_power)
    return w.bytes()


def _dec_evidence(data: bytes) -> a.EvidenceABCI:
    out = a.EvidenceABCI()
    for f, _, v in pw.Reader(data):
        if f == 1:
            out.type = v
        elif f == 2:
            for ff, _, vv in pw.Reader(v):
                if ff == 1:
                    out.validator_address = vv
                elif ff == 3:
                    out.validator_power = pw.int64_from_varint(vv)
        elif f == 3:
            out.height = pw.int64_from_varint(v)
        elif f == 4:
            out.time_ns = pw.int64_from_varint(v)
        elif f == 5:
            out.total_voting_power = pw.int64_from_varint(v)
    return out


def _enc_snapshot(s: a.Snapshot) -> bytes:
    w = pw.Writer()
    w.varint_field(1, s.height)
    w.varint_field(2, s.format)
    w.varint_field(3, s.chunks)
    w.bytes_field(4, s.hash)
    w.bytes_field(5, s.metadata)
    return w.bytes()


def _dec_snapshot(data: bytes) -> a.Snapshot:
    s = a.Snapshot()
    for f, _, v in pw.Reader(data):
        if f == 1:
            s.height = pw.int64_from_varint(v)
        elif f == 2:
            s.format = v
        elif f == 3:
            s.chunks = v
        elif f == 4:
            s.hash = v
        elif f == 5:
            s.metadata = v
    return s


def _maybe_encode(obj) -> bytes:
    if obj is None:
        return b""
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj)
    enc = getattr(obj, "encode", None)
    return enc() if enc else b""


# ---------------------------------------------------------------------------
# message field specs: (field_no, attr, kind)
# kinds: i=varint int, b=bool, y=bytes, s=str, E=[Event], V=[ValidatorUpdate],
#        L=LastCommitInfo, X=[EvidenceABCI], S=Snapshot, SS=[Snapshot],
#        O=opaque submessage (encode() out, raw bytes in), I=[int], T=[str]
# ---------------------------------------------------------------------------

SPECS: Dict[type, List[Tuple[int, str, str]]] = {
    a.RequestInfo: [(1, "version", "s"), (2, "block_version", "i"), (3, "p2p_version", "i")],
    a.ResponseInfo: [(1, "data", "s"), (2, "version", "s"), (3, "app_version", "i"),
                     (4, "last_block_height", "i"), (5, "last_block_app_hash", "y")],
    a.RequestSetOption: [(1, "key", "s"), (2, "value", "s")],
    a.ResponseSetOption: [(1, "code", "i"), (3, "log", "s"), (4, "info", "s")],
    a.RequestInitChain: [(1, "time_ns", "i"), (2, "chain_id", "s"), (3, "consensus_params", "O"),
                         (4, "validators", "V"), (5, "app_state_bytes", "y"), (6, "initial_height", "i")],
    a.ResponseInitChain: [(1, "consensus_params", "O"), (2, "validators", "V"), (3, "app_hash", "y")],
    a.RequestQuery: [(1, "data", "y"), (2, "path", "s"), (3, "height", "i"), (4, "prove", "b")],
    a.ResponseQuery: [(1, "code", "i"), (3, "log", "s"), (4, "info", "s"), (5, "index", "i"),
                      (6, "key", "y"), (7, "value", "y"), (8, "proof_ops", "O"),
                      (9, "height", "i"), (10, "codespace", "s")],
    a.RequestBeginBlock: [(1, "hash", "y"), (2, "header", "O"), (3, "last_commit_info", "L"),
                          (4, "byzantine_validators", "X")],
    a.ResponseBeginBlock: [(1, "events", "E")],
    a.RequestCheckTx: [(1, "tx", "y"), (2, "type", "i"),
                       # node-side signature-precheck verdict (ABCI split,
                       # types.SIG_PRECHECK_*); proto3 zero-default = NONE,
                       # so peers without the field interop unchanged
                       (3, "sig_precheck", "i")],
    a.ResponseCheckTx: [(1, "code", "i"), (2, "data", "y"), (3, "log", "s"), (4, "info", "s"),
                        (5, "gas_wanted", "i"), (6, "gas_used", "i"), (7, "events", "E"),
                        (8, "codespace", "s")],
    a.RequestDeliverTx: [(1, "tx", "y")],
    a.ResponseDeliverTx: [(1, "code", "i"), (2, "data", "y"), (3, "log", "s"), (4, "info", "s"),
                          (5, "gas_wanted", "i"), (6, "gas_used", "i"), (7, "events", "E"),
                          (8, "codespace", "s")],
    a.RequestEndBlock: [(1, "height", "i")],
    a.ResponseEndBlock: [(1, "validator_updates", "V"), (2, "consensus_param_updates", "O"),
                         (3, "events", "E")],
    a.ResponseCommit: [(2, "data", "y"), (3, "retain_height", "i")],
    a.ResponseListSnapshots: [(1, "snapshots", "SS")],
    a.RequestOfferSnapshot: [(1, "snapshot", "S"), (2, "app_hash", "y")],
    a.ResponseOfferSnapshot: [(1, "result", "i")],
    a.RequestLoadSnapshotChunk: [(1, "height", "i"), (2, "format", "i"), (3, "chunk", "i")],
    a.ResponseLoadSnapshotChunk: [(1, "chunk", "y")],
    a.RequestApplySnapshotChunk: [(1, "index", "i"), (2, "chunk", "y"), (3, "sender", "s")],
    a.ResponseApplySnapshotChunk: [(1, "result", "i"), (2, "refetch_chunks", "I"),
                                   (3, "reject_senders", "T")],
}


def encode_msg(msg) -> bytes:
    w = pw.Writer()
    for num, attr, kind in SPECS[type(msg)]:
        val = getattr(msg, attr)
        if kind == "i":
            w.varint_field(num, int(val))
        elif kind == "b":
            w.varint_field(num, 1 if val else 0)
        elif kind == "y":
            w.bytes_field(num, bytes(val))
        elif kind == "s":
            w.string_field(num, val)
        elif kind == "E":
            for ev in val:
                w.message_field(num, _enc_event(ev), always=True)
        elif kind == "V":
            for u in val:
                w.message_field(num, _enc_valupdate(u), always=True)
        elif kind == "L":
            w.message_field(num, _enc_lci(val), always=True)
        elif kind == "X":
            for e in val:
                w.message_field(num, _enc_evidence(e), always=True)
        elif kind == "S":
            if val is not None:
                w.message_field(num, _enc_snapshot(val), always=True)
        elif kind == "SS":
            for s in val:
                w.message_field(num, _enc_snapshot(s), always=True)
        elif kind == "O":
            raw = _maybe_encode(val)
            if raw:
                w.message_field(num, raw, always=True)
        elif kind == "I":
            for x in val:
                w.varint_field(num, x, emit_zero=True)
        elif kind == "T":
            for s in val:
                w.string_field(num, s, emit_empty=True)
    return w.bytes()


def decode_msg(cls, data: bytes):
    spec = {num: (attr, kind) for num, attr, kind in SPECS[cls]}
    msg = cls()
    for f, _, v in pw.Reader(data):
        if f not in spec:
            continue
        attr, kind = spec[f]
        if kind == "i":
            setattr(msg, attr, pw.int64_from_varint(v))
        elif kind == "b":
            setattr(msg, attr, bool(v))
        elif kind == "y":
            setattr(msg, attr, v)
        elif kind == "s":
            setattr(msg, attr, v.decode())
        elif kind == "E":
            getattr(msg, attr).append(_dec_event(v))
        elif kind == "V":
            getattr(msg, attr).append(_dec_valupdate(v))
        elif kind == "L":
            setattr(msg, attr, _dec_lci(v))
        elif kind == "X":
            getattr(msg, attr).append(_dec_evidence(v))
        elif kind == "S":
            setattr(msg, attr, _dec_snapshot(v))
        elif kind == "SS":
            getattr(msg, attr).append(_dec_snapshot(v))
        elif kind == "O":
            setattr(msg, attr, v)  # raw bytes; types layer decodes if needed
        elif kind == "I":
            getattr(msg, attr).append(pw.int64_from_varint(v))
        elif kind == "T":
            getattr(msg, attr).append(v.decode())
    return msg


# ---------------------------------------------------------------------------
# Request / Response envelopes (oneof field numbers from the v0.34 proto)
# ---------------------------------------------------------------------------

REQUEST_FIELDS = {
    "echo": 1, "flush": 2, "info": 3, "set_option": 4, "init_chain": 5,
    "query": 6, "begin_block": 7, "check_tx": 8, "deliver_tx": 9,
    "end_block": 10, "commit": 11, "list_snapshots": 12, "offer_snapshot": 13,
    "load_snapshot_chunk": 14, "apply_snapshot_chunk": 15,
}
REQUEST_TYPES = {
    "info": a.RequestInfo, "set_option": a.RequestSetOption,
    "init_chain": a.RequestInitChain, "query": a.RequestQuery,
    "begin_block": a.RequestBeginBlock, "check_tx": a.RequestCheckTx,
    "deliver_tx": a.RequestDeliverTx, "end_block": a.RequestEndBlock,
    "offer_snapshot": a.RequestOfferSnapshot,
    "load_snapshot_chunk": a.RequestLoadSnapshotChunk,
    "apply_snapshot_chunk": a.RequestApplySnapshotChunk,
}
RESPONSE_FIELDS = {
    "exception": 1, "echo": 2, "flush": 3, "info": 4, "set_option": 5,
    "init_chain": 6, "query": 7, "begin_block": 8, "check_tx": 9,
    "deliver_tx": 10, "end_block": 11, "commit": 12, "list_snapshots": 13,
    "offer_snapshot": 14, "load_snapshot_chunk": 15, "apply_snapshot_chunk": 16,
}
RESPONSE_TYPES = {
    "info": a.ResponseInfo, "set_option": a.ResponseSetOption,
    "init_chain": a.ResponseInitChain, "query": a.ResponseQuery,
    "begin_block": a.ResponseBeginBlock, "check_tx": a.ResponseCheckTx,
    "deliver_tx": a.ResponseDeliverTx, "end_block": a.ResponseEndBlock,
    "commit": a.ResponseCommit, "list_snapshots": a.ResponseListSnapshots,
    "offer_snapshot": a.ResponseOfferSnapshot,
    "load_snapshot_chunk": a.ResponseLoadSnapshotChunk,
    "apply_snapshot_chunk": a.ResponseApplySnapshotChunk,
}
_REQ_FIELD_TO_NAME = {v: k for k, v in REQUEST_FIELDS.items()}
_RESP_FIELD_TO_NAME = {v: k for k, v in RESPONSE_FIELDS.items()}


def encode_request(method: str, msg=None) -> bytes:
    w = pw.Writer()
    body = b"" if method in ("flush", "echo") and msg is None else (
        encode_msg(msg) if msg is not None else b""
    )
    w.message_field(REQUEST_FIELDS[method], body, always=True)
    return w.bytes()


def decode_request(data: bytes):
    """-> (method, msg_or_None)"""
    for f, _, v in pw.Reader(data):
        name = _REQ_FIELD_TO_NAME.get(f)
        if name is None:
            continue
        cls = REQUEST_TYPES.get(name)
        return name, (decode_msg(cls, v) if cls else None)
    raise ValueError("empty ABCI request")


def encode_response(method: str, msg=None, exception: str = "") -> bytes:
    w = pw.Writer()
    if exception:
        ew = pw.Writer()
        ew.string_field(1, exception)
        w.message_field(RESPONSE_FIELDS["exception"], ew.bytes(), always=True)
        return w.bytes()
    body = encode_msg(msg) if msg is not None else b""
    w.message_field(RESPONSE_FIELDS[method], body, always=True)
    return w.bytes()


def decode_response(data: bytes):
    """-> (method, msg_or_None); raises on exception responses."""
    for f, _, v in pw.Reader(data):
        name = _RESP_FIELD_TO_NAME.get(f)
        if name is None:
            continue
        if name == "exception":
            err = ""
            for ff, _, vv in pw.Reader(v):
                if ff == 1:
                    err = vv.decode()
            raise RuntimeError(f"ABCI exception: {err}")
        cls = RESPONSE_TYPES.get(name)
        return name, (decode_msg(cls, v) if cls else None)
    raise ValueError("empty ABCI response")
