"""BlockPool: concurrent per-height block requesters for fast sync
(reference: blockchain/v0/pool.go:62,107).

The pool tracks peers' reported heights, keeps up to `request_window` heights
in flight, assigns each height to a peer, and exposes a sliding window of
downloaded blocks to the reactor (peek_two_blocks / pop_request). A peer that
times out or sends a bad block is punished and its heights redone."""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

logger = logging.getLogger("tendermint_tpu.blocksync")

REQUEST_WINDOW = 40  # max heights in flight (reference: maxPendingRequests-ish)
# defaults for the [fastsync] peer_timeout / retry_sleep config knobs
# (kept as module constants for tests and non-config callers)
PEER_TIMEOUT = 10.0
RETRY_SLEEP = 0.05


@dataclass
class _PoolPeer:
    peer_id: str
    height: int = 0
    base: int = 0
    pending: int = 0
    did_timeout: bool = False


@dataclass
class _Requester:
    height: int
    peer_id: str = ""
    block: Optional[object] = None
    requested_at: float = field(default_factory=lambda: time.monotonic())


class BlockPool:
    def __init__(self, start_height: int, send_request: Callable, punish_peer: Callable,
                 metrics=None, peer_timeout: float = PEER_TIMEOUT,
                 retry_sleep: float = RETRY_SLEEP):
        """send_request(peer_id, height) -> awaitable; punish_peer(peer_id, reason);
        metrics: an optional BlockSyncMetrics (num_peers / latest_block_height);
        peer_timeout/retry_sleep: [fastsync] knobs (defaults unchanged)."""
        self.height = start_height  # next height to pop
        self.metrics = metrics
        self.peer_timeout = peer_timeout
        self.retry_sleep = retry_sleep
        self._peers: Dict[str, _PoolPeer] = {}
        self._requesters: Dict[int, _Requester] = {}
        self._send_request = send_request
        self._punish_peer = punish_peer
        self._task: Optional[asyncio.Task] = None
        self._running = False

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(self._make_requests_routine(), name="pool-requests")

    def stop(self) -> None:
        self._running = False
        if self._task:
            self._task.cancel()

    # -- peers -------------------------------------------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        p = self._peers.get(peer_id)
        if p is None:
            p = self._peers[peer_id] = _PoolPeer(peer_id)
        p.base, p.height = base, height
        if self.metrics is not None:
            self.metrics.num_peers.set(len(self._peers))

    def remove_peer(self, peer_id: str) -> None:
        self._peers.pop(peer_id, None)
        if self.metrics is not None:
            self.metrics.num_peers.set(len(self._peers))
        for req in self._requesters.values():
            if req.peer_id == peer_id and req.block is None:
                req.peer_id = ""
                req.requested_at = time.monotonic()

    def max_peer_height(self) -> int:
        return max((p.height for p in self._peers.values()), default=0)

    def num_peers(self) -> int:
        return len(self._peers)

    # -- blocks ------------------------------------------------------------

    def add_block(self, peer_id: str, block) -> bool:
        req = self._requesters.get(block.header.height)
        if req is None or req.block is not None:
            return False
        if req.peer_id != peer_id:
            # only the assigned requester's peer may fill the slot — otherwise
            # a bad block is unattributable and an attacker can pre-fill
            # heights with junk that is never re-requested (reference:
            # pool.go AddBlock checks the requester's peer)
            return False
        req.block = block
        p = self._peers.get(peer_id)
        if p:
            p.pending = max(0, p.pending - 1)
        return True

    def get_block(self, height: int):
        """Downloaded block at height, or None."""
        req = self._requesters.get(height)
        return req.block if req else None

    def pop_request(self) -> None:
        """first block was applied: advance (reference: pool.go PopRequest)."""
        self._requesters.pop(self.height, None)
        self.height += 1
        if self.metrics is not None:
            self.metrics.latest_block_height.set(self.height)

    def redo_request(self, height: int) -> str:
        """first/second failed validation: punish the sender, refetch
        (reference: pool.go RedoRequest)."""
        req = self._requesters.get(height)
        if req is None:
            return ""
        bad_peer = req.peer_id
        req.block = None
        req.peer_id = ""
        req.requested_at = time.monotonic()
        return bad_peer

    # -- request scheduling -------------------------------------------------

    def _pick_peer(self, height: int) -> Optional[_PoolPeer]:
        candidates = [
            p for p in self._peers.values()
            if p.base <= height <= p.height and p.pending < 20
        ]
        if not candidates:
            return None
        return random.choice(candidates)

    async def _make_requests_routine(self) -> None:
        try:
            while self._running:
                # spawn requesters for the window
                max_h = self.max_peer_height()
                next_h = self.height
                while (
                    len(self._requesters) < REQUEST_WINDOW
                    and next_h <= max_h
                ):
                    if next_h not in self._requesters:
                        self._requesters[next_h] = _Requester(next_h, "")
                    next_h += 1
                # assign unassigned / timed-out requesters
                now = time.monotonic()
                for req in list(self._requesters.values()):
                    if req.block is not None:
                        continue
                    if req.peer_id and now - req.requested_at > self.peer_timeout:
                        if self.metrics is not None:
                            self.metrics.peer_timeouts.inc()
                        await self._punish_peer(req.peer_id, "block request timeout")
                        self.remove_peer(req.peer_id)
                    if not req.peer_id:
                        peer = self._pick_peer(req.height)
                        if peer is None:
                            continue
                        req.peer_id = peer.peer_id
                        req.requested_at = now
                        peer.pending += 1
                        await self._send_request(peer.peer_id, req.height)
                await asyncio.sleep(self.retry_sleep)
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("pool request routine died")
