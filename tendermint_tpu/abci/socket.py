"""ABCI socket client + server: length-prefixed proto over TCP/unix with
strict FIFO request/response matching
(reference: abci/client/socket_client.go, abci/server/socket_server.go:30).

The client presents the same synchronous ABCIClient surface as the local
client (consensus and mempool call it from sync code), with pipelined
`*_async` variants returning futures — deliver_tx_async is what the executor
uses to pipeline a block's transactions (reference: state/execution.go:308
DeliverTxAsync). A dedicated reader thread matches responses FIFO."""

from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
from concurrent.futures import Future
from typing import Optional, Tuple

from tendermint_tpu.abci import types as a
from tendermint_tpu.abci import wire
from tendermint_tpu.abci.client import ABCIClient
from tendermint_tpu.libs import fail
from tendermint_tpu.libs import protowire as pw

logger = logging.getLogger("tendermint_tpu.abci.socket")


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("ABCI socket closed")
        buf += chunk
    return buf


def _read_varint(sock: socket.socket) -> int:
    out = shift = 0
    while True:
        b = _read_exact(sock, 1)[0]
        out |= (b & 0x7F) << shift
        if not (b & 0x80):
            return out
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def read_frame(sock: socket.socket, max_size: int = 104_857_600) -> bytes:
    ln = _read_varint(sock)
    if ln > max_size:
        raise ValueError("ABCI message too large")
    return _read_exact(sock, ln)


def write_frame(sock: socket.socket, data: bytes) -> None:
    sock.sendall(pw.encode_varint(len(data)) + data)


def _parse_addr(addr: str) -> Tuple[str, object]:
    if addr.startswith("unix://"):
        return "unix", addr[len("unix://") :]
    addr = addr.split("://", 1)[-1]
    host, _, port = addr.rpartition(":")
    return "tcp", (host or "127.0.0.1", int(port))


class SocketClient(ABCIClient):
    """(reference: abci/client/socket_client.go)"""

    def __init__(
        self,
        addr: str,
        connect_timeout: float = 10.0,
        call_timeout: float = 30.0,
    ):
        self.addr = addr
        self.call_timeout = call_timeout  # per-call ([base] abci_call_timeout)
        kind, target = _parse_addr(addr)
        if kind == "unix":
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(connect_timeout)
        self._sock.connect(target)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._pending: "queue.Queue[Tuple[str, Future]]" = queue.Queue()
        self._closed = False
        self._dead: Optional[Exception] = None  # reader died / socket broke
        self._reader = threading.Thread(target=self._recv_routine, daemon=True, name="abci-sock-recv")
        self._reader.start()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def is_dead(self) -> bool:
        return self._closed or self._dead is not None

    # -- plumbing ----------------------------------------------------------

    def _recv_routine(self) -> None:
        """Strict FIFO matching (reference: socket_client.go recvResponseRoutine)."""
        try:
            while not self._closed:
                frame = read_frame(self._sock)
                method, msg = wire.decode_response(frame)
                if method == "flush":
                    continue  # flush responses pair with flush requests we absorb
                want, fut = self._pending.get_nowait()
                if want != method:
                    err = RuntimeError(f"unexpected response {method}, want {want}")
                    if not fut.done():
                        fut.set_exception(err)  # fail the popped waiter too
                    raise err
                fut.set_result(msg)
        except Exception as e:
            self._dead = e
            if not self._closed:
                logger.error("ABCI socket reader died: %s", e)
            # fail all pending futures
            while True:
                try:
                    _, fut = self._pending.get_nowait()
                except queue.Empty:
                    break
                if not fut.done():
                    fut.set_exception(ConnectionError(str(e)))

    def _call_async(self, method: str, msg=None) -> Future:
        if self.is_dead():
            raise ConnectionError(
                f"ABCI socket client is dead: {self._dead or 'closed'}"
            )
        # chaos hook: a registered handler can kill the app server (or this
        # client's socket) mid-flight to exercise the reconnect path
        # (docs/ROBUSTNESS.md fail-point catalog)
        fail.fail_point("abci_client_call")
        fut: Future = Future()
        with self._wlock:
            self._pending.put((method, fut))
            write_frame(self._sock, wire.encode_request(method, msg))
        return fut

    def _call(self, method: str, msg=None):
        fut = self._call_async(method, msg)
        self.flush()
        return fut.result(timeout=self.call_timeout)

    def flush(self) -> None:
        with self._wlock:
            write_frame(self._sock, wire.encode_request("flush"))

    # -- the 17 methods ----------------------------------------------------

    def echo(self, msg: str) -> str:
        return msg  # transport liveness only

    def info(self, req: a.RequestInfo) -> a.ResponseInfo:
        return self._call("info", req)

    def set_option(self, req: a.RequestSetOption) -> a.ResponseSetOption:
        return self._call("set_option", req)

    def query(self, req: a.RequestQuery) -> a.ResponseQuery:
        return self._call("query", req)

    def check_tx(self, req: a.RequestCheckTx) -> a.ResponseCheckTx:
        return self._call("check_tx", req)

    def init_chain(self, req: a.RequestInitChain) -> a.ResponseInitChain:
        return self._call("init_chain", req)

    def begin_block(self, req: a.RequestBeginBlock) -> a.ResponseBeginBlock:
        return self._call("begin_block", req)

    def deliver_tx(self, req: a.RequestDeliverTx) -> a.ResponseDeliverTx:
        return self._call("deliver_tx", req)

    def deliver_tx_async(self, req: a.RequestDeliverTx) -> Future:
        """Pipelined delivery (reference: state/execution.go:308)."""
        return self._call_async("deliver_tx", req)

    def end_block(self, req: a.RequestEndBlock) -> a.ResponseEndBlock:
        return self._call("end_block", req)

    def commit(self) -> a.ResponseCommit:
        return self._call("commit")

    def list_snapshots(self) -> a.ResponseListSnapshots:
        return self._call("list_snapshots")

    def offer_snapshot(self, req: a.RequestOfferSnapshot) -> a.ResponseOfferSnapshot:
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req: a.RequestLoadSnapshotChunk) -> a.ResponseLoadSnapshotChunk:
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req: a.RequestApplySnapshotChunk) -> a.ResponseApplySnapshotChunk:
        return self._call("apply_snapshot_chunk", req)


def socket_client_creator(addr: str, call_timeout: float = 30.0):
    """ClientCreator for AppConns: one fresh connection per logical conn
    (reference: proxy/client.go NewRemoteClientCreator)."""

    def create() -> SocketClient:
        return SocketClient(addr, call_timeout=call_timeout)

    return create


class SocketServer:
    """Serves one Application to N connections, each handled by a thread;
    requests processed in order per connection
    (reference: abci/server/socket_server.go:30)."""

    def __init__(self, addr: str, app: a.Application):
        self.app = app
        self.kind, self.target = _parse_addr(addr)
        if self.kind == "unix":
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(self.target)
        self._sock.listen(8)
        self._app_lock = threading.Lock()  # one app, many conns
        self._threads = []
        self._conns: list = []  # live accepted sockets, closed on stop()
        self._running = False
        self.bound_addr = self._sock.getsockname()

    def start(self) -> None:
        self._running = True
        t = threading.Thread(target=self._accept_routine, daemon=True, name="abci-srv-accept")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        """Close the listener AND every accepted connection — a stopped app
        must look dead to its clients immediately (their reads fail now, not
        whenever the OS notices), which is what the reconnect path and the
        chaos app-restart scenario key off."""
        self._running = False
        try:
            # shutdown BEFORE close: a thread blocked in accept() pins the
            # open file description, so close() alone leaves the port in
            # LISTEN until that accept returns — shutdown wakes it, making
            # an immediate rebind (app restart on the same port) possible
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()

    def serve_forever(self) -> None:
        self.start()
        import time

        while self._running:
            time.sleep(0.2)

    def _accept_routine(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # daemon handler threads are not tracked: reconnecting clients
            # would otherwise accumulate dead Thread objects unboundedly
            self._conns.append(conn)
            threading.Thread(target=self._handle_conn, args=(conn,), daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                frame = read_frame(conn)
                method, msg = wire.decode_request(frame)
                if method == "flush":
                    write_frame(conn, wire.encode_response("flush"))
                    continue
                if method == "echo":
                    write_frame(conn, wire.encode_response("echo"))
                    continue
                try:
                    with self._app_lock:
                        handler = getattr(self.app, method)
                        resp = handler(msg) if msg is not None else handler()
                    write_frame(conn, wire.encode_response(method, resp))
                except Exception as e:  # app error -> exception response
                    logger.exception("app %s failed", method)
                    write_frame(conn, wire.encode_response(method, exception=str(e)))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
            try:
                conn.close()
            except OSError:
                pass
