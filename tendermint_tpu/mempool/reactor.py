"""Mempool reactor: gossips transactions on channel 0x30
(reference: mempool/reactor.go:18,190).

Per-peer broadcast task walks the mempool's tx list by insertion order and
skips txs the peer sent us (peer-ID tracking, reference: :41-96 mempoolIDs)."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List

from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor

logger = logging.getLogger("tendermint_tpu.mempool")

MEMPOOL_CHANNEL = 0x30
BROADCAST_SLEEP = 0.02


def encode_txs(txs: List[bytes]) -> bytes:
    w = pw.Writer()
    for tx in txs:
        w.bytes_field(1, tx, emit_empty=True)
    return w.bytes()


def decode_txs(data: bytes) -> List[bytes]:
    return [v for f, _, v in pw.Reader(data) if f == 1]


class MempoolReactor(Reactor):
    def __init__(self, mempool, broadcast: bool = True):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast = broadcast
        self._peer_tasks: Dict[str, asyncio.Task] = {}

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5, send_queue_capacity=128)]

    async def add_peer(self, peer) -> None:
        if self.broadcast:
            self._peer_tasks[peer.id] = asyncio.create_task(
                self._broadcast_tx_routine(peer), name=f"mempool-bcast-{peer.id[:8]}"
            )

    async def remove_peer(self, peer, reason) -> None:
        t = self._peer_tasks.pop(peer.id, None)
        if t:
            t.cancel()

    async def stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()
        self._peer_tasks.clear()

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        loop = asyncio.get_running_loop()
        for tx in decode_txs(msg_bytes):
            # check_tx holds the mempool lock and calls the app synchronously;
            # run off-loop so a slow CheckTx can't stall all p2p/consensus I/O
            # (same policy as the RPC broadcast path).
            try:
                await loop.run_in_executor(None, self.mempool.check_tx, tx, peer.id)
            except Exception as e:
                logger.debug("gossiped tx rejected: %s", e)

    async def _broadcast_tx_routine(self, peer) -> None:
        """(reference: mempool/reactor.go:190 broadcastTxRoutine)"""
        sent: set = set()
        try:
            while True:
                entries = self.mempool.entries()
                progress = False
                for key, tx, senders in entries:
                    if key in sent:
                        continue
                    if peer.id in senders:
                        sent.add(key)  # peer gave it to us; skip
                        continue
                    ok = await peer.send(MEMPOOL_CHANNEL, encode_txs([tx]))
                    if ok:
                        sent.add(key)
                        progress = True
                if not progress:
                    await asyncio.sleep(BROADCAST_SLEEP)
                # GC the sent-set against the live mempool
                if len(sent) > 10000:
                    live = {k for k, _, _ in self.mempool.entries()}
                    sent &= live
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("mempool broadcast died for %s", peer.id[:10])
