"""Release gate: ONE entrypoint, ONE exit code, over every referee.

Composes the three verdicts that gate a PR (ISSUE 17) into a single
machine-checkable decision:

  1. **fleet referee** (tools/fleet_referee.py) over a fleet soak's
     observatory dumps — safety audit, SLO verdicts, coverage;
  2. **perf ledger** (tools/perf_ledger.py) over the round artifacts —
     headline budget + fleet-gate column;
  3. optionally **tier-1 tests**, run as a subprocess via `--tier1-cmd`.

Exit codes are PINNED (tests assert them without spawning any fleet) and
severity-ordered — when several gates fail, the worst one names the exit:

    0  pass               every requested gate held
    2  safety_violation   the fleet referee found conflicting commits
    3  slo_tripped        a fleet SLO burn-rate guard tripped
    4  partial            fleet coverage gaps (missing/corrupt dumps)
    5  perf_regression    perf ledger headline/fleet-gate regression
    6  fleet_missing      fleet evidence absent/unusable (and not skipped)
    7  tier1_failed       the tier-1 test command exited nonzero

Usage:

    python tools/release_gate.py --fleet-dumps ./observatory --root . --check
    python tools/release_gate.py --skip-fleet --root . --check   # perf only
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import time
from typing import Any, Dict, List, Optional

from tendermint_tpu.tools import fleet_referee, perf_ledger

EXIT_PASS = 0
EXIT_SAFETY = 2
EXIT_SLO = 3
EXIT_PARTIAL = 4
EXIT_PERF = 5
EXIT_FLEET_MISSING = 6
EXIT_TIER1 = 7

# worst-first: a fork outranks a tripped SLO outranks a coverage gap
# outranks a perf regression outranks missing evidence outranks red tests
SEVERITY = (
    EXIT_SAFETY,
    EXIT_SLO,
    EXIT_PARTIAL,
    EXIT_PERF,
    EXIT_FLEET_MISSING,
    EXIT_TIER1,
)

_GATE_NAMES = {
    EXIT_PASS: "pass",
    EXIT_SAFETY: "safety_violation",
    EXIT_SLO: "slo_tripped",
    EXIT_PARTIAL: "partial",
    EXIT_PERF: "perf_regression",
    EXIT_FLEET_MISSING: "fleet_missing",
    EXIT_TIER1: "tier1_failed",
}


def _fleet_gate(
    dumps_dir: Optional[str],
    manifest_path: Optional[str],
    max_heights: Optional[int],
) -> dict:
    """Run the fleet referee in-process. Missing/unusable evidence is its
    own failure (EXIT_FLEET_MISSING): a release gate that quietly passes
    because nobody ran the fleet is not a gate."""
    if not dumps_dir or not os.path.isdir(dumps_dir):
        return {
            "status": "missing",
            "exit_code": EXIT_FLEET_MISSING,
            "detail": f"no dumps directory at {dumps_dir!r}",
        }
    dumps = fleet_referee.obs.load_dumps(dumps_dir)
    if not dumps:
        return {
            "status": "missing",
            "exit_code": EXIT_FLEET_MISSING,
            "detail": f"no observatory dumps under {dumps_dir!r}",
        }
    manifest = fleet_referee.load_manifest(manifest_path or dumps_dir)
    report = fleet_referee.build_report(
        dumps, manifest=manifest, max_heights=max_heights
    )
    fleet_referee.write_report(report, dumps_dir)
    code = report["exit_code"]
    if report["verdict"] == fleet_referee.VERDICT_NO_DATA:
        code = EXIT_FLEET_MISSING
    return {
        "status": report["verdict"],
        "exit_code": code,
        "detail": {
            "safety_violations": [
                v["height"] for v in report["safety"]["violations"]
            ],
            "slo_any_tripped": report["slo_any_tripped"],
            "coverage_missing": report["coverage"]["missing"],
            "heights_merged": report["waterfall"]["heights_merged"],
        },
    }


def _perf_gate(root: str, tolerance: float) -> dict:
    """perf_ledger --check in-process: headline budget + the fleet-gate
    column. An empty ledger is a pass here (young repos have no rounds),
    not a failure — the fleet gate owns evidence-missing semantics."""
    ledger = perf_ledger.load_ledger(root)
    if not ledger["bench"] and not ledger["multichip"]:
        return {"status": "no_rounds", "exit_code": EXIT_PASS, "detail": None}
    failures = perf_ledger.check_regressions(ledger, tolerance)
    if failures:
        return {
            "status": "regression",
            "exit_code": EXIT_PERF,
            "detail": failures,
        }
    return {
        "status": "pass",
        "exit_code": EXIT_PASS,
        "detail": {
            "bench_rounds": len(ledger["bench"]),
            "fleet_gate_missing_rounds": len(
                ledger["fleet_gate_missing_rounds"]
            ),
        },
    }


def _tier1_gate(cmd: Optional[str], timeout: float) -> dict:
    if not cmd:
        return {"status": "skipped", "exit_code": EXIT_PASS, "detail": None}
    try:
        proc = subprocess.run(
            cmd, shell=True, capture_output=True, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired:
        return {
            "status": "timeout",
            "exit_code": EXIT_TIER1,
            "detail": f"tier-1 command timed out after {timeout:.0f}s",
        }
    if proc.returncode != 0:
        return {
            "status": "failed",
            "exit_code": EXIT_TIER1,
            "detail": {
                "rc": proc.returncode,
                "tail": (proc.stdout or "")[-2000:] + (proc.stderr or "")[-500:],
            },
        }
    return {"status": "pass", "exit_code": EXIT_PASS, "detail": None}


def evaluate(
    *,
    fleet_dumps: Optional[str] = None,
    fleet_manifest: Optional[str] = None,
    max_heights: Optional[int] = None,
    skip_fleet: bool = False,
    perf_root: Optional[str] = ".",
    tolerance: float = 0.25,
    skip_perf: bool = False,
    tier1_cmd: Optional[str] = None,
    tier1_timeout: float = 1800.0,
) -> dict:
    """Run every requested gate and fold the failures severity-first into
    one exit code. Pure composition — each gate is independently testable
    and a skipped gate is RECORDED as skipped, never silently passed."""
    gates: Dict[str, Any] = {}
    if skip_fleet:
        gates["fleet"] = {"status": "skipped", "exit_code": EXIT_PASS, "detail": None}
    else:
        gates["fleet"] = _fleet_gate(fleet_dumps, fleet_manifest, max_heights)
    if skip_perf:
        gates["perf"] = {"status": "skipped", "exit_code": EXIT_PASS, "detail": None}
    else:
        gates["perf"] = _perf_gate(perf_root or ".", tolerance)
    gates["tier1"] = _tier1_gate(tier1_cmd, tier1_timeout)

    codes = {g["exit_code"] for g in gates.values()}
    exit_code = next((c for c in SEVERITY if c in codes), EXIT_PASS)
    return {
        "release_gate": 1,
        "generated_ts": round(time.time(), 3),
        "verdict": _GATE_NAMES[exit_code],
        "exit_code": exit_code,
        "gates": gates,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--fleet-dumps", default="./observatory",
        help="fleet soak dumps directory (default ./observatory)",
    )
    ap.add_argument(
        "--fleet-manifest",
        help="fleet manifest path (default <fleet-dumps>/fleet_manifest.json)",
    )
    ap.add_argument(
        "--heights", type=int, default=0,
        help="most recent heights to merge in the referee (0 = all)",
    )
    ap.add_argument(
        "--skip-fleet", action="store_true",
        help="skip the fleet gate (recorded as skipped, not passed silently)",
    )
    ap.add_argument(
        "--root", default=".",
        help="perf ledger root holding BENCH_r*.json (default .)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.25,
        help="perf ledger headline tolerance (default 0.25)",
    )
    ap.add_argument("--skip-perf", action="store_true", help="skip the perf gate")
    ap.add_argument(
        "--tier1-cmd",
        help="shell command running the tier-1 suite (nonzero rc => exit 7)",
    )
    ap.add_argument(
        "--tier1-timeout", type=float, default=1800.0,
        help="tier-1 command timeout in seconds (default 1800)",
    )
    ap.add_argument("--out", help="write the gate summary JSON here")
    ap.add_argument(
        "--check", action="store_true",
        help="exit with the severity-ordered gate code instead of 0",
    )
    args = ap.parse_args(argv)

    result = evaluate(
        fleet_dumps=args.fleet_dumps,
        fleet_manifest=args.fleet_manifest,
        max_heights=args.heights or None,
        skip_fleet=args.skip_fleet,
        perf_root=args.root,
        tolerance=args.tolerance,
        skip_perf=args.skip_perf,
        tier1_cmd=args.tier1_cmd,
        tier1_timeout=args.tier1_timeout,
    )
    print(json.dumps(result, indent=1, default=repr))
    if args.out:
        # the referee's --out is a directory; accept the same here rather
        # than masking the gate's exit code with an IsADirectoryError
        out = args.out
        if os.path.isdir(out):
            out = os.path.join(out, "release_gate.json")
        with open(out, "w") as f:
            json.dump(result, f, indent=1, default=repr)
    print(
        f"\nRELEASE GATE: {result['verdict'].upper()} "
        f"(exit {result['exit_code']})"
    )
    if args.check:
        return result["exit_code"]
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
