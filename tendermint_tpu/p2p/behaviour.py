"""Peer behaviour reporting + trust metric.

reference: behaviour/reporter.go + peer_behaviour.go (thin indirection for
reactors to report peer conduct -> switch mark/stop) and p2p/trust/metric.go
(EWMA-ish trust score per peer).

Wiring: the Switch owns a Reporter (switch.reporter); message delivery counts
as good conduct and receive errors as bad, so every peer carries a live trust
score (exposed via /net_info). Reactors can report richer conduct directly.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict

logger = logging.getLogger("tendermint_tpu.p2p")

# behaviour kinds (reference: behaviour/peer_behaviour.go)
BAD_MESSAGE = "bad_message"
MESSAGE_OUT_OF_ORDER = "message_out_of_order"
CONSENSUS_VOTE = "consensus_vote"
BLOCK_PART = "block_part"

_GOOD = {CONSENSUS_VOTE, BLOCK_PART}


@dataclass(frozen=True)
class PeerBehaviour:
    peer_id: str
    kind: str
    reason: str = ""

    def is_good(self) -> bool:
        return self.kind in _GOOD


class TrustMetric:
    """Exponentially weighted good/bad ratio in [0, 1]
    (reference: p2p/trust/metric.go — proportional + integral terms,
    simplified to a decayed ratio with the same monotonicity)."""

    def __init__(self, decay: float = 0.9):
        self.decay = decay
        self.good = 1.0  # optimistic prior (reference starts at 100%)
        self.bad = 0.0
        self._last = time.monotonic()

    def _decay_to_now(self) -> None:
        now = time.monotonic()
        steps = now - self._last
        if steps > 0:
            f = self.decay ** min(steps, 60.0)
            self.good *= f
            self.bad *= f
            self._last = now

    def record_good(self, weight: float = 1.0) -> None:
        self._decay_to_now()
        self.good += weight

    def record_bad(self, weight: float = 1.0) -> None:
        self._decay_to_now()
        self.bad += weight

    def score(self) -> float:
        self._decay_to_now()
        total = self.good + self.bad
        return self.good / total if total > 0 else 1.0


class Reporter:
    """Routes behaviour reports to the switch: repeated bad conduct stops the
    peer (reference: behaviour/reporter.go SwitchReporter)."""

    def __init__(self, switch=None, bad_threshold: float = 0.3, history_size: int = 1000):
        self.switch = switch
        self.bad_threshold = bad_threshold
        self.metrics: Dict[str, TrustMetric] = {}
        self.history: Deque[PeerBehaviour] = deque(maxlen=history_size)

    MAX_TRACKED = 4096  # node ids are attacker-generated; bound the map

    def metric(self, peer_id: str) -> TrustMetric:
        m = self.metrics.get(peer_id)
        if m is None:
            while len(self.metrics) >= self.MAX_TRACKED:
                self.metrics.pop(next(iter(self.metrics)))
            m = self.metrics[peer_id] = TrustMetric()
        return m

    async def report(self, pb: PeerBehaviour) -> None:
        self.history.append(pb)
        m = self.metric(pb.peer_id)
        if pb.is_good():
            m.record_good()
            return
        m.record_bad()
        if self.switch is not None and m.score() < self.bad_threshold:
            peer = self.switch.peers.get(pb.peer_id)
            if peer is not None:
                logger.info(
                    "peer %s trust %.2f below threshold; disconnecting",
                    pb.peer_id[:10], m.score(),
                )
                await self.switch.stop_peer_for_error(
                    peer, f"low trust after {pb.kind}: {pb.reason}"
                )

    def score(self, peer_id: str) -> float:
        m = self.metrics.get(peer_id)
        return m.score() if m is not None else 1.0
