"""In-process example applications (reference: abci/example/kvstore, counter).

KVStoreApplication: key=value transactions, app hash = big-endian encoded tx
count (mirrors the reference's size-based app hash, abci/example/kvstore/kvstore.go:66).
PersistentKVStoreApplication adds validator-update txs ("val:pubkeyhex!power")
and height persistence for handshake/replay testing.
CounterApplication: serial nonce check (abci/example/counter/counter.go:11).
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.libs.kvdb import KVDB, MemDB

VALIDATOR_TX_PREFIX = b"val:"


SNAPSHOT_CHUNK_SIZE = 65536


class KVStoreApplication(abci.Application):
    def __init__(self, db: Optional[KVDB] = None, snapshot_interval: int = 0,
                 snapshot_keep: int = 5):
        self.db = db or MemDB()
        self.size = int.from_bytes(self.db.get(b"__size__") or b"\x00", "big")
        self.height = int.from_bytes(self.db.get(b"__height__") or b"\x00", "big")
        self.app_hash = self.db.get(b"__apphash__") or b""
        self.staged: List[tuple] = []
        # state-sync snapshots: height -> (Snapshot, [chunk bytes])
        self.snapshot_interval = snapshot_interval
        self.snapshot_keep = snapshot_keep
        self._snapshots: Dict[int, tuple] = {}
        self._restore: Optional[dict] = None  # in-flight restore

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": self.size}),
            version="0.1.0",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if not req.tx:
            return abci.ResponseCheckTx(code=1, log="empty tx")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if b"=" in req.tx:
            key, value = req.tx.split(b"=", 1)
        else:
            key = value = req.tx
        self.staged.append((key, value))
        events = [
            abci.Event(
                type="app",
                attributes=[(b"creator", b"tendermint_tpu", True), (b"key", key, True)],
            )
        ]
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK, events=events)

    def _compute_app_hash(self) -> bytes:
        # app hash = encoded size (mirrors reference kvstore.go:113)
        return struct.pack(">Q", self.size)

    def commit(self) -> abci.ResponseCommit:
        for key, value in self.staged:
            self.db.set(b"kv/" + key, value)
            self.size += 1
        self.staged.clear()
        self.height += 1
        self.app_hash = self._compute_app_hash()
        self.db.set(b"__size__", self.size.to_bytes(8, "big"))
        self.db.set(b"__height__", self.height.to_bytes(8, "big"))
        self.db.set(b"__apphash__", self.app_hash)
        if self.snapshot_interval and self.height % self.snapshot_interval == 0:
            self._take_snapshot()
        return abci.ResponseCommit(data=self.app_hash)

    # -- state-sync snapshots (reference: the ABCI snapshot protocol the
    # reference kvstore leaves unimplemented; format 1 = JSON dump) ---------

    def _take_snapshot(self) -> None:
        import hashlib

        payload = json.dumps(
            {
                "height": self.height,
                "size": self.size,
                "app_hash": self.app_hash.hex(),
                "items": [
                    [k[len(b"kv/"):].hex(), v.hex()]
                    for k, v in sorted(self.db.iterate_prefix(b"kv/"))
                ],
            },
            separators=(",", ":"),
        ).encode()
        chunks = [
            payload[i : i + SNAPSHOT_CHUNK_SIZE]
            for i in range(0, len(payload), SNAPSHOT_CHUNK_SIZE)
        ] or [b""]
        snap = abci.Snapshot(
            height=self.height,
            format=1,
            chunks=len(chunks),
            hash=hashlib.sha256(payload).digest(),
        )
        self._snapshots[self.height] = (snap, chunks)
        while len(self._snapshots) > self.snapshot_keep:
            del self._snapshots[min(self._snapshots)]

    def list_snapshots(self) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots(
            snapshots=[s for s, _ in self._snapshots.values()]
        )

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        entry = self._snapshots.get(req.height)
        if entry is None or entry[0].format != req.format:
            return abci.ResponseLoadSnapshotChunk()
        snap, chunks = entry
        if not (0 <= req.chunk < len(chunks)):
            return abci.ResponseLoadSnapshotChunk()
        return abci.ResponseLoadSnapshotChunk(chunk=chunks[req.chunk])

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        s = req.snapshot
        if s is None:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT)
        if s.format != 1:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT_FORMAT)
        self._restore = {"snapshot": s, "app_hash": req.app_hash, "chunks": {}}
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        import hashlib

        if self._restore is None:
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_SNAPSHOT_CHUNK_ABORT)
        self._restore["chunks"][req.index] = req.chunk
        snap = self._restore["snapshot"]
        if len(self._restore["chunks"]) < snap.chunks:
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_SNAPSHOT_CHUNK_ACCEPT)

        payload = b"".join(self._restore["chunks"][i] for i in range(snap.chunks))
        if hashlib.sha256(payload).digest() != snap.hash:
            self._restore = None
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT
            )
        doc = json.loads(payload.decode())
        # the payload's claimed app hash must match the light-client-trusted
        # hash tendermint handed us in OfferSnapshot — a self-consistent but
        # forged payload fails here
        trusted = self._restore["app_hash"]
        if trusted and bytes.fromhex(doc["app_hash"]) != trusted:
            self._restore = None
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT
            )
        for k, _ in list(self.db.iterate_prefix(b"kv/")):
            self.db.delete(k)
        for k_hex, v_hex in doc["items"]:
            self.db.set(b"kv/" + bytes.fromhex(k_hex), bytes.fromhex(v_hex))
        self.size = doc["size"]
        self.height = doc["height"]
        self.app_hash = bytes.fromhex(doc["app_hash"])
        self.db.set(b"__size__", self.size.to_bytes(8, "big"))
        self.db.set(b"__height__", self.height.to_bytes(8, "big"))
        self.db.set(b"__apphash__", self.app_hash)
        self._restore = None
        return abci.ResponseApplySnapshotChunk(result=abci.APPLY_SNAPSHOT_CHUNK_ACCEPT)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "/store" or req.path == "":
            value = self.db.get(b"kv/" + req.data)
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK,
                key=req.data,
                value=value or b"",
                height=self.height,
                log="exists" if value is not None else "does not exist",
            )
        return abci.ResponseQuery(code=1, log=f"unknown path {req.path}")


class SignedKVStoreApplication(KVStoreApplication):
    """KVStore requiring a signed-tx envelope (types/signed_tx.py) on every
    tx — the stub application behind device-batched CheckTx admission.

    CheckTx is the ABCI split in action: when the node pre-verified the
    envelope's signature through the scheduler's admission lane, the
    request carries `sig_precheck` = OK|BAD and the app CONSUMES the
    verdict; with no verdict (NONE — plain node, remote submitter,
    precheck disabled) it verifies serially on the host, which is exactly
    the per-tx loop the admission lane replaces (and the serial arm the
    `tx_admission` bench measures).

    DeliverTx unwraps the payload and applies it as a normal key=value tx.
    It trusts CheckTx-gated admission and does not re-verify — fine for a
    stub/bench app; a production app distrusting proposers would check
    `sig_precheck` at DeliverTx too (the envelope rides in the block, so
    anyone can)."""

    CODE_BAD_ENVELOPE = 10
    CODE_BAD_SIGNATURE = 11

    def __init__(self, db: Optional[KVDB] = None, **kw):
        super().__init__(db, **kw)
        self.serial_verifies = 0  # host verifies paid (no precheck verdict)
        self.precheck_consumed = 0  # verdicts consumed from the node

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        from tendermint_tpu.types import signed_tx as stx

        env = stx.decode_signed_tx(req.tx)
        if env is None:
            return abci.ResponseCheckTx(
                code=self.CODE_BAD_ENVELOPE, log="not a signed-tx envelope"
            )
        if req.sig_precheck == abci.SIG_PRECHECK_OK:
            self.precheck_consumed += 1
            ok = True
        elif req.sig_precheck == abci.SIG_PRECHECK_BAD:
            self.precheck_consumed += 1
            ok = False
        else:
            self.serial_verifies += 1
            ok = stx.verify_signed_tx(env)
        if not ok:
            return abci.ResponseCheckTx(
                code=self.CODE_BAD_SIGNATURE, log="invalid tx signature"
            )
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        from tendermint_tpu.types import signed_tx as stx

        env = stx.decode_signed_tx(req.tx)
        if env is None:
            return abci.ResponseDeliverTx(
                code=self.CODE_BAD_ENVELOPE, log="not a signed-tx envelope"
            )
        return super().deliver_tx(abci.RequestDeliverTx(tx=env.payload))


class MerkleKVStoreApplication(KVStoreApplication):
    """KVStore whose app hash is the SimpleMap merkle root over its pairs,
    with `prove=true` queries answered by ValueOp proofs that chain to the
    header's app_hash — the tree shape crypto/merkle/proof_value.go:14
    verifies. This is what the light proxy's verified abci_query runs
    against (light/rpc/client.go:116)."""

    def _pairs(self) -> Dict[bytes, bytes]:
        return {
            k[len(b"kv/"):]: v for k, v in sorted(self.db.iterate_prefix(b"kv/"))
        }

    def _compute_app_hash(self) -> bytes:
        from tendermint_tpu.crypto.proof_ops import simple_map_proofs

        # One tree build per commit; proved queries reuse the per-key
        # ValueOps until the next commit replaces them.
        root, ops = simple_map_proofs(self._pairs())
        self._proof_cache = (self.height, ops)
        return root

    def _proofs(self):
        cache = getattr(self, "_proof_cache", None)
        if cache is None or cache[0] != self.height:
            from tendermint_tpu.crypto.proof_ops import simple_map_proofs

            _, ops = simple_map_proofs(self._pairs())
            cache = self._proof_cache = (self.height, ops)
        return cache[1]

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        res = super().query(req)
        if req.prove and res.code == abci.CODE_TYPE_OK and res.value:
            vop = self._proofs().get(req.data)
            if vop is not None:
                res.proof_ops = [vop.proof_op()]
        return res


class PersistentKVStoreApplication(KVStoreApplication):
    """Adds validator updates via "val:<pubkey_hex>!<power>" txs
    (reference: abci/example/kvstore/persistent_kvstore.go)."""

    def __init__(self, db: Optional[KVDB] = None):
        super().__init__(db)
        self.val_updates: List[abci.ValidatorUpdate] = []

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for v in req.validators:
            self._set_validator(v)
        return abci.ResponseInitChain()

    def _set_validator(self, v: abci.ValidatorUpdate) -> None:
        key = b"valkey/" + v.pub_key_bytes
        if v.power == 0:
            self.db.delete(key)
        else:
            self.db.set(key, str(v.power).encode())

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            body = req.tx[len(VALIDATOR_TX_PREFIX):]
            try:
                pubkey_hex, power_s = body.split(b"!", 1)
                pubkey = bytes.fromhex(pubkey_hex.decode())
                power = int(power_s)
            except Exception:
                return abci.ResponseDeliverTx(code=2, log="invalid validator tx")
            if len(pubkey) != 32 or power < 0:
                return abci.ResponseDeliverTx(code=2, log="invalid validator tx")
            update = abci.ValidatorUpdate("ed25519", pubkey, power)
            self.val_updates.append(update)
            self._set_validator(update)
            return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)
        return super().deliver_tx(req)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        updates, self.val_updates = self.val_updates, []
        return abci.ResponseEndBlock(validator_updates=updates)


class CounterApplication(abci.Application):
    """Serial-nonce app (reference: abci/example/counter/counter.go)."""

    def __init__(self, serial: bool = True):
        self.serial = serial
        self.tx_count = 0
        self.height = 0

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=f"txs:{self.tx_count}", last_block_height=self.height,
            last_block_app_hash=(
                struct.pack(">Q", self.tx_count) if self.height else b""
            ),
        )

    def _check_value(self, tx: bytes, expected: int) -> bool:
        if len(tx) > 8:
            return False
        value = int.from_bytes(tx, "big")
        return value == expected

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if self.serial and not self._check_value(req.tx, self.tx_count):
            return abci.ResponseCheckTx(code=2, log="invalid nonce")
        return abci.ResponseCheckTx()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if self.serial and not self._check_value(req.tx, self.tx_count):
            return abci.ResponseDeliverTx(code=2, log="invalid nonce")
        self.tx_count += 1
        return abci.ResponseDeliverTx()

    def commit(self) -> abci.ResponseCommit:
        self.height += 1
        if self.tx_count == 0:
            return abci.ResponseCommit()
        return abci.ResponseCommit(data=struct.pack(">Q", self.tx_count))
