"""Offline profiler-trace analyzer: capture directory → per-stage time table.

Turns a `libs/profiler.py` capture (or any jax/TensorBoard profile dump)
into the PERF.md-style attribution table — per-kernel and per-fused-stage
(uptree, fenwick_reduce, bucket_fold, persig) totals — in one command
instead of an afternoon of perfetto spelunking:

    python tools/profile_report.py <capture-dir-or-file> [--top N] [--json OUT]

Two input forms, no external deps:

- `*.trace.json.gz` — the perfetto/chrome trace jax writes next to the
  xplane file: `X` (complete) events with per-thread nesting; process and
  thread names from `M` metadata events.
- `*.xplane.pb` — the XSpace protobuf, parsed with a minimal protobuf
  wire-format walker (tensorflow/tensorboard are NOT importable in this
  container, and the schema needed here is 4 small messages: XSpace →
  XPlane → XLine → XEvent + the id→name metadata maps).

Times are reported as **total** (event wall span, includes children) and
**self** (total minus nested children on the same thread) — `self` is the
honest per-stage cost; `total` localises where a wall-clock budget went.
Python host-tracing events (`$`-prefixed) are folded into one `host_python`
stage so device/runtime rows aren't swamped.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

# Stage classification, first match wins (case-insensitive). Kernel names
# surface differently per backend (Pjit wrappers on host, fusion names on
# device planes), so patterns match the stable substrings our kernels carry
# (ops/pallas_msm.py, ops/msm_jax.py, ops/ed25519_jax.py).
STAGE_PATTERNS: List[Tuple[str, str]] = [
    ("uptree", r"uptree"),
    ("fenwick_reduce", r"fenwick"),
    ("bucket_fold", r"bucket"),
    ("persig", r"persig|verify_prepared|verify_core|ladder"),
    ("decompress", r"decompress|ristretto"),
    ("msm_other", r"rlc|msm|pallas|pippenger"),
    (
        "compile",
        r"backend_compile|compile|codegen|llvm|hlo passes|lower|"
        r"trace_to_jaxpr|optimization|emit",
    ),
    (
        "transfer",
        r"transferto|transferfrom|device_put|copyto|bufferfromhost|"
        r"toliteral|h2d|d2h|copy_to|transfer",
    ),
    (
        "dispatch",
        r"pjitfunction|executesharded|execute|runthunks|thunk|"
        r"parsearguments|donate",
    ),
    ("host_python", r"^\$"),
]
_COMPILED = [(stage, re.compile(pat, re.IGNORECASE)) for stage, pat in STAGE_PATTERNS]


def classify(name: str) -> str:
    for stage, rx in _COMPILED:
        if rx.search(name):
            return stage
    return "other"


# ---------------------------------------------------------------------------
# Input discovery


def find_capture_files(path: str) -> List[str]:
    """Resolve a run dir / capture dir / single file to trace artifacts,
    preferring the newest capture and the richer json form."""
    if os.path.isfile(path):
        return [path]
    jsons = sorted(glob.glob(os.path.join(path, "**", "*.trace.json.gz"), recursive=True))
    xplanes = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"), recursive=True))
    picked = []
    if jsons:
        picked.append(jsons[-1])
    elif xplanes:
        picked.append(xplanes[-1])
    return picked


# ---------------------------------------------------------------------------
# chrome-trace (.trace.json.gz) parsing


def _load_chrome_trace(path: str):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    evs = data.get("traceEvents", data if isinstance(data, list) else [])
    pnames: Dict[int, str] = {}
    tnames: Dict[Tuple[int, int], str] = {}
    out = []
    for e in evs:
        ph = e.get("ph")
        if ph == "M":
            if e.get("name") == "process_name":
                pnames[e.get("pid")] = e.get("args", {}).get("name", "")
            elif e.get("name") == "thread_name":
                tnames[(e.get("pid"), e.get("tid"))] = e.get("args", {}).get("name", "")
        elif ph == "X":
            out.append(
                {
                    "name": e.get("name", ""),
                    "ts_us": float(e.get("ts", 0.0)),
                    "dur_us": float(e.get("dur", 0.0)),
                    "pid": e.get("pid"),
                    "tid": e.get("tid"),
                }
            )
    for e in out:
        e["plane"] = pnames.get(e["pid"], str(e["pid"]))
        e["thread"] = tnames.get((e["pid"], e["tid"]), str(e["tid"]))
    return out


# ---------------------------------------------------------------------------
# xplane (.xplane.pb) parsing — minimal protobuf wire walker


def _walk(buf: bytes, pos: int = 0, end: Optional[int] = None):
    """Yield (field_no, wire_type, value) triples from a protobuf buffer.
    Varints decode to int; length-delimited fields yield memoryview slices."""
    view = memoryview(buf)
    if end is None:
        end = len(buf)
    while pos < end:
        tag = 0
        shift = 0
        while True:
            b = view[pos]
            pos += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v = 0
            shift = 0
            while True:
                b = view[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            yield fno, wt, v
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                b = view[pos]
                pos += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            yield fno, wt, view[pos : pos + ln]
            pos += ln
        elif wt == 5:
            yield fno, wt, view[pos : pos + 4]
            pos += 4
        elif wt == 1:
            yield fno, wt, view[pos : pos + 8]
            pos += 8
        else:  # groups (3/4) never appear in xplane
            raise ValueError(f"unsupported wire type {wt} at {pos}")


def _svarint(v: int) -> int:
    """Protobuf int64 fields arrive as two's-complement varints."""
    return v - (1 << 64) if v >= 1 << 63 else v


def _load_xplane(path: str):
    """XSpace → flat event list. Schema (xplane.proto): XSpace.planes=1;
    XPlane{name=2, lines=3, event_metadata=4 map<i64,XEventMetadata{name=2}>};
    XLine{name=2, timestamp_ns=3, events=4, display_name=11};
    XEvent{metadata_id=1, offset_ps=2, duration_ps=3}."""
    with open(path, "rb") as f:
        buf = f.read()
    out = []
    for fno, _wt, plane_buf in _walk(buf):
        if fno != 1:
            continue
        plane_name = ""
        lines = []
        ev_names: Dict[int, str] = {}
        for pf, _pwt, pv in _walk(plane_buf):
            if pf == 2:
                plane_name = bytes(pv).decode(errors="replace")
            elif pf == 3:
                lines.append(pv)
            elif pf == 4:  # map entry {key=1 varint, value=2 XEventMetadata}
                key, name = None, ""
                for mf, _mwt, mv in _walk(pv):
                    if mf == 1:
                        key = _svarint(mv)
                    elif mf == 2:
                        for ef, _ewt, ev in _walk(mv):
                            if ef == 2:
                                name = bytes(ev).decode(errors="replace")
                if key is not None:
                    ev_names[key] = name
        for line_buf in lines:
            line_name = ""
            line_ts_ns = 0
            events = []
            for lf, _lwt, lv in _walk(line_buf):
                if lf == 2:
                    line_name = bytes(lv).decode(errors="replace")
                elif lf == 11 and not line_name:
                    line_name = bytes(lv).decode(errors="replace")
                elif lf == 3:
                    line_ts_ns = _svarint(lv)
                elif lf == 4:
                    events.append(lv)
            for ev_buf in events:
                mid = offset_ps = dur_ps = 0
                for ef, _ewt, ev in _walk(ev_buf):
                    if ef == 1:
                        mid = _svarint(ev)
                    elif ef == 2:
                        offset_ps = _svarint(ev)
                    elif ef == 3:
                        dur_ps = _svarint(ev)
                out.append(
                    {
                        "name": ev_names.get(mid, f"metadata:{mid}"),
                        "ts_us": line_ts_ns / 1e3 + offset_ps / 1e6,
                        "dur_us": dur_ps / 1e6,
                        "pid": plane_name,
                        "tid": line_name,
                        "plane": plane_name,
                        "thread": line_name,
                    }
                )
    return out


def load_events(path: str) -> List[dict]:
    if path.endswith(".xplane.pb"):
        return _load_xplane(path)
    return _load_chrome_trace(path)


# ---------------------------------------------------------------------------
# Aggregation


def _with_self_times(events: List[dict]) -> None:
    """Annotate each event with `self_us` = dur minus same-thread nested
    children (stack sweep per thread; chrome/xplane events nest properly)."""
    by_thread: Dict[Tuple, List[dict]] = {}
    for e in events:
        e["self_us"] = e["dur_us"]
        by_thread.setdefault((e["pid"], e["tid"]), []).append(e)
    for evs in by_thread.values():
        evs.sort(key=lambda e: (e["ts_us"], -e["dur_us"]))
        stack: List[dict] = []
        for e in evs:
            while stack and stack[-1]["ts_us"] + stack[-1]["dur_us"] <= e["ts_us"] + 1e-9:
                stack.pop()
            if stack:
                stack[-1]["self_us"] -= e["dur_us"]
            stack.append(e)


_PROFILER_SELF = re.compile(r"(start|stop)_trace$")


def analyze(events: List[dict]) -> dict:
    """Events → {wall_ms, stages: [...], ops: [...], planes: [...]} with
    stages/ops sorted by self time descending. The profiler's own
    start/stop_trace wrapper events span the whole capture window and would
    swamp the host_python stage, so they are dropped first."""
    events = [e for e in events if not _PROFILER_SELF.search(e["name"])]
    _with_self_times(events)
    ops: Dict[str, dict] = {}
    stages: Dict[str, dict] = {}
    planes: Dict[str, dict] = {}
    t_min, t_max = float("inf"), 0.0
    for e in events:
        t_min = min(t_min, e["ts_us"])
        t_max = max(t_max, e["ts_us"] + e["dur_us"])
        stage = classify(e["name"])
        o = ops.setdefault(
            e["name"], {"stage": stage, "count": 0, "total_us": 0.0, "self_us": 0.0}
        )
        o["count"] += 1
        o["total_us"] += e["dur_us"]
        o["self_us"] += max(0.0, e["self_us"])
        s = stages.setdefault(stage, {"count": 0, "total_us": 0.0, "self_us": 0.0})
        s["count"] += 1
        s["total_us"] += e["dur_us"]
        s["self_us"] += max(0.0, e["self_us"])
        p = planes.setdefault(e["plane"], {"events": 0, "self_us": 0.0})
        p["events"] += 1
        p["self_us"] += max(0.0, e["self_us"])
    wall_us = (t_max - t_min) if events else 0.0
    self_total = sum(s["self_us"] for s in stages.values()) or 1.0

    def _row(name, d):
        return {
            "name": name,
            **{k: (round(v, 3) if isinstance(v, float) else v) for k, v in d.items()},
            "share": round(d["self_us"] / self_total, 4),
        }

    return {
        "events": len(events),
        "wall_ms": round(wall_us / 1e3, 3),
        "stages": sorted(
            (_row(k, v) for k, v in stages.items()),
            key=lambda r: -r["self_us"],
        ),
        "ops": sorted(
            (_row(k, v) for k, v in ops.items()), key=lambda r: -r["self_us"]
        ),
        "planes": [
            {"plane": k, **{kk: round(vv, 3) for kk, vv in v.items()}}
            for k, v in sorted(planes.items())
        ],
    }


def report(path: str, top: int = 25) -> dict:
    """Full report for a capture dir or trace file."""
    files = find_capture_files(path)
    if not files:
        raise FileNotFoundError(
            f"no *.trace.json.gz or *.xplane.pb under {path!r}"
        )
    events = []
    for f in files:
        events.extend(load_events(f))
    out = analyze(events)
    out["capture"] = files
    out["ops"] = out["ops"][: max(0, top)]
    return out


def render_markdown(rep: dict) -> str:
    lines = [
        f"# Profile report — {len(rep.get('capture', []))} artifact(s), "
        f"{rep['events']} events, {rep['wall_ms']:.1f} ms wall",
        "",
        "## Per-stage (self time; total includes nested children)",
        "",
        "| stage | events | self ms | total ms | share |",
        "|---|---:|---:|---:|---:|",
    ]
    for s in rep["stages"]:
        lines.append(
            f"| {s['name']} | {s['count']} | {s['self_us']/1e3:.3f} "
            f"| {s['total_us']/1e3:.3f} | {s['share']*100:.1f}% |"
        )
    lines += [
        "",
        "## Top ops",
        "",
        "| op | stage | count | self ms | total ms |",
        "|---|---|---:|---:|---:|",
    ]
    for o in rep["ops"]:
        name = o["name"] if len(o["name"]) <= 72 else o["name"][:69] + "..."
        lines.append(
            f"| `{name}` | {o['stage']} | {o['count']} "
            f"| {o['self_us']/1e3:.3f} | {o['total_us']/1e3:.3f} |"
        )
    if rep.get("planes"):
        lines += ["", "## Planes", ""]
        for p in rep["planes"]:
            lines.append(
                f"- `{p['plane']}`: {p['events']} events, "
                f"{p['self_us']/1e3:.1f} ms self"
            )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="capture directory (or a single trace file)")
    ap.add_argument("--top", type=int, default=25, help="top-N ops to list")
    ap.add_argument("--json", help="also write the full report as JSON here")
    args = ap.parse_args(argv)
    try:
        rep = report(args.path, top=args.top)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    sys.stdout.write(render_markdown(rep))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rep, f, indent=1)
        print(f"\nJSON report: {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
