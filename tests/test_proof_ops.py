"""Merkle proof operators (crypto/proof_ops.py) — the generalized proof
framework behind light-client-verified abci_query
(reference: crypto/merkle/proof_op.go, proof_value.go, proof_key_path.go)."""

import pytest

from tendermint_tpu.crypto.proof_ops import (
    KEY_ENCODING_HEX,
    KEY_ENCODING_URL,
    KeyPath,
    ProofOp,
    ValueOp,
    decode_proof_ops,
    default_proof_runtime,
    encode_proof_ops,
    key_path_to_keys,
    simple_map_proofs,
)


def test_key_path_roundtrip():
    kp = KeyPath()
    kp.append_key(b"App", KEY_ENCODING_URL)
    kp.append_key(b"IBC", KEY_ENCODING_URL)
    kp.append_key(b"\x01\x02\x03", KEY_ENCODING_HEX)
    s = str(kp)
    assert s == "/App/IBC/x:010203"
    assert key_path_to_keys(s) == [b"App", b"IBC", b"\x01\x02\x03"]
    # url-encoding survives awkward bytes
    kp2 = KeyPath().append_key(b"a/b c%", KEY_ENCODING_URL)
    assert key_path_to_keys(str(kp2)) == [b"a/b c%"]
    with pytest.raises(ValueError):
        key_path_to_keys("no-leading-slash")


def test_value_op_verifies_and_rejects_tampering():
    kv = {b"k%d" % i: b"v%d" % i for i in range(7)}
    root, ops = simple_map_proofs(kv)
    prt = default_proof_runtime()

    pop = ops[b"k3"].proof_op()
    kp = str(KeyPath().append_key(b"k3"))
    prt.verify_value([pop], root, kp, b"v3")  # ok

    with pytest.raises(ValueError):  # wrong value
        prt.verify_value([pop], root, kp, b"v4")
    with pytest.raises(ValueError):  # wrong root
        prt.verify_value([pop], b"\x00" * 32, kp, b"v3")
    with pytest.raises(ValueError):  # wrong key in path
        prt.verify_value([pop], root, str(KeyPath().append_key(b"k4")), b"v3")
    with pytest.raises(ValueError):  # leftover keypath segments
        prt.verify_value(
            [pop], root, str(KeyPath().append_key(b"extra").append_key(b"k3")), b"v3"
        )


def test_proof_op_wire_roundtrip():
    kv = {b"alpha": b"1", b"beta": b"2"}
    root, ops = simple_map_proofs(kv)
    pop = ops[b"beta"].proof_op()
    raw = encode_proof_ops([pop])
    back = decode_proof_ops(raw)
    assert len(back) == 1
    assert back[0].type == pop.type and back[0].key == pop.key
    vop = ValueOp.from_proof_op(back[0])
    assert vop.run([b"2"])[0] == root


def test_two_layer_op_chain():
    """Substore root proven inside an outer map — the multi-op path the
    runtime walks right-to-left (proof_op.go:39)."""
    inner = {b"x": b"42"}
    inner_root, inner_ops = simple_map_proofs(inner)
    outer = {b"store": inner_root, b"other": b"zzz"}
    outer_root, outer_ops = simple_map_proofs(outer)

    pops = [inner_ops[b"x"].proof_op(), outer_ops[b"store"].proof_op()]
    kp = KeyPath().append_key(b"store").append_key(b"x")
    default_proof_runtime().verify_value(pops, outer_root, str(kp), b"42")

    with pytest.raises(ValueError):
        default_proof_runtime().verify_value(pops, outer_root, str(kp), b"43")


def test_merkle_kvstore_app_proofs():
    """MerkleKVStoreApplication: app_hash == simple-map root; prove=true
    queries carry a ValueOp that verifies against it."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.kvstore import MerkleKVStoreApplication

    app = MerkleKVStoreApplication()
    app.deliver_tx(abci.RequestDeliverTx(tx=b"name=tpu"))
    app.deliver_tx(abci.RequestDeliverTx(tx=b"lang=py"))
    res_commit = app.commit()
    root = res_commit.data
    assert root == app.app_hash and len(root) == 32

    res = app.query(abci.RequestQuery(data=b"name", prove=True))
    assert res.value == b"tpu"
    assert res.proof_ops and len(res.proof_ops) == 1
    prt = default_proof_runtime()
    prt.verify_value(res.proof_ops, root, str(KeyPath().append_key(b"name")), b"tpu")

    # unproven query has no ops
    res2 = app.query(abci.RequestQuery(data=b"name"))
    assert res2.proof_ops is None


def test_key_path_high_bytes_gowire_parity():
    """Raw high bytes must escape byte-wise (%FF), matching Go's
    url.PathEscape — a UTF-8 str round-trip would emit %C3%BF and break
    cross-implementation keypath interop (advisor finding r3)."""
    key = b"\xff\x00 high&/bytes"
    kp = KeyPath().append_key(key, KEY_ENCODING_URL)
    s = str(kp)
    assert "%FF" in s.upper()
    assert "%C3" not in s.upper()
    assert key_path_to_keys(s) == [key]
