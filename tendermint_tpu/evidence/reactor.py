"""Evidence reactor: gossips pending evidence on channel 0x38
(reference: evidence/reactor.go:16)."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List

from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor
from tendermint_tpu.types.evidence import DuplicateVoteEvidence, decode_evidence

logger = logging.getLogger("tendermint_tpu.evidence")

EVIDENCE_CHANNEL = 0x38
BROADCAST_SLEEP = 0.1


def encode_evidence_list(evs: List[DuplicateVoteEvidence]) -> bytes:
    w = pw.Writer()
    for ev in evs:
        w.message_field(1, ev.encode(), always=True)
    return w.bytes()


def decode_evidence_list(data: bytes) -> List[DuplicateVoteEvidence]:
    return [decode_evidence(v) for f, _, v in pw.Reader(data) if f == 1]


class EvidenceReactor(Reactor):
    def __init__(self, evpool):
        super().__init__("EVIDENCE")
        self.evpool = evpool
        self._peer_tasks: Dict[str, asyncio.Task] = {}
        # flipped by the overload controller at CRITICAL pressure: pending
        # evidence is re-offered once pressure clears, so pausing the walk
        # delays inclusion without losing anything
        self.shed = False

    def get_channels(self) -> List[ChannelDescriptor]:
        # sheddable: evidence gossip re-sends until ack'd by inclusion, so a
        # shed message is retried — safe to drop under overload (reference
        # maxMsgSize: evidence lists are bounded by consensus params)
        return [
            ChannelDescriptor(
                EVIDENCE_CHANNEL, priority=6, send_queue_capacity=10,
                recv_message_capacity=1_048_576, sheddable=True,
            )
        ]

    async def add_peer(self, peer) -> None:
        self._peer_tasks[peer.id] = asyncio.create_task(
            self._broadcast_routine(peer), name=f"ev-bcast-{peer.id[:8]}"
        )

    async def remove_peer(self, peer, reason) -> None:
        t = self._peer_tasks.pop(peer.id, None)
        if t:
            t.cancel()

    async def stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()
        self._peer_tasks.clear()

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        try:
            evs = decode_evidence_list(msg_bytes)
        except Exception as e:
            logger.error("bad evidence msg from %s: %s", peer.id[:10], e)
            await self.switch.stop_peer_for_error(peer, e)
            return
        from tendermint_tpu.evidence.pool import EvidenceWindowError

        import asyncio as _asyncio

        loop = _asyncio.get_running_loop()
        for ev in evs:
            try:
                # off-loop: gossiped evidence's signature checks ride the
                # scheduler's catch-up lane (idle-soak; see
                # EvidencePool._catchup_verifier), and that wait must park
                # an executor thread, never the consensus event loop
                await loop.run_in_executor(None, self.evpool.add_evidence, ev)
            except EvidenceWindowError as e:
                # benign race: honest peers with lagging/leading state offer
                # evidence outside OUR window — drop, never score
                logger.info("dropped out-of-window evidence from %s: %s", peer.id[:10], e)
            except Exception as e:
                # INVALID evidence (bad sigs, wrong set, forged powers) is
                # peer misconduct — it costs every receiver two signature
                # verifications; score it so a spammer eventually trips the
                # trust threshold (p2p/behaviour.py).
                logger.info("rejected evidence from %s: %s", peer.id[:10], e)
                try:
                    from tendermint_tpu.p2p.behaviour import (
                        BAD_MESSAGE,
                        PeerBehaviour,
                    )

                    await self.switch.reporter.report(
                        PeerBehaviour(peer.id, BAD_MESSAGE, f"bad evidence: {e}")
                    )
                except Exception:
                    pass

    async def _broadcast_routine(self, peer) -> None:
        """Periodically offer all pending evidence the peer may lack
        (reference: evidence/reactor.go broadcastEvidenceRoutine)."""
        sent: set = set()
        try:
            while True:
                if self.shed:
                    await asyncio.sleep(BROADCAST_SLEEP)
                    continue
                pending = self.evpool.pending_evidence(-1)
                fresh = [ev for ev in pending if ev.hash() not in sent]
                if fresh:
                    ok = await peer.send(EVIDENCE_CHANNEL, encode_evidence_list(fresh))
                    if ok:
                        sent.update(ev.hash() for ev in fresh)
                if len(sent) > 4096:
                    # bound the per-peer dedup set on a long-lived connection:
                    # evidence that left the pending set (committed/expired)
                    # no longer needs suppressing
                    sent &= {ev.hash() for ev in pending}
                await asyncio.sleep(BROADCAST_SLEEP)
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("evidence broadcast died for %s", peer.id[:10])
