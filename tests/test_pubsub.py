"""Query DSL + pubsub server (libs/pubsub.py; reference: libs/pubsub/query
query_test.go grammar cases, libs/pubsub/pubsub.go subscription policy)."""

import asyncio

import pytest

from tendermint_tpu.libs.pubsub import PubSubServer, Query


def ev(**kw):
    return {k.replace("__", "."): [str(v)] for k, v in kw.items()}


def test_query_equals_and_and():
    q = Query("tm.event = 'Tx' AND tx.height = 5")
    assert q.matches({"tm.event": ["Tx"], "tx.height": ["5"]})
    assert not q.matches({"tm.event": ["Tx"], "tx.height": ["6"]})
    assert not q.matches({"tm.event": ["NewBlock"], "tx.height": ["5"]})


def test_query_numeric_comparisons():
    q = Query("account.balance >= 100 AND account.balance < 200")
    assert q.matches({"account.balance": ["150"]})
    assert not q.matches({"account.balance": ["99"]})
    assert not q.matches({"account.balance": ["200"]})


def test_query_contains_exists():
    q = Query("tx.memo CONTAINS 'abc' AND tx.fee EXISTS")
    assert q.matches({"tx.memo": ["xxabcyy"], "tx.fee": ["1"]})
    assert not q.matches({"tx.memo": ["zz"], "tx.fee": ["1"]})
    assert not q.matches({"tx.memo": ["xxabcyy"]})


def test_query_time_comparisons():
    """TIME literals compare chronologically, not lexically/numerically
    (reference: libs/pubsub/query/query.go time conditions)."""
    q = Query("block.timestamp >= TIME 2013-05-03T14:45:00Z")
    assert q.matches({"block.timestamp": ["2013-05-03T14:45:01Z"]})
    assert q.matches({"block.timestamp": ["2014-01-01T00:00:00Z"]})
    assert not q.matches({"block.timestamp": ["2013-05-03T14:44:59Z"]})
    # offsets are honored: 15:45+01:00 == 14:45Z
    assert q.matches({"block.timestamp": ["2013-05-03T15:45:00+01:00"]})
    assert not q.matches({"block.timestamp": ["2013-05-03T15:44:59+01:00"]})
    # non-time attribute values simply don't match
    assert not q.matches({"block.timestamp": ["not-a-time"]})


def test_query_date_comparisons():
    q = Query("block.date = DATE 2013-05-03")
    assert q.matches({"block.date": ["2013-05-03"]})
    assert not q.matches({"block.date": ["2013-05-04"]})
    q2 = Query("block.date > DATE 2013-05-03")
    assert q2.matches({"block.date": ["2013-05-04"]})
    # a full timestamp on the same day is after midnight
    assert q2.matches({"block.date": ["2013-05-03T10:00:00Z"]})
    assert not q2.matches({"block.date": ["2013-05-03"]})


def test_query_time_rejects_bad_literals():
    with pytest.raises(ValueError):
        Query("a.b = TIME not-a-time")
    with pytest.raises(ValueError):
        Query("a.b = DATE 2013-13-90")


def test_pubsub_publish_and_slow_subscriber_cancel():
    async def run():
        srv = PubSubServer()
        sub = srv.subscribe("s1", Query("tm.event = 'Tx'"), out_capacity=2)
        srv.publish("d1", {"tm.event": ["Tx"]})
        srv.publish("ignored", {"tm.event": ["NewBlock"]})
        m = await sub.next()
        assert m.data == "d1"
        # overflow cancels the subscriber (reference: pubsub.go full-buffer policy)
        for _ in range(4):
            srv.publish("x", {"tm.event": ["Tx"]})
        assert sub.cancelled
        assert srv.num_client_subscriptions("s1") == 0

    asyncio.run(run())
