"""GenesisDoc (reference: types/genesis.go)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.crypto.keys import PubKey, pubkey_from_type_and_bytes
from tendermint_tpu.types.params import ConsensusParams, DEFAULT_CONSENSUS_PARAMS
from tendermint_tpu.types.validator_set import Validator

MAX_CHAIN_ID_LEN = 50


@dataclass
class GenesisValidator:
    pub_key: PubKey
    power: int
    name: str = ""
    address: bytes = b""

    def __post_init__(self):
        if not self.address:
            self.address = self.pub_key.address()


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: ConsensusParams = field(default_factory=lambda: DEFAULT_CONSENSUS_PARAMS)
    validators: List[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: bytes = b"{}"

    def validate_and_complete(self) -> None:
        """(reference: types/genesis.go ValidateAndComplete)"""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(f"chain_id in genesis doc is too long (max: {MAX_CHAIN_ID_LEN})")
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        self.consensus_params.validate_basic()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(f"the genesis file cannot contain validators with no voting power: {i}")
            if v.address and v.pub_key.address() != v.address:
                raise ValueError(f"incorrect address for validator {i}")

    def validator_hash(self) -> bytes:
        from tendermint_tpu.types.validator_set import ValidatorSet

        vs = ValidatorSet([Validator(v.pub_key, v.power) for v in self.validators])
        return vs.hash()

    def to_json(self) -> str:
        return json.dumps(
            {
                "genesis_time_ns": self.genesis_time_ns,
                "chain_id": self.chain_id,
                "initial_height": str(self.initial_height),
                "consensus_params": {
                    "block": {
                        "max_bytes": str(self.consensus_params.block.max_bytes),
                        "max_gas": str(self.consensus_params.block.max_gas),
                    },
                    "evidence": {
                        "max_age_num_blocks": str(self.consensus_params.evidence.max_age_num_blocks),
                        "max_age_duration_ns": str(self.consensus_params.evidence.max_age_duration_ns),
                        "max_bytes": str(self.consensus_params.evidence.max_bytes),
                    },
                    "validator": {
                        "pub_key_types": list(self.consensus_params.validator.pub_key_types)
                    },
                },
                "validators": [
                    {
                        "address": v.address.hex().upper(),
                        "pub_key": {
                            "type": v.pub_key.type_name(),
                            "value": v.pub_key.bytes().hex(),
                        },
                        "power": str(v.power),
                        "name": v.name,
                    }
                    for v in self.validators
                ],
                "app_hash": self.app_hash.hex().upper(),
                "app_state": json.loads(self.app_state.decode("utf-8") or "{}"),
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, data: str) -> "GenesisDoc":
        obj = json.loads(data)
        from tendermint_tpu.types.params import (
            BlockParams,
            EvidenceParams,
            ValidatorParams,
        )

        cp = obj.get("consensus_params", {})
        params = ConsensusParams(
            block=BlockParams(
                max_bytes=int(cp.get("block", {}).get("max_bytes", 22020096)),
                max_gas=int(cp.get("block", {}).get("max_gas", -1)),
            ),
            evidence=EvidenceParams(
                max_age_num_blocks=int(cp.get("evidence", {}).get("max_age_num_blocks", 100000)),
                max_age_duration_ns=int(
                    cp.get("evidence", {}).get("max_age_duration_ns", 48 * 3600 * 10**9)
                ),
                max_bytes=int(cp.get("evidence", {}).get("max_bytes", 1048576)),
            ),
            validator=ValidatorParams(
                pub_key_types=tuple(cp.get("validator", {}).get("pub_key_types", ["ed25519"]))
            ),
        )
        validators = []
        for v in obj.get("validators", []):
            pk = pubkey_from_type_and_bytes(v["pub_key"]["type"], bytes.fromhex(v["pub_key"]["value"]))
            validators.append(
                GenesisValidator(pub_key=pk, power=int(v["power"]), name=v.get("name", ""))
            )
        doc = cls(
            chain_id=obj["chain_id"],
            genesis_time_ns=int(obj.get("genesis_time_ns", 0)),
            initial_height=int(obj.get("initial_height", 1)),
            consensus_params=params,
            validators=validators,
            app_hash=bytes.fromhex(obj.get("app_hash", "")),
            app_state=json.dumps(obj.get("app_state", {})).encode(),
        )
        doc.validate_and_complete()
        return doc
