"""RLC (random-linear-combination) batch verification — the Pippenger MSM
fast path (ops/msm_jax.py + crypto/batch.py).

Differential-tested against the host reference implementation and the
per-signature kernel. Semantics under test: the RLC path must return the
SAME mask as per-signature verification in every case — directly when the
combined check passes, via fallback when it fails
(reference semantics: types/validator_set.go:680-702, one accept/reject per
signature).

Shapes are kept to the production lane buckets (Na=64 -> 128 lanes) so the
persistent compile cache is shared with real use.
"""

import pytest

pytestmark = [pytest.mark.kernel, pytest.mark.slow]  # heavy one-time
# compiles: excluded from the tier-1 budget lane (-m 'not slow'); run
# explicitly via -m kernel

import os

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "jax")

from tendermint_tpu.crypto import batch as B
from tendermint_tpu.crypto.keys import gen_ed25519


def make_batch(n, seed=0, msg_len=40):
    pubkeys, msgs, sigs = [], [], []
    for i in range(n):
        priv = gen_ed25519(bytes([seed]) * 31 + bytes([i]))
        msg = b"msm-%03d-" % i + b"x" * (msg_len - 8)
        pubkeys.append(priv.pub_key().bytes())
        msgs.append(msg)
        sigs.append(priv.sign(msg))
    return pubkeys, msgs, sigs


@pytest.fixture(scope="module", autouse=True)
def _free_compile_memory():
    """Same guard as tests/test_sharded.py: XLA aborted (SIGABRT inside
    compilation_cache.get_executable_and_time) deserializing this module's
    large RLC executables in a process already holding ~36 earlier kernel
    tests' executables (observed r5 full-lane run; passes standalone).
    Dropping accumulated executables first keeps the process under the
    ceiling — later tests reload from the persistent cache."""
    from tests.conftest import free_compile_memory

    free_compile_memory()
    yield


@pytest.fixture
def rlc_on(monkeypatch):
    monkeypatch.setattr(B, "RLC_MIN", 1)
    monkeypatch.setenv("TMTPU_RLC", "1")
    # the test env exposes 8 virtual CPU devices; disable mesh routing so the
    # RLC path (single-device production shape) is what runs
    monkeypatch.setenv("TMTPU_SHARDED", "0")
    B._A_CACHE.clear()


def test_rlc_all_valid_and_cached_path(rlc_on):
    pubkeys, msgs, sigs = make_batch(40)
    # first call: uncached kernel; fills the pubkey cache
    mask = B.verify_batch_jax(pubkeys, msgs, sigs)
    assert mask.all()
    assert all(B._cache_key(bytes(pk), "ed25519") in B._A_CACHE for pk in pubkeys)
    # second call: cached-A kernel; same verdict
    mask2 = B.verify_batch_jax(pubkeys, msgs, sigs)
    assert mask2.all()
    assert B.LAST_RLC_TIMINGS.get("cached") is True


def test_rlc_bad_sig_falls_back_to_exact_mask(rlc_on):
    pubkeys, msgs, sigs = make_batch(40)
    bad = bytearray(sigs[7])
    bad[3] ^= 0xFF
    sigs[7] = bytes(bad)
    mask = B.verify_batch_jax(pubkeys, msgs, sigs)
    expected = np.ones(40, dtype=bool)
    expected[7] = False
    assert (mask == expected).all()


def test_rlc_wrong_message_falls_back(rlc_on):
    pubkeys, msgs, sigs = make_batch(40)
    msgs[0] = b"tampered" + msgs[0][8:]
    msgs[13] = b"tampered" + msgs[13][8:]
    mask = B.verify_batch_jax(pubkeys, msgs, sigs)
    expected = np.ones(40, dtype=bool)
    expected[0] = expected[13] = False
    assert (mask == expected).all()


def test_rlc_invalid_encodings_and_precheck(rlc_on):
    pubkeys, msgs, sigs = make_batch(40)
    # non-canonical s (>= L): rejected host-side, excluded from the batch eq
    from tendermint_tpu.crypto.ed25519_ref import L

    s_big = (L + 5).to_bytes(32, "little")
    sigs[3] = sigs[3][:32] + s_big
    # invalid pubkey encoding (y >= p, not on curve)
    pubkeys[11] = b"\xff" * 32
    mask = B.verify_batch_jax(pubkeys, msgs, sigs)
    expected = np.ones(40, dtype=bool)
    expected[3] = expected[11] = False
    assert (mask == expected).all()


def test_rlc_matches_cpu_backend_on_mixed_validity(rlc_on):
    pubkeys, msgs, sigs = make_batch(40, seed=2)
    # corrupt a scattering of rows in different ways
    sigs[1] = sigs[2]  # signature for the wrong message/key
    msgs[20] = msgs[21]
    rng = np.random.default_rng(3)
    junk = bytes(rng.integers(0, 256, 64, dtype=np.uint8))
    sigs[39] = junk[:32] + (int.from_bytes(junk[32:], "little") % (1 << 250)).to_bytes(32, "little")
    got = B.verify_batch_jax(pubkeys, msgs, sigs)
    want = B.verify_batch_cpu(pubkeys, msgs, sigs)
    assert (got == want).all()


def make_mixed_batch(n, n_sr, seed=0, msg_len=40):
    """Interleaved ed25519/sr25519 rows (sr rows scattered, not a suffix)."""
    from tendermint_tpu.crypto.sr25519 import gen_sr25519

    pubkeys, msgs, sigs, types = [], [], [], []
    for i in range(n):
        sd = bytes([seed]) * 30 + bytes([i // 256, i % 256])
        msg = b"mix-%03d-" % i + b"y" * (msg_len - 8)
        if i % max(n // max(n_sr, 1), 1) == 1 and sum(
            1 for t in types if t == "sr25519"
        ) < n_sr:
            priv = gen_sr25519(sd)
            types.append("sr25519")
        else:
            priv = gen_ed25519(sd)
            types.append("ed25519")
        pubkeys.append(priv.pub_key().bytes())
        msgs.append(msg)
        sigs.append(priv.sign(msg))
    return pubkeys, msgs, sigs, types


@pytest.mark.heavy
def test_rlc_mixed_all_valid_device_path(rlc_on):
    pubkeys, msgs, sigs, types = make_mixed_batch(40, 10)
    mask = B.verify_batch(pubkeys, msgs, sigs, backend="jax", key_types=types)
    assert mask.all()
    assert B.LAST_JAX_PATH[0] == "rlc-mixed"
    assert B.LAST_RLC_TIMINGS.get("mode") == "mixed"
    # sr keys landed in the typed cache
    for pk, t in zip(pubkeys, types):
        assert B._cache_key(bytes(pk), t) in B._A_CACHE


@pytest.mark.heavy
def test_rlc_mixed_bad_rows_fall_back_to_exact_mask(rlc_on):
    pubkeys, msgs, sigs, types = make_mixed_batch(40, 10, seed=3)
    sr_rows = [i for i, t in enumerate(types) if t == "sr25519"]
    ed_rows = [i for i, t in enumerate(types) if t == "ed25519"]
    bad_sr, bad_ed = sr_rows[2], ed_rows[5]
    sigs[bad_sr] = sigs[bad_sr][:33] + bytes([sigs[bad_sr][33] ^ 1]) + sigs[bad_sr][34:]
    msgs[bad_ed] = b"tampered" + msgs[bad_ed][8:]
    mask = B.verify_batch(pubkeys, msgs, sigs, backend="jax", key_types=types)
    expected = np.ones(40, dtype=bool)
    expected[bad_sr] = expected[bad_ed] = False
    assert (mask == expected).all()


def test_rlc_mixed_matches_host_verifiers(rlc_on):
    from tendermint_tpu.crypto.keys import Ed25519PubKey
    from tendermint_tpu.crypto.sr25519 import sr25519_verify

    pubkeys, msgs, sigs, types = make_mixed_batch(32, 8, seed=5)
    # corrupt: sr sig without marker bit, ed invalid pubkey, swapped messages
    sr_rows = [i for i, t in enumerate(types) if t == "sr25519"]
    i0 = sr_rows[0]
    sigs[i0] = sigs[i0][:63] + bytes([sigs[i0][63] & 0x7F])  # clear marker
    msgs[2], msgs[3] = msgs[3], msgs[2]
    got = B.verify_batch(pubkeys, msgs, sigs, backend="jax", key_types=types)
    for i in range(32):
        if types[i] == "ed25519":
            want = Ed25519PubKey(bytes(pubkeys[i])).verify(bytes(msgs[i]), bytes(sigs[i]))
        else:
            want = sr25519_verify(bytes(pubkeys[i]), bytes(msgs[i]), bytes(sigs[i]))
        assert got[i] == want, (i, types[i])


def test_rlc_accepts_pure_torsion_defect_no_fallback(rlc_on):
    """The RLC batch equation is cofactored: a signature whose only defect
    is small torsion in R passes the combined check directly (no per-sig
    fallback), agreeing with the per-sig kernel and the host wrapper —
    the single framework predicate (advisor r3 medium)."""
    from tests.sigutil import torsion_defect_sig

    pubkeys, msgs, sigs = make_batch(12)
    a_enc, msg, sig = torsion_defect_sig(seed=11, msg=b"rlc-torsion-agreement")
    pubkeys.append(a_enc)
    msgs.append(msg)
    sigs.append(sig)
    mask = B.verify_batch_jax(pubkeys, msgs, sigs)
    assert mask.all()
    assert B.LAST_JAX_PATH[0] == "rlc"  # combined check passed, no fallback


def test_device_sort_matches_host_sort():
    """sort_windows_device must produce identical `ends` and a
    bucket-equivalent `perm` (same lane SET per digit bucket — intra-bucket
    order is free, bucket sums are commutative)."""
    import jax

    from tendermint_tpu.ops import msm_jax

    rng = np.random.default_rng(21)
    for n in (5, 130, 1024):
        digits = rng.integers(0, 256, size=(n, msm_jax.NWIN), dtype=np.uint8)
        perm_h, ends_h = msm_jax.sort_windows(digits)
        perm_d, ends_d = jax.jit(msm_jax.sort_windows_device)(digits)
        perm_d, ends_d = np.asarray(perm_d), np.asarray(ends_d)
        assert (ends_d == ends_h.astype(np.int64)).all()
        for w in range(msm_jax.NWIN):
            # same multiset of lanes inside every bucket
            start = 0
            for v in range(msm_jax.NBUCKETS):
                end = ends_h[w, v]
                assert set(perm_h[w, start:end].tolist()) == set(
                    perm_d[w, start:end].tolist()
                ), (w, v)
                start = end


def test_rlc_device_sort_variant_matches_host_sort_variant(rlc_on, monkeypatch):
    """The dsort kernel (digits in, sort in-graph) and the host-sorted kernel
    return the same packed verdict on valid and tampered batches — and the
    dsort kernel's ACCEPT path works (no silent always-fallback: a valid
    batch must pass the combined check, not fall back per-sig)."""
    pubkeys, msgs, sigs = make_batch(24, seed=3)

    # valid batch first: the device-sorted combined check itself must accept
    monkeypatch.setenv("TMTPU_DEVICE_SORT", "1")
    B._A_CACHE.clear()
    B.verify_batch_jax(pubkeys, msgs, sigs)  # fill A cache
    mask = B.verify_batch_jax(pubkeys, msgs, sigs)
    assert mask.all()
    assert B.LAST_JAX_PATH[0] == "rlc", B.LAST_JAX_PATH

    sigs[5] = sigs[5][:10] + bytes([sigs[5][10] ^ 1]) + sigs[5][11:]
    masks = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("TMTPU_DEVICE_SORT", flag)
        B._A_CACHE.clear()
        B.verify_batch_jax(pubkeys, msgs, sigs)  # fill A cache
        masks[flag] = B.verify_batch_jax(pubkeys, msgs, sigs)  # cached path
    assert (masks["1"] == masks["0"]).all()
    assert not masks["1"][5] and masks["1"].sum() == 23
