"""AOT artifact cache: cold-start without retracing.

The RLC kernels trace to ~400k jaxpr equations (every Pallas call site
inlines its kernel body), so a FRESH PROCESS pays ~70 s of pure Python
tracing/lowering per (kernel, shape bucket) — even when XLA's persistent
compile cache HITS (measured r4: 71 s first call on a cache hit, 27 s of
which was XLA; the rest tracing). jax.export solves this: the traced+
lowered StableHLO is serialized to disk once, and later processes
deserialize and call it directly — no tracing.

Artifacts live in .jax_cache/export/, keyed by kernel name + arg
shapes/dtypes + a hash of the kernel source files (so any kernel edit
invalidates them). XLA compilation of a deserialized artifact still goes
through the persistent compile cache, so a warm machine pays only
deserialize + device program load."""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
import time
from typing import Callable, Dict

import jax
import numpy as np

_LOCK = threading.Lock()
_MEM: Dict[str, Callable] = {}
_KEY_LOCKS: Dict[str, object] = {}
_SRC_HASH: str | None = None


def _src_hash() -> str:
    """Hash of the kernel-defining sources: edits invalidate artifacts."""
    global _SRC_HASH
    if _SRC_HASH is None:
        h = hashlib.sha256()
        base = os.path.dirname(os.path.abspath(__file__))
        for mod in (
            "fe25519.py",
            "ed25519_jax.py",
            "msm_jax.py",
            "pallas_fe.py",
            "pallas_msm.py",  # fused-pipeline kernels (traced into *_f keys)
            "ristretto_jax.py",  # traced into the mixed kernel
        ):
            with open(os.path.join(base, mod), "rb") as f:
                h.update(f.read())
        h.update(jax.__version__.encode())
        _SRC_HASH = h.hexdigest()[:16]
    return _SRC_HASH


def _machine_key() -> str:
    """Host machine fingerprint component of artifact keys. An artifact's
    first CALL compiles through XLA's persistent cache, whose CPU entries
    bake in host CPU features — loading a foreign-machine artifact then
    fails in cpu_aot_loader (the failure that killed every MULTICHIP round,
    MULTICHIP_r05.json). Keying on the fingerprint makes a foreign artifact
    a MISS — skipped and re-exported — never loaded. TPU programs are
    host-portable, so only the backend that compiles for the host CPU is
    scoped."""
    if jax.default_backend() != "cpu":
        return "anyhost"
    from tendermint_tpu.ops.cache_hardening import machine_fingerprint

    return machine_fingerprint()


def _cache_dir() -> str | None:
    d = jax.config.jax_compilation_cache_dir or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR"
    )
    if not d:
        return None
    return os.path.join(d, "export")


def _arg_key(args) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(args):
        h.update(str(np.shape(leaf)).encode())
        h.update(str(np.asarray(leaf).dtype if not hasattr(leaf, "dtype") else leaf.dtype).encode())
    return h.hexdigest()[:16]


_REGISTERED = False


def _register_pytrees() -> None:
    """The kernel arg NamedTuples must be registered for export
    serialization (once per process)."""
    global _REGISTERED
    if _REGISTERED:
        return
    from jax import export as jexport

    from tendermint_tpu.ops.ed25519_jax import FieldCtx
    from tendermint_tpu.ops.msm_jax import SmallCtx

    for t in (FieldCtx, SmallCtx):
        try:
            jexport.register_namedtuple_serialization(
                t, serialized_name=f"tendermint_tpu.{t.__name__}"
            )
        except ValueError:
            pass  # already registered
    _REGISTERED = True


def enabled() -> bool:
    # CPU included since r5: the test suite's kernel lane was retracing
    # ~400k-eq jaxprs in every process (the dominant cost of `pytest -m
    # kernel` — XLA compiles were already persistent-cached); export
    # artifacts are keyed per backend so CPU and TPU never collide.
    return os.environ.get("TMTPU_AOT", "1") != "0"


def call(name: str, jit_fn, *args):
    """Call `jit_fn(*args)` through the AOT artifact cache.

    First use on a machine: traces + exports + serializes (background cost,
    same as before). Later processes: deserialize (~1 s) instead of
    retracing (~70 s). Falls back to the plain jit call on any export
    machinery failure."""
    if not enabled():
        return jit_fn(*args)
    key = (
        f"{name}-{jax.default_backend()}-{_machine_key()}-"
        f"{_src_hash()}-{_arg_key(args)}"
    )
    fn = _MEM.get(key)
    if fn is not None:
        return fn(*args)
    # per-key in-flight guard: the prewarm thread and the event loop must
    # not both pay the ~70s export trace for the same kernel
    with _LOCK:
        klock = _KEY_LOCKS.setdefault(key, __import__("threading").Lock())
    with klock:
        fn = _MEM.get(key)
        if fn is not None:
            return fn(*args)
        return _call_locked(name, key, jit_fn, *args)


def _record_aot(result: str) -> None:
    """Artifact-cache outcome into the mesh telemetry (hit / miss /
    corrupt): machine-scoped keys mean a foreign host's artifacts surface
    here as misses instead of the cpu_aot_loader failures that killed
    MULTICHIP r04/r05 — the counter is how a round proves which it was."""
    try:
        from tendermint_tpu.parallel import telemetry as _mesh_tm

        _mesh_tm.record_aot(result)
    except Exception:  # telemetry must never fail a kernel call
        pass


def _call_locked(name, key, jit_fn, *args):
    from tendermint_tpu.libs import trace as _trace

    try:
        from jax import export as jexport

        _register_pytrees()
        d = _cache_dir()
        path = os.path.join(d, key + ".bin") if d else None
        exp = None
        corrupt = False
        if path and os.path.exists(path):
            try:
                _t0 = time.perf_counter()
                with open(path, "rb") as f:
                    exp = jexport.deserialize(bytearray(f.read()))
                _trace.record_compile(
                    name, time.perf_counter() - _t0, "deserialize"
                )
                _record_aot("hit")
            except Exception:
                # Corrupted artifact: delete it and fall through to a fresh
                # export — permanently disabling the AOT path for this key
                # (the old behavior) made every future process repay both
                # the failed deserialize AND the ~70 s retrace.
                import logging

                logging.getLogger("tendermint_tpu.ops.aot").warning(
                    "corrupt AOT artifact %s; deleting and re-exporting", path
                )
                try:
                    os.unlink(path)
                except OSError:
                    pass
                _record_aot("corrupt")
                exp = None
                corrupt = True
        if exp is None:
            if not corrupt:
                # hit/miss/corrupt are disjoint outcomes per call — a
                # corrupt artifact is NOT also a miss
                _record_aot("miss")
            _t0 = time.perf_counter()
            exp = jexport.export(jit_fn)(*args)
            # trace+lower+export wall time — the "compile" half of the
            # compile-vs-execute split (XLA's own compile of the artifact
            # happens inside the first wrapped call, below)
            _trace.record_compile(name, time.perf_counter() - _t0, "export")
            if path:
                os.makedirs(d, exist_ok=True)
                blob = exp.serialize()
                fd, tmp = tempfile.mkstemp(dir=d, prefix=".aot-")
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
        wrapped = jax.jit(exp.call)
    except Exception:
        import logging

        logging.getLogger("tendermint_tpu.ops.aot").exception(
            "AOT export cache failed for %s; using plain jit", name
        )
        with _LOCK:
            _MEM[key] = jit_fn
        return jit_fn(*args)
    with _LOCK:
        _MEM[key] = wrapped
    # Outside the try: a RUNTIME error here (device OOM, transient tunnel
    # failure) must propagate as itself, not be mislabeled as an export
    # failure and permanently disable the AOT path for this key.
    _t0 = time.perf_counter()
    out = wrapped(*args)
    # The first call pays XLA compilation (or persistent-cache load) of the
    # artifact; recorded as its own kind so compile-vs-execute splits stay
    # honest — later calls on this key skip _call_locked entirely.
    _trace.record_compile(name, time.perf_counter() - _t0, "first_call")
    return out
