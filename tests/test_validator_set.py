"""ValidatorSet: proposer rotation, updates, batched commit verification."""

from fractions import Fraction

import pytest

from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.types.basic import BlockID, BlockIDFlag, PartSetHeader, SignedMsgType
from tendermint_tpu.types.block import CommitSig
from tendermint_tpu.types.validator_set import (
    CommitVerifyError,
    NotEnoughVotingPowerError,
    Validator,
    ValidatorSet,
)
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import ConflictingVotesError, VoteSet

CHAIN = "test-chain"
BID = BlockID(hash=b"\xaa" * 32, part_set_header=PartSetHeader(total=2, hash=b"\xbb" * 32))


def make_vals(n, power=10):
    privs = [gen_ed25519(bytes([i + 1]) * 32) for i in range(n)]
    vals = [Validator(p.pub_key(), power) for p in privs]
    vs = ValidatorSet(vals)
    # map privs to sorted order
    by_addr = {p.pub_key().address(): p for p in privs}
    sorted_privs = [by_addr[v.address] for v in vs.validators]
    return vs, sorted_privs


def test_sorting_and_lookup():
    vs, privs = make_vals(5)
    addrs = [v.address for v in vs.validators]
    assert addrs == sorted(addrs)  # equal power -> sorted by address
    idx, val = vs.get_by_address(addrs[2])
    assert idx == 2 and val.address == addrs[2]
    assert vs.total_voting_power() == 50
    assert vs.has_address(addrs[0]) and not vs.has_address(b"\x00" * 20)


def test_proposer_rotation_equal_power():
    vs, _ = make_vals(4)
    seen = []
    for _ in range(8):
        vs.increment_proposer_priority(1)
        seen.append(vs.get_proposer().address)
    # with equal power every validator proposes once per 4 rounds
    assert set(seen[:4]) == set(v.address for v in vs.validators)
    assert seen[:4] == seen[4:8]


def test_proposer_weighted():
    a = gen_ed25519(b"\x01" * 32).pub_key()
    b = gen_ed25519(b"\x02" * 32).pub_key()
    vs = ValidatorSet([Validator(a, 3), Validator(b, 1)])
    counts = {}
    for _ in range(40):
        vs.increment_proposer_priority(1)
        addr = vs.get_proposer().address
        counts[addr] = counts.get(addr, 0) + 1
    assert counts[a.address()] == 30
    assert counts[b.address()] == 10


def test_priorities_centered():
    vs, _ = make_vals(7, power=100)
    for _ in range(50):
        vs.increment_proposer_priority(1)
    total = sum(v.proposer_priority for v in vs.validators)
    # centered around zero, bounded by 2*total power window
    assert abs(total) <= vs.total_voting_power() * 2 * len(vs.validators)


def test_copy_increment_does_not_mutate():
    vs, _ = make_vals(3)
    before = [(v.address, v.proposer_priority) for v in vs.validators]
    vs2 = vs.copy_increment_proposer_priority(3)
    after = [(v.address, v.proposer_priority) for v in vs.validators]
    assert before == after
    assert vs2 is not vs


def test_updates_add_remove():
    vs, _ = make_vals(3, power=10)
    new_priv = gen_ed25519(b"\x09" * 32)
    vs.update_with_change_set([Validator(new_priv.pub_key(), 5)])
    assert vs.size() == 4
    assert vs.total_voting_power() == 35
    # new validator got the -1.125*total penalty -> not immediately proposer
    _, nv = vs.get_by_address(new_priv.pub_key().address())
    assert nv.voting_power == 5
    # remove it
    vs.update_with_change_set([Validator(new_priv.pub_key(), 0)])
    assert vs.size() == 3 and vs.total_voting_power() == 30
    # removing an unknown validator errors
    with pytest.raises(ValueError, match="failed to find"):
        vs.update_with_change_set([Validator(new_priv.pub_key(), 0)])
    # power update
    target = vs.validators[0]
    vs.update_with_change_set([Validator(target.pub_key, 42)])
    assert vs.total_voting_power() == 42 + 20


def test_hash_changes_with_set():
    vs, _ = make_vals(3)
    h1 = vs.hash()
    vs.update_with_change_set([Validator(gen_ed25519(b"\x0a" * 32).pub_key(), 7)])
    assert vs.hash() != h1


def _signed_commit(vs, privs, height=5, round_=0, block_id=BID, nil_idx=(), absent_idx=(), bad_idx=()):
    sigs = []
    for i, (val, priv) in enumerate(zip(vs.validators, privs)):
        if i in absent_idx:
            sigs.append(CommitSig.absent_sig())
            continue
        bid = BlockID() if i in nil_idx else block_id
        flag = BlockIDFlag.NIL if i in nil_idx else BlockIDFlag.COMMIT
        v = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=height,
            round=round_,
            block_id=bid,
            timestamp_ns=1000 + i,
            validator_address=val.address,
            validator_index=i,
        )
        sig = priv.sign(v.sign_bytes(CHAIN))
        if i in bad_idx:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        sigs.append(CommitSig(flag, val.address, v.timestamp_ns, sig))
    from tendermint_tpu.types.block import Commit

    return Commit(height, round_, block_id, tuple(sigs))


def test_verify_commit_ok():
    vs, privs = make_vals(6)
    commit = _signed_commit(vs, privs)
    vs.verify_commit(CHAIN, BID, 5, commit)
    vs.verify_commit_light(CHAIN, BID, 5, commit)
    vs.verify_commit_light_trusting(CHAIN, commit, Fraction(1, 3))


def test_verify_commit_with_nil_and_absent():
    vs, privs = make_vals(6)
    commit = _signed_commit(vs, privs, nil_idx=(1,))
    vs.verify_commit(CHAIN, BID, 5, commit)  # 5/6 voting for block > 2/3
    # exactly 2/3 (4 of 6) is NOT enough: threshold is strict
    commit2 = _signed_commit(vs, privs, nil_idx=(1,), absent_idx=(2,))
    with pytest.raises(NotEnoughVotingPowerError):
        vs.verify_commit(CHAIN, BID, 5, commit2)


def test_verify_commit_insufficient_power():
    vs, privs = make_vals(6)
    commit = _signed_commit(vs, privs, nil_idx=(0, 1), absent_idx=(2,))
    with pytest.raises(NotEnoughVotingPowerError):
        vs.verify_commit(CHAIN, BID, 5, commit)


def test_verify_commit_bad_signature():
    vs, privs = make_vals(4)
    commit = _signed_commit(vs, privs, bad_idx=(3,))
    with pytest.raises(CommitVerifyError, match="wrong signature"):
        vs.verify_commit(CHAIN, BID, 5, commit)


def test_verify_commit_wrong_height_blockid_size():
    vs, privs = make_vals(4)
    commit = _signed_commit(vs, privs)
    with pytest.raises(CommitVerifyError, match="height"):
        vs.verify_commit(CHAIN, BID, 6, commit)
    other = BlockID(hash=b"\xee" * 32, part_set_header=PartSetHeader(1, b"\xff" * 32))
    with pytest.raises(CommitVerifyError, match="block ID"):
        vs.verify_commit(CHAIN, other, 5, commit)
    small, _ = make_vals(3)
    with pytest.raises(CommitVerifyError, match="set size"):
        small.verify_commit(CHAIN, BID, 5, commit)


def test_verify_commit_light_trusting_different_set():
    vs, privs = make_vals(6)
    commit = _signed_commit(vs, privs)
    # trusted set = subset with extra unknown validator
    extra = Validator(gen_ed25519(b"\x0b" * 32).pub_key(), 10)
    trusted = ValidatorSet([Validator(v.pub_key, v.voting_power) for v in vs.validators[:4]] + [extra])
    trusted.verify_commit_light_trusting(CHAIN, commit, Fraction(1, 3))
    with pytest.raises(NotEnoughVotingPowerError):
        trusted.verify_commit_light_trusting(CHAIN, commit, Fraction(9, 10))


def test_vote_set_two_thirds():
    vs, privs = make_vals(4)
    vote_set = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vs)
    for i, (val, priv) in enumerate(zip(vs.validators, privs)):
        v = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=5,
            round=0,
            block_id=BID,
            timestamp_ns=1000,
            validator_address=val.address,
            validator_index=i,
        )
        v = v.with_signature(priv.sign(v.sign_bytes(CHAIN)))
        assert vote_set.add_vote(v)
        if i < 2:
            assert not vote_set.has_two_thirds_majority()
    assert vote_set.has_two_thirds_majority()
    assert vote_set.two_thirds_majority() == BID
    commit = vote_set.make_commit()
    vs.verify_commit(CHAIN, BID, 5, commit)


def test_vote_set_rejects_invalid():
    vs, privs = make_vals(3)
    vote_set = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vs)
    val, priv = vs.validators[0], privs[0]
    v = Vote(
        type=SignedMsgType.PRECOMMIT,
        height=5,
        round=0,
        block_id=BID,
        timestamp_ns=0,
        validator_address=val.address,
        validator_index=0,
    )
    signed = v.with_signature(priv.sign(v.sign_bytes(CHAIN)))
    # wrong height
    import dataclasses

    from tendermint_tpu.types.vote_set import VoteSetError

    with pytest.raises(VoteSetError, match="expected"):
        vote_set.add_vote(dataclasses.replace(signed, height=6))
    # bad signature
    with pytest.raises(VoteSetError, match="invalid signature"):
        vote_set.add_vote(v.with_signature(b"\x00" * 64))
    # good vote then duplicate
    assert vote_set.add_vote(signed)
    assert not vote_set.add_vote(signed)


def test_vote_set_conflict_detection():
    vs, privs = make_vals(3)
    vote_set = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vs)
    val, priv = vs.validators[0], privs[0]

    def mk(bid):
        v = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=5,
            round=0,
            block_id=bid,
            timestamp_ns=0,
            validator_address=val.address,
            validator_index=0,
        )
        return v.with_signature(priv.sign(v.sign_bytes(CHAIN)))

    assert vote_set.add_vote(mk(BID))
    other = BlockID(hash=b"\xcc" * 32, part_set_header=PartSetHeader(1, b"\xdd" * 32))
    with pytest.raises(ConflictingVotesError):
        vote_set.add_vote(mk(other))


def test_vote_set_deferred_batch_flush():
    vs, privs = make_vals(4)
    vote_set = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vs, defer_verification=True)
    for i, (val, priv) in enumerate(zip(vs.validators, privs)):
        v = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=5,
            round=0,
            block_id=BID,
            timestamp_ns=0,
            validator_address=val.address,
            validator_index=i,
        )
        sig = priv.sign(v.sign_bytes(CHAIN))
        if i == 2:
            sig = bytes([sig[0] ^ 1]) + sig[1:]  # corrupt one
        vote_set.add_vote(v.with_signature(sig))
    assert not vote_set.has_two_thirds_majority()  # nothing committed yet
    committed, failed = vote_set.flush()
    assert failed == [2]
    assert len(committed) == 3  # the valid votes, published only now
    assert vote_set.has_two_thirds_majority()  # 3/4 valid > 2/3


def test_vote_set_deferred_detects_equivocation():
    vs, privs = make_vals(4)
    vote_set = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vs, defer_verification=True)
    val, priv = vs.validators[0], privs[0]

    def mk(bid, i=0):
        v = Vote(
            type=SignedMsgType.PRECOMMIT, height=5, round=0, block_id=bid,
            timestamp_ns=0, validator_address=vs.validators[i].address, validator_index=i,
        )
        return v.with_signature(privs[i].sign(v.sign_bytes(CHAIN)))

    other = BlockID(hash=b"\xcc" * 32, part_set_header=PartSetHeader(1, b"\xdd" * 32))
    v1, v2 = mk(BID), mk(other)
    assert vote_set.add_vote(v1)
    assert not vote_set.add_vote(v1)  # duplicate detected while pending
    assert vote_set.add_vote(v2) == "pending"  # queued; conflict surfaces at flush
    committed, failed = vote_set.flush()
    assert failed == []
    conflicts = vote_set.pop_conflicts()
    assert len(conflicts) == 1
    assert {conflicts[0].vote_a.block_id, conflicts[0].vote_b.block_id} == {BID, other}
    assert vote_set.pop_conflicts() == []


def test_vote_set_peer_maj23_tracks_conflicting_votes():
    # Mirrors reference behavior: a conflicting vote for a peer-claimed-maj23
    # block is still tallied under that block and can produce the 2/3 majority.
    vs, privs = make_vals(4)
    vote_set = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vs)

    def mk(i, bid):
        v = Vote(
            type=SignedMsgType.PRECOMMIT, height=5, round=0, block_id=bid,
            timestamp_ns=0, validator_address=vs.validators[i].address, validator_index=i,
        )
        return v.with_signature(privs[i].sign(v.sign_bytes(CHAIN)))

    nil = BlockID()
    vote_set.set_peer_maj23("peer1", BID)
    # validator 0 votes nil first, then equivocates with a vote for BID
    assert vote_set.add_vote(mk(0, nil))
    with pytest.raises(ConflictingVotesError):
        vote_set.add_vote(mk(0, BID))
    # the conflicting vote was tracked under BID: it counts toward the 2/3,
    # so only 2 more votes are needed (10+10+10 = 30 > 2/3*40)
    assert vote_set.add_vote(mk(1, BID))
    assert not vote_set.has_two_thirds_majority()
    assert vote_set.add_vote(mk(2, BID))
    assert vote_set.has_two_thirds_majority()
    assert vote_set.two_thirds_majority() == BID


def test_update_with_change_set_does_not_mutate_caller():
    vs, _ = make_vals(3)
    new_val = Validator(gen_ed25519(b"\x0c" * 32).pub_key(), 5)
    assert new_val.proposer_priority == 0
    vs.update_with_change_set([new_val])
    assert new_val.proposer_priority == 0  # caller's object untouched


def test_vote_set_deferred_flush_mixed_key_types():
    """Deferred flush must verify each vote under ITS key type: an sr25519
    vote checked as ed25519 always fails (marker bit forces s >= L), which
    would silently drop valid votes — a liveness break in mixed sets
    (advisor r3 medium; mirrors validator_set batched Verify*)."""
    from tendermint_tpu.crypto.sr25519 import gen_sr25519

    privs = [gen_ed25519(bytes([i + 1]) * 32) for i in range(3)] + [
        gen_sr25519(b"\x77" * 32)
    ]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    sorted_privs = [by_addr[v.address] for v in vs.validators]
    vote_set = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vs, defer_verification=True)
    for i, (val, priv) in enumerate(zip(vs.validators, sorted_privs)):
        v = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=5,
            round=0,
            block_id=BID,
            timestamp_ns=0,
            validator_address=val.address,
            validator_index=i,
        )
        vote_set.add_vote(v.with_signature(priv.sign(v.sign_bytes(CHAIN))))
    committed, failed = vote_set.flush()
    assert failed == []
    assert len(committed) == 4  # the sr25519 vote survives the deferred path
