"""End-to-end: single-validator node produces blocks against the kvstore app.

This is the 'minimum end-to-end slice' (SURVEY.md §7.6): every commit flows
through consensus (propose → prevote → precommit → commit) with real
signatures, the WAL, the block store, and ABCI."""

import asyncio
import os

import pytest

from tendermint_tpu.abci import types as abci_types
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import test_config
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

# Host-path verification for consensus votes in these tests (1 validator);
# the batched TPU path is exercised by test_validator_set/test_ed25519_jax.
os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")


def make_node(tmp_path, n_blocks_app=None, root=None):
    cfg = test_config()
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = ""  # no RPC in this test
    cfg.root_dir = ""
    if root:
        cfg.root_dir = str(root)
        cfg.base.db_backend = "sqlite"
    priv = FilePV(gen_ed25519(b"\x42" * 32))
    gen = GenesisDoc(
        chain_id="e2e-chain",
        validators=[GenesisValidator(priv.get_pub_key(), 10)],
    )
    app = KVStoreApplication()
    node = Node(cfg, gen, priv_validator=priv, app=app)
    # WAL in tmp
    return node


@pytest.fixture
def anyio_backend():
    return "asyncio"


def test_single_node_produces_blocks(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)

    async def run():
        node = make_node(tmp_path)
        await node.start()
        try:
            await node.wait_for_height(3, timeout=30)
            assert node.block_store.height >= 3
            # blocks are linked
            b2 = node.block_store.load_block(2)
            b3 = node.block_store.load_block(3)
            assert b3.header.last_block_id.hash == b2.hash()
            # commits verify against the validator set
            commit = node.block_store.load_seen_commit(3)
            meta = node.block_store.load_block_meta(3)
            vals = node.state_store.load_validators(3)
            vals.verify_commit("e2e-chain", meta[0], 3, commit)
        finally:
            await node.stop()

    asyncio.run(run())


def test_node_commits_txs(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)

    async def run():
        node = make_node(tmp_path)
        await node.start()
        try:
            await node.wait_for_height(1, timeout=30)
            res = node.mempool.check_tx(b"name=satoshi")
            assert res.code == abci_types.CODE_TYPE_OK
            # wait until the tx lands in a block
            deadline = asyncio.get_event_loop().time() + 20
            committed = None
            while asyncio.get_event_loop().time() < deadline:
                for h in range(1, node.block_store.height + 1):
                    block = node.block_store.load_block(h)
                    if block and b"name=satoshi" in block.txs:
                        committed = h
                        break
                if committed:
                    break
                await asyncio.sleep(0.05)
            assert committed, "tx never committed"
            # app state reflects the tx
            res = node.proxy_app.query.query(
                abci_types.RequestQuery(data=b"name", path="/store")
            )
            assert res.value == b"satoshi"
            # mempool no longer has it
            assert node.mempool.size() == 0
            # tx was indexed
            from tendermint_tpu.crypto import tmhash

            await asyncio.sleep(0.2)  # indexer is async
            assert node.tx_indexer.get(tmhash.sum256(b"name=satoshi")) is not None
        finally:
            await node.stop()

    asyncio.run(run())


def test_node_restart_resumes(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    root = tmp_path / "node_home"
    (root / "data").mkdir(parents=True)

    async def run1():
        node = make_node(tmp_path, root=root)
        await node.start()
        try:
            await node.wait_for_height(2, timeout=30)
            return node.block_store.height
        finally:
            await node.stop()

    h1 = asyncio.run(run1())
    assert h1 >= 2

    async def run2():
        node = make_node(tmp_path, root=root)
        # handshake must have synced state with store
        assert node.state.last_block_height == node.block_store.height
        assert node.block_store.height >= h1
        await node.start()
        try:
            await node.wait_for_height(h1 + 2, timeout=30)
        finally:
            await node.stop()

    asyncio.run(run2())
