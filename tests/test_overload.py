"""Overload-protection unit/integration coverage (ISSUE 5): the p2p inbound
token buckets (votes NEVER shed — the vote-path guard), per-channel recv
capacity, the RPC load gate + structured mempool errors + 429s, the node
overload controller's pressure machine, and ABCI reconnect-with-backoff
through an app restart. Runs without the `cryptography` wheel or TPUs."""

import asyncio
import os
import time
from types import SimpleNamespace

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.config.config import test_config
from tendermint_tpu.libs import metrics as M
from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.p2p.conn.connection import (
    ChannelDescriptor,
    MConnection,
    RecvRateLimit,
    TokenBucket,
)

VOTE_CH = 0x22
MEMPOOL_CH = 0x30


# ---------------------------------------------------------------------------
# token bucket


def test_token_bucket_burst_then_refuse():
    tb = TokenBucket(bytes_per_s=100, msgs_per_s=0)
    assert tb.admit(60)
    assert not tb.admit(60)  # only ~40 credit left
    assert tb.admit(30)


def test_token_bucket_msg_budget():
    tb = TokenBucket(bytes_per_s=0, msgs_per_s=2)
    assert tb.admit(1)
    assert tb.admit(1)
    assert not tb.admit(1)


def test_token_bucket_refills_but_never_banks_past_one_window():
    tb = TokenBucket(bytes_per_s=1000, msgs_per_s=0)
    assert tb.admit(1000)
    assert not tb.admit(10)
    time.sleep(0.05)  # ~50 tokens back
    assert tb.admit(20)
    # idle "forever": credit caps at one window's worth
    tb._ts -= 3600.0
    assert tb.admit(1000)
    assert not tb.admit(200)


def test_token_bucket_admits_message_larger_than_burst():
    """A message bigger than one second of byte budget must still pass from
    a full bucket (else a max-size tx on a budget == its own size is
    PERMANENTLY inadmissible); the balance goes negative and subsequent
    messages are shed until refill pays it back."""
    tb = TokenBucket(bytes_per_s=1000, msgs_per_s=0)
    assert tb.admit(5000)  # full bucket: oversize admitted
    assert not tb.admit(10)  # deep in debt now
    tb._ts -= 10.0  # refill time elapses (credit caps at one window)
    assert tb.admit(10)


def test_token_bucket_zero_rates_disable():
    tb = TokenBucket(bytes_per_s=0, msgs_per_s=0)
    for _ in range(1000):
        assert tb.admit(1 << 20)


# ---------------------------------------------------------------------------
# MConnection shed path


class _NullTransport:
    async def write(self, data):
        pass

    async def read(self, n):
        raise NotImplementedError

    def close(self):
        pass


def _packet_env(chan_id: int, data: bytes) -> bytes:
    body = pw.Writer()
    body.varint_field(1, chan_id)
    body.varint_field(2, 1)  # eof: whole message in one packet
    body.bytes_field(3, data, emit_empty=True)
    env = pw.Writer()
    env.message_field(3, body.bytes(), always=True)
    return env.bytes()


def _mconn(limit, metrics=None, on_exceeded=None):
    received = []

    async def on_receive(chan_id, msg):
        received.append((chan_id, msg))

    async def on_error(e):
        raise AssertionError(f"on_error: {e}")

    chans = [
        ChannelDescriptor(VOTE_CH, priority=7),
        ChannelDescriptor(MEMPOOL_CH, priority=5, sheddable=True,
                          recv_message_capacity=1024),
    ]
    conn = MConnection(
        _NullTransport(), chans, on_receive, on_error,
        recv_limit=limit, metrics=metrics,
        on_rate_limit_exceeded=on_exceeded,
    )
    return conn, received


def test_vote_channel_never_shed_while_mempool_floods():
    """THE vote-path guard: with the mempool channel saturated far past its
    budget, every vote-channel message still dispatches and the shed
    accounting shows zero drops on consensus channels."""
    reg = M.Registry()
    pm = M.P2PMetrics(reg)
    limit = RecvRateLimit(bytes_per_s=0, msgs_per_s=5, strikes=10 ** 9)
    conn, received = _mconn(limit, metrics=pm)

    async def run():
        for i in range(200):
            await conn._handle_packet(_packet_env(MEMPOOL_CH, b"tx%03d" % i))
            await conn._handle_packet(_packet_env(VOTE_CH, b"vote%03d" % i))

    asyncio.run(run())
    votes = [m for c, m in received if c == VOTE_CH]
    txs = [m for c, m in received if c == MEMPOOL_CH]
    assert len(votes) == 200  # zero votes dropped
    assert len(txs) <= 6  # bucket: 5 + at most one refill tick
    assert conn.shed_msgs == 200 - len(txs)
    assert VOTE_CH not in conn.shed_by_channel
    assert conn.shed_by_channel[MEMPOOL_CH] == conn.shed_msgs
    # counters: only the mempool channel appears
    assert pm.rate_limited_msgs._values.get(("0x30",), 0) == conn.shed_msgs
    assert pm.rate_limited_msgs._values.get(("0x22",), 0) == 0
    # status() surfaces the shed accounting for net_info//debug/overload
    st = conn.status()
    assert st["shed_msgs_total"] == conn.shed_msgs
    assert st["shed_by_channel"] == {"0x30": conn.shed_msgs}


def test_persistent_flooder_triggers_misbehavior_callback():
    fired = asyncio.Event()

    async def on_exceeded():
        fired.set()

    limit = RecvRateLimit(bytes_per_s=0, msgs_per_s=1, strikes=5,
                          strike_window=60.0)
    conn, _ = _mconn(limit, on_exceeded=on_exceeded)

    async def run():
        for i in range(10):
            await conn._handle_packet(_packet_env(MEMPOOL_CH, b"x"))
        await asyncio.sleep(0)  # let the fire-and-forget report task run
        assert fired.is_set()

    asyncio.run(run())


def test_no_limit_config_admits_everything():
    conn, received = _mconn(None)

    async def run():
        for i in range(50):
            await conn._handle_packet(_packet_env(MEMPOOL_CH, b"x"))

    asyncio.run(run())
    assert len(received) == 50
    assert conn.shed_msgs == 0


def test_oversized_message_counted_and_fatal():
    reg = M.Registry()
    pm = M.P2PMetrics(reg)
    conn, _ = _mconn(None, metrics=pm)

    async def run():
        with pytest.raises(ValueError, match="exceeds recv capacity"):
            await conn._handle_packet(_packet_env(MEMPOOL_CH, b"z" * 2048))

    asyncio.run(run())
    assert pm.oversized_msgs._values.get(("0x30",), 0) == 1


def test_reactor_channel_shed_policy():
    """Consensus channels must never be sheddable; mempool/pex/evidence must
    be — the shed ORDER (txs, gossip, never votes) is a declared invariant,
    not an emergent one."""
    from tendermint_tpu.consensus.reactor import ConsensusReactor
    from tendermint_tpu.evidence.reactor import EvidenceReactor
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.p2p.pex import AddrBook, PexReactor

    cons = ConsensusReactor.__new__(ConsensusReactor)
    for d in ConsensusReactor.get_channels(cons):
        assert not d.sheddable, f"consensus channel {d.id:#x} marked sheddable"
        assert d.recv_message_capacity <= 22020096
    for d in MempoolReactor(None).get_channels():
        assert d.sheddable
    for d in EvidenceReactor(None).get_channels():
        assert d.sheddable
    for d in PexReactor(AddrBook(None)).get_channels():
        assert d.sheddable


# ---------------------------------------------------------------------------
# RPC load gate


def _gate(max_inflight=2):
    reg = M.Registry()
    rm = M.RPCMetrics(reg)
    from tendermint_tpu.rpc.server import LoadGate

    return LoadGate(max_inflight, metrics=rm), rm


def test_gate_bounds_sheddable_only():
    gate, _ = _gate(2)
    assert gate.admits("broadcast_tx_sync")
    gate.enter()
    gate.enter()
    assert not gate.admits("broadcast_tx_sync")
    assert not gate.admits("abci_query")
    # non-sheddable methods bypass a full gate
    for m in ("health", "status", "consensus_state", "net_info",
              "debug_overload", "broadcast_evidence"):
        assert gate.admits(m)
    gate.exit()
    assert gate.admits("broadcast_tx_sync")


def test_gate_overload_switches_shed_writes_then_reads():
    gate, rm = _gate(100)
    gate.shed_writes = True
    assert not gate.admits("broadcast_tx_commit")
    assert gate.admits("abci_query")  # reads still served at ELEVATED
    gate.shed_reads = True
    assert not gate.admits("abci_query")
    assert gate.admits("status")  # never shed
    gate.record_shed("broadcast_tx_commit")
    assert gate.shed_total == 1
    assert rm.shed_requests._values.get(("broadcast_tx_commit",), 0) == 1


class _FakeRequest:
    def __init__(self, body):
        self._body = body
        self.query = {}

    async def json(self):
        return self._body


def _rpc_server(mempool=None, max_inflight=2):
    from tendermint_tpu.rpc.server import RPCServer

    cfg = test_config()
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.max_inflight_requests = max_inflight
    node = SimpleNamespace(
        config=cfg, metrics=M.NodeMetrics(), mempool=mempool,
        rpc_server=None, switch=None, overload=None,
    )
    return RPCServer(node)


def test_rpc_429_with_retry_after_when_gate_full():
    import json as _json

    rpc = _rpc_server()
    rpc.gate.enter()
    rpc.gate.enter()  # gate saturated

    async def run():
        resp = await rpc._handle_jsonrpc(
            _FakeRequest({"id": 1, "method": "broadcast_tx_sync",
                          "params": {"tx": "00"}})
        )
        assert resp.status == 429
        assert resp.headers["Retry-After"]
        body = _json.loads(resp.text)
        assert body["error"]["code"] == -32005
        assert body["error"]["data"]["method"] == "broadcast_tx_sync"
        # health bypasses the saturated gate
        ok = await rpc._handle_jsonrpc(_FakeRequest({"id": 2, "method": "health"}))
        assert ok.status == 200
        # shed accounting fed the metrics
        assert rpc.gate.shed_total == 1

    asyncio.run(run())


def test_rpc_structured_mempool_reject_not_500():
    """broadcast_tx_sync against a full/quota'd mempool returns a typed
    JSON-RPC error carrying the reject reason — not -32603 with a bare
    traceback string."""
    import json as _json

    from tendermint_tpu.mempool.mempool import MempoolFullError, SenderQuotaError

    class RejectingMempool:
        def __init__(self, exc):
            self.exc = exc

        def check_tx(self, tx, sender=""):
            raise self.exc

    for exc, reason in (
        (MempoolFullError("no evictable lower-priority txs"), "full"),
        (SenderQuotaError("peerX", 3), "quota"),
    ):
        rpc = _rpc_server(mempool=RejectingMempool(exc))

        async def run():
            resp = await rpc._handle_jsonrpc(
                _FakeRequest({"id": 7, "method": "broadcast_tx_sync",
                              "params": {"tx": "00"}})
            )
            assert resp.status == 200  # JSON-RPC error, not an HTTP failure
            body = _json.loads(resp.text)
            assert body["error"]["code"] == -32001
            assert body["error"]["data"]["reason"] == reason
            assert "Traceback" not in body["error"]["data"]["detail"]

        asyncio.run(run())


def test_debug_overload_route_shape():
    class Pool:
        max_txs = 10
        max_txs_bytes = 1000

        def size(self):
            return 3

        def txs_bytes(self):
            return 30

        def is_full(self, n):
            return False

        evicted_total = 2
        expired_total = 1

    rpc = _rpc_server(mempool=Pool())

    async def run():
        out = await rpc._debug_overload({})
        assert out["rpc"]["max_inflight_requests"] == 2
        assert out["mempool"]["size"] == 3
        assert out["mempool"]["evicted_total"] == 2
        assert out["controller"] is None  # SimpleNamespace node: no controller

    asyncio.run(run())


# ---------------------------------------------------------------------------
# overload controller


def _controller(mempool_fill):
    from tendermint_tpu.config.config import OverloadConfig
    from tendermint_tpu.node.overload import OverloadController
    from tendermint_tpu.rpc.server import LoadGate

    class Pool:
        max_txs = 100
        max_txs_bytes = 10 ** 9

        def __init__(self):
            self.n = 0

        def size(self):
            return self.n

        def txs_bytes(self):
            return 0

    pool = Pool()
    pool.n = mempool_fill
    gate = LoadGate(10)
    reg = M.Registry()
    node = SimpleNamespace(
        mempool=pool,
        consensus=SimpleNamespace(_queue=asyncio.Queue(maxsize=100)),
        rpc_server=SimpleNamespace(gate=gate),
        switch=None,
        mempool_reactor=SimpleNamespace(shed=False),
        overload=None,
    )
    ctl = OverloadController(node, OverloadConfig(), metrics=M.OverloadMetrics(reg))
    return ctl, node, pool, gate


def test_controller_level_transitions_with_hysteresis():
    ctl, node, pool, gate = _controller(0)
    assert ctl.evaluate() == 0
    assert not node.mempool_reactor.shed and not gate.shed_writes

    pool.n = 75  # >= elevated watermark 0.7
    assert ctl.evaluate() == 1
    assert node.mempool_reactor.shed
    assert gate.shed_writes and not gate.shed_reads

    pool.n = 95  # >= critical watermark 0.9
    assert ctl.evaluate() == 2
    assert gate.shed_reads

    pool.n = 80  # 0.8: above 0.8*critical(0.72) -> stays critical
    assert ctl.evaluate() == 2

    pool.n = 60  # 0.6: below 0.72 but above 0.8*elevated(0.56) -> elevated
    assert ctl.evaluate() == 1
    assert not gate.shed_reads and gate.shed_writes

    pool.n = 10  # recovery: everything re-admitted
    assert ctl.evaluate() == 0
    assert not node.mempool_reactor.shed
    assert not gate.shed_writes and not gate.shed_reads
    assert ctl.transitions_up == 2 and ctl.transitions_down == 2

    snap = ctl.snapshot()
    assert snap["level"] == 0 and snap["level_name"] == "normal"
    assert snap["shed"]["votes"] is False
    assert "mempool" in snap["signals"]


def test_controller_boundary_no_flap():
    ctl, node, pool, gate = _controller(0)
    pool.n = 70
    levels = set()
    for _ in range(10):
        levels.add(ctl.evaluate())
    assert levels == {1}  # sits at elevated, no oscillation
    assert ctl.transitions_up == 1


def test_controller_samples_rpc_and_queue_signals():
    ctl, node, pool, gate = _controller(0)
    for _ in range(9):
        gate.enter()
    node.consensus._queue.put_nowait(object())
    sig = ctl.sample()
    assert sig["rpc_inflight"] == 0.9
    assert sig["consensus_queue"] == 0.01
    assert sig["mempool"] == 0.0


def test_mempool_reactor_sheds_gossip_when_full_or_switched():
    from tendermint_tpu.mempool.reactor import MempoolReactor, encode_txs

    class Pool:
        def __init__(self):
            self.full = False
            self.checked = []

        def is_full(self, n):
            return self.full

        def check_tx(self, tx, sender=""):
            self.checked.append(tx)

        def check_tx_batch(self, txs, sender=""):
            # the reactor's one-executor-hop batch path (ISSUE 11)
            return [self.check_tx(tx, sender) for tx in txs]

        def entries(self):
            return []

    pool = Pool()
    reg = M.Registry()
    r = MempoolReactor(pool, metrics=M.OverloadMetrics(reg))
    peer = SimpleNamespace(id="peerZ")

    async def run():
        await r.receive(0x30, peer, encode_txs([b"t1"]))
        assert pool.checked == [b"t1"]
        pool.full = True
        await r.receive(0x30, peer, encode_txs([b"t2", b"t3"]))
        assert pool.checked == [b"t1"]  # no CheckTx (or decode) paid for shed batches
        assert r.shed_rx == 1  # counts dropped MESSAGES, decode is skipped
        pool.full = False
        r.shed = True  # overload controller switch
        await r.receive(0x30, peer, encode_txs([b"t4"]))
        assert r.shed_rx == 2
        r.shed = False
        await r.receive(0x30, peer, encode_txs([b"t5"]))
        assert pool.checked == [b"t1", b"t5"]

    asyncio.run(run())


# ---------------------------------------------------------------------------
# ABCI resilience


def _start_app_server(port=0):
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.abci.socket import SocketServer

    last = None
    for _ in range(40):  # rebinding a just-closed port can race the kernel
        try:
            srv = SocketServer(f"tcp://127.0.0.1:{port}", KVStoreApplication())
            srv.start()
            return srv, srv.bound_addr[1]
        except OSError as e:
            last = e
            time.sleep(0.05)
    raise last


def test_reconnecting_client_survives_app_restart():
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.client import ReconnectingClient
    from tendermint_tpu.abci.socket import socket_client_creator

    srv, port = _start_app_server()
    addr = f"tcp://127.0.0.1:{port}"
    rc = ReconnectingClient(
        socket_client_creator(addr, call_timeout=5.0),
        attempts=20, base_delay=0.05, max_delay=0.2, name="mempool",
    )
    try:
        assert rc.check_tx(abci.RequestCheckTx(tx=b"k=v")).code == 0
        # kill the app (listener AND live conns) — then restart on the port
        srv.stop()
        time.sleep(0.05)
        srv, _ = _start_app_server(port)
        # the wrapped conn reconnects with backoff and the call succeeds
        assert rc.check_tx(abci.RequestCheckTx(tx=b"k2=v2")).code == 0
        assert rc.reconnects >= 1
    finally:
        rc.close()
        srv.stop()


def test_raw_consensus_conn_stays_fatal_loud():
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.socket import SocketClient

    srv, port = _start_app_server()
    client = SocketClient(f"tcp://127.0.0.1:{port}", call_timeout=5.0)
    try:
        assert client.info(abci.RequestInfo()) is not None
        srv.stop()
        time.sleep(0.1)
        with pytest.raises((ConnectionError, OSError)):
            client.info(abci.RequestInfo())
        # and it STAYS dead: no silent recovery on a later call
        with pytest.raises((ConnectionError, OSError)):
            client.info(abci.RequestInfo())
        assert client.is_dead()
    finally:
        client.close()
        srv.stop()


def test_abci_chaos_fail_point_kills_app_mid_flight():
    """The `abci_client_call` fail point lets a chaos schedule kill the app
    server just before a call is written — the ReconnectingClient must ride
    through it (restarted app), the raw client must not."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.client import ReconnectingClient
    from tendermint_tpu.abci.socket import socket_client_creator
    from tendermint_tpu.libs import fail

    srv, port = _start_app_server()
    addr = f"tcp://127.0.0.1:{port}"
    state = {"srv": srv, "armed": True}

    def kill_app_once():
        if state["armed"]:
            state["armed"] = False
            state["srv"].stop()
            state["srv"], _ = _start_app_server(port)

    rc = ReconnectingClient(
        socket_client_creator(addr, call_timeout=5.0),
        attempts=20, base_delay=0.05, max_delay=0.2, name="query",
    )
    try:
        assert rc.info(abci.RequestInfo()) is not None  # conn established
        fail.inject("abci_client_call", kill_app_once)
        res = rc.info(abci.RequestInfo())
        assert res is not None
        assert rc.reconnects >= 1
    finally:
        fail.inject("abci_client_call", None)
        rc.close()
        state["srv"].stop()


def test_appconns_wraps_only_non_consensus_conns():
    from tendermint_tpu.abci.client import LocalClient, ReconnectingClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.proxy.multi import AppConns, local_client_creator

    conns = AppConns(local_client_creator(KVStoreApplication()), resilient=True)
    assert isinstance(conns.consensus, LocalClient)  # never wrapped
    for c in (conns.mempool, conns.query, conns.snapshot):
        assert isinstance(c, ReconnectingClient)
    conns.stop()

    plain = AppConns(local_client_creator(KVStoreApplication()))
    for c in (plain.consensus, plain.mempool, plain.query, plain.snapshot):
        assert isinstance(c, LocalClient)
    plain.stop()


def test_node_with_socket_app_survives_mempool_conn_break(tmp_path):
    """End-to-end: a single-validator node against an out-of-process socket
    app keeps committing after the mempool connection is broken mid-chain
    (ReconnectingClient path) — the node-level acceptance shape."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    srv, port = _start_app_server()
    cfg = test_config()
    cfg.base.db_backend = "memdb"
    cfg.base.proxy_app = f"tcp://127.0.0.1:{port}"
    cfg.base.abci = "socket"
    cfg.base.abci_reconnect_base_delay = 0.05
    cfg.base.abci_reconnect_attempts = 20
    cfg.rpc.laddr = ""
    cfg.root_dir = ""
    cfg.consensus.wal_path = str(tmp_path / "wal")
    priv = FilePV(gen_ed25519(b"\x91" * 32))
    gen = GenesisDoc(chain_id="abci-restart",
                     validators=[GenesisValidator(priv.get_pub_key(), 10)])
    node = Node(cfg, gen, priv_validator=priv)

    async def run():
        await node.start()
        try:
            await node.wait_for_height(2, timeout=30)
            # submit a tx through the (wrapped) mempool conn, then break it
            node.mempool.check_tx(b"pre=break")
            inner = node.proxy_app.mempool._client
            assert inner is not None
            inner.close()  # simulated broken pipe on the mempool conn
            # next mempool call reconnects and succeeds; chain keeps going
            res = node.mempool.check_tx(b"post=break")
            assert res.code == abci.CODE_TYPE_OK
            assert node.proxy_app.mempool.reconnects >= 1
            h = node.block_store.height
            await node.wait_for_height(h + 2, timeout=30)
        finally:
            await node.stop()

    try:
        asyncio.run(run())
    finally:
        srv.stop()
