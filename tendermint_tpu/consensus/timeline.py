"""Per-height/round consensus timeline ring.

The distributed-system complement of libs/trace.py's device-side flight
recorder: a bounded, thread-safe record of WHERE each height spent its time
— step entries, round escalations, proposal/vote arrival, commit — kept as
structured per-height records instead of a flat span ring, so one GET of
`/debug/consensus_timeline` answers "why was height H slow?" without
grepping logs. The reference exposes only the *current* round state
(rpc/core/consensus.go DumpConsensusState); history dies with the round.

Two producers share this format:

- the live ConsensusState (consensus/cs_state.py) feeds wall-clock events
  while running (gated on `tracer.enabled`: with tracing off the hot path
  pays only flag checks and the ring stays empty);
- the offline WAL inspector (tools/wal_inspect.py) replays a crashed or
  slow node's WAL into the same structure, deriving timestamps from the
  signed vote/proposal times embedded in the messages.

Overhead contract: every record_* call is a few dict/list operations under
one lock; per-round vote arrivals aggregate into a fixed bucket histogram
(VOTE_ARRIVAL_BUCKETS_MS), never an unbounded list.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

DEFAULT_MAX_HEIGHTS = 128

# vote-arrival offsets from round start, cumulative buckets in milliseconds
VOTE_ARRIVAL_BUCKETS_MS = (5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000)

# per-hop propagation latencies (skew-corrected), buckets in milliseconds
PROPAGATION_BUCKETS_MS = (1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500)

# bounds on remote-supplied cardinality: a peer controls the origin id in a
# trace stamp, so per-origin tables cap out into an "_other" bucket instead
# of growing with whatever a flood invents; reactor-side recording also
# arrives BEFORE consensus validation, so round keys are capped too (a real
# net escalates a handful of rounds; a flood invents millions)
MAX_ORIGINS_PER_ROUND = 64
MAX_PEER_STATS_ORIGINS = 128
MAX_ROUNDS_PER_HEIGHT = 32
OVERFLOW_ORIGIN = "_other"


def _bucketize(buckets, counters: List[int], value_ms: float) -> None:
    for i, b in enumerate(buckets):
        if value_ms <= b:
            counters[i] += 1
            return
    counters[-1] += 1

# default for record_* ts args: "stamp with wall-clock now". The offline WAL
# inspector instead passes an explicit float (derived from signed message
# timestamps) or None ("no time reference yet" — the record is kept, its
# durations stay undefined).
_NOW = object()


class ConsensusTimeline:
    """Bounded ring of per-height consensus records, oldest evicted first."""

    def __init__(self, max_heights: int = DEFAULT_MAX_HEIGHTS):
        self.max_heights = max(1, int(max_heights))
        self._lock = threading.Lock()
        self._heights: "OrderedDict[int, dict]" = OrderedDict()
        # cross-height per-origin propagation aggregates (the per-peer lag
        # ranking the chain observatory merges): origin node id -> per-kind
        # {count, sum_ms, max_ms} plus how many samples were skew-corrected
        self._peer_stats: Dict[str, dict] = {}

    # -- recording ----------------------------------------------------------

    def _rec(self, height: int) -> dict:
        rec = self._heights.get(height)
        if rec is None:
            rec = {
                "height": height,
                "steps": [],  # [{"round", "step", "ts"}] in arrival order
                "round_start": {},  # round -> ts of its first step
                "proposals": [],  # [{"round", "ts"}]
                "votes": {},  # round -> {"prevote", "precommit", "arrival_ms"}
                # round -> cross-node propagation evidence (chain observatory):
                # first-seen proposal latency + origin/hops, and the block-part
                # gossip fan-out window (first..last part receipt)
                "propagation": {},
                "commit": None,  # {"round", "ts", "txs"}
                "end_height_ts": None,
            }
            self._heights[height] = rec
            while len(self._heights) > self.max_heights:
                self._heights.popitem(last=False)
        return rec

    def record_step(self, height: int, round_: int, step: str, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            rec = self._rec(height)
            rec["steps"].append({"round": round_, "step": step, "ts": ts})
            if ts is not None:
                rec["round_start"].setdefault(round_, ts)

    def record_proposal(self, height: int, round_: int, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            self._rec(height)["proposals"].append({"round": round_, "ts": ts})

    def record_vote(self, height: int, round_: int, vote_type: str, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        key = "prevote" if "PREVOTE" in vote_type.upper() else "precommit"
        with self._lock:
            rec = self._rec(height)
            votes = rec["votes"].get(round_)
            if votes is None:
                votes = rec["votes"][round_] = {
                    "prevote": 0,
                    "precommit": 0,
                    "arrival_ms": [0] * (len(VOTE_ARRIVAL_BUCKETS_MS) + 1),
                }
            votes[key] += 1
            start = rec["round_start"].get(round_)
            if start is not None and ts is not None:
                off_ms = max(0.0, (ts - start) * 1e3)
                _bucketize(VOTE_ARRIVAL_BUCKETS_MS, votes["arrival_ms"], off_ms)

    # -- cross-node propagation (chain observatory, ISSUE 8) ----------------

    def _prop(self, rec: dict, round_: int) -> Optional[dict]:
        prop = rec["propagation"].get(round_)
        if prop is None:
            if len(rec["propagation"]) >= MAX_ROUNDS_PER_HEIGHT:
                return None  # remote-supplied round flood: stop allocating
            prop = rec["propagation"][round_] = {
                # first-seen proposal receipt: skew-corrected latency from
                # the origin's stamp, who proposed it, and over how many hops
                "proposal_first_seen_ms": None,
                "proposal_origin": None,
                "proposal_hops": None,
                "proposal_receipts": 0,
                # block-part gossip fan-out window on THIS node
                "parts": 0,
                "parts_first_ts": None,
                "parts_last_ts": None,
                "part_latency_ms": [0] * (len(PROPAGATION_BUCKETS_MS) + 1),
            }
        return prop

    def record_proposal_propagation(
        self, height: int, round_: int, origin: str, latency_s: float,
        hops: int = 0, ts=_NOW,
    ) -> None:
        """A proposal ARRIVED from a peer: record the first-seen propagation
        latency (seconds, already skew-corrected and clamped >= 0 by the
        caller) for (height, round). Later duplicate receipts only count."""
        with self._lock:
            prop = self._prop(self._rec(height), round_)
            if prop is None:
                return
            prop["proposal_receipts"] += 1
            if prop["proposal_first_seen_ms"] is None:
                prop["proposal_first_seen_ms"] = round(latency_s * 1e3, 3)
                prop["proposal_origin"] = origin
                prop["proposal_hops"] = hops

    def record_block_part(
        self, height: int, round_: int, latency_s: Optional[float] = None, ts=_NOW
    ) -> None:
        """One gossiped block part arrived: widen the fan-out window (the
        dump derives parts_fanout_s = last - first receipt) and histogram
        its per-hop latency when a trace stamp supplied one."""
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            prop = self._prop(self._rec(height), round_)
            if prop is None:
                return
            prop["parts"] += 1
            if ts is not None:
                if prop["parts_first_ts"] is None:
                    prop["parts_first_ts"] = ts
                prop["parts_last_ts"] = ts
            if latency_s is not None:
                _bucketize(
                    PROPAGATION_BUCKETS_MS, prop["part_latency_ms"], latency_s * 1e3
                )

    def record_vote_origin(
        self, height: int, round_: int, vote_type: str, origin: str,
        latency_s: Optional[float] = None,
    ) -> None:
        """Vote arrival attributed to its ORIGIN validator node (from the
        trace stamp; falls back to the direct peer id at the call site):
        per-origin counts + propagation-latency histogram, the evidence for
        'whose votes reach us last'. Origin cardinality is capped."""
        key = "prevote" if "PREVOTE" in vote_type.upper() else "precommit"
        with self._lock:
            rec = self._rec(height)
            votes = rec["votes"].get(round_)
            if votes is None:
                if len(rec["votes"]) >= MAX_ROUNDS_PER_HEIGHT:
                    return  # remote-supplied round flood: stop allocating
                votes = rec["votes"][round_] = {
                    "prevote": 0,
                    "precommit": 0,
                    "arrival_ms": [0] * (len(VOTE_ARRIVAL_BUCKETS_MS) + 1),
                }
            by_origin = votes.setdefault("by_origin", {})
            ent = by_origin.get(origin)
            if ent is None:
                if len(by_origin) >= MAX_ORIGINS_PER_ROUND:
                    origin = OVERFLOW_ORIGIN
                    ent = by_origin.get(origin)
                if ent is None:
                    ent = by_origin[origin] = {
                        "prevote": 0,
                        "precommit": 0,
                        "latency_ms": [0] * (len(PROPAGATION_BUCKETS_MS) + 1),
                        "max_ms": 0.0,
                    }
            ent[key] += 1
            if latency_s is not None:
                ms = latency_s * 1e3
                _bucketize(PROPAGATION_BUCKETS_MS, ent["latency_ms"], ms)
                if ms > ent["max_ms"]:
                    ent["max_ms"] = round(ms, 3)

    def record_hop(
        self, origin: str, kind: str, latency_s: float, skew_corrected: bool = False
    ) -> None:
        """Cross-height per-origin hop-latency aggregate over every traced
        message kind (proposal/block_part/vote/has_vote/round_step) — the
        per-peer lag ranking. Bounded per MAX_PEER_STATS_ORIGINS."""
        with self._lock:
            st = self._peer_stats.get(origin)
            if st is None:
                if len(self._peer_stats) >= MAX_PEER_STATS_ORIGINS:
                    origin = OVERFLOW_ORIGIN
                    st = self._peer_stats.get(origin)
                if st is None:
                    st = self._peer_stats[origin] = {
                        "kinds": {}, "skew_corrected": 0, "uncorrected": 0,
                    }
            k = st["kinds"].get(kind)
            if k is None:
                k = st["kinds"][kind] = {"count": 0, "sum_ms": 0.0, "max_ms": 0.0}
            ms = latency_s * 1e3
            k["count"] += 1
            k["sum_ms"] += ms
            if ms > k["max_ms"]:
                k["max_ms"] = ms
            if skew_corrected:
                st["skew_corrected"] += 1
            else:
                st["uncorrected"] += 1

    def peer_stats(self) -> Dict[str, dict]:
        """Per-origin propagation aggregates with derived means, worst
        origin first (by mean latency over all kinds)."""
        with self._lock:
            snap = {
                o: {
                    "kinds": {
                        k: {
                            "count": v["count"],
                            "mean_ms": round(v["sum_ms"] / v["count"], 3),
                            "max_ms": round(v["max_ms"], 3),
                        }
                        for k, v in st["kinds"].items()
                    },
                    "skew_corrected": st["skew_corrected"],
                    "uncorrected": st["uncorrected"],
                }
                for o, st in self._peer_stats.items()
            }
        for st in snap.values():
            total = sum(k["count"] for k in st["kinds"].values())
            st["count"] = total
            st["mean_ms"] = (
                round(
                    sum(k["mean_ms"] * k["count"] for k in st["kinds"].values())
                    / total,
                    3,
                )
                if total
                else 0.0
            )
        return dict(
            sorted(snap.items(), key=lambda kv: -kv[1]["mean_ms"])
        )

    def record_commit(self, height: int, round_: int, txs: int = 0, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            self._rec(height)["commit"] = {"round": round_, "ts": ts, "txs": txs}

    def record_end_height(self, height: int, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            self._rec(height)["end_height_ts"] = ts

    # -- introspection ------------------------------------------------------

    def dump(self, limit: Optional[int] = None) -> List[dict]:
        """Time-ordered per-height records (ascending height; the most
        recent `limit` heights if given). Step durations are derived on the
        way out: each step's `dur_s` is the gap to the next recorded step of
        the same height (the last step stays open-ended)."""
        with self._lock:
            heights = [self._copy_rec(r) for r in self._heights.values()]
        heights.sort(key=lambda r: r["height"])
        if limit is not None and limit >= 0:
            heights = heights[-limit:] if limit else []
        for rec in heights:
            steps = rec["steps"]
            for i, st in enumerate(steps):
                nxt = steps[i + 1]["ts"] if i + 1 < len(steps) else None
                if nxt is not None and st["ts"] is not None:
                    # clamp: WAL-reconstructed timestamps come from different
                    # validators' clocks, so skew could make the gap negative
                    st["dur_s"] = round(max(0.0, nxt - st["ts"]), 6)
            # rounds the state machine actually ENTERED (steps/commit) —
            # votes are excluded: next-round and peer-catchup votes arrive
            # for rounds this node never escalated to, and counting them
            # would fabricate round escalations in the report
            rounds = {s["round"] for s in steps}
            if rec["commit"] is not None:
                rounds.add(rec["commit"]["round"])
            rec["round_count"] = (max(rounds) + 1) if rounds else 0
            commit = rec["commit"]
            start = rec["round_start"].get(0)
            if commit is not None and commit["ts"] is not None and start is not None:
                rec["total_s"] = round(max(0.0, commit["ts"] - start), 6)
            # derived gossip fan-out: first..last block-part receipt window
            for prop in rec.get("propagation", {}).values():
                if prop["parts_first_ts"] is not None and prop["parts_last_ts"] is not None:
                    prop["parts_fanout_s"] = round(
                        max(0.0, prop["parts_last_ts"] - prop["parts_first_ts"]), 6
                    )
            # internal bookkeeping, derivable from steps[] — not API surface
            rec.pop("round_start", None)
        return heights

    def _copy_rec(self, rec: dict) -> dict:
        out = dict(rec)
        out["steps"] = [dict(s) for s in rec["steps"]]
        out["proposals"] = [dict(p) for p in rec["proposals"]]
        votes = {}
        for r, v in rec["votes"].items():
            cv = {**v, "arrival_ms": list(v["arrival_ms"])}
            if "by_origin" in v:
                cv["by_origin"] = {
                    o: {**e, "latency_ms": list(e["latency_ms"])}
                    for o, e in v["by_origin"].items()
                }
            votes[r] = cv
        out["votes"] = votes
        out["propagation"] = {
            r: {
                **p,
                "part_latency_ms": list(p["part_latency_ms"]),
            }
            for r, p in rec.get("propagation", {}).items()
        }
        out["round_start"] = dict(rec["round_start"])
        if rec["commit"] is not None:
            out["commit"] = dict(rec["commit"])
        return out

    def heights(self) -> List[int]:
        with self._lock:
            return sorted(self._heights)

    def clear(self) -> None:
        with self._lock:
            self._heights.clear()
            self._peer_stats.clear()
