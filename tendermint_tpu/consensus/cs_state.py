"""The Tendermint BFT consensus state machine (reference: consensus/state.go:83).

Architecture: ONE asyncio task (`_receive_loop`, the analog of receiveRoutine,
reference: consensus/state.go:684) serializes every input — peer messages,
internal (self-generated) messages, timeouts, tx-availability — and is the
only mutator of RoundState. Timeouts come from a single replaceable timer
(reference: consensus/ticker.go). Every input is WAL-written before
processing; internal messages are fsynced.

Step functions mirror the reference one-for-one: enterNewRound → enterPropose
→ (proposal+parts complete) → enterPrevote → enterPrevoteWait → enterPrecommit
(locking/POL rules, reference: consensus/state.go:1255) → enterPrecommitWait →
enterCommit → tryFinalizeCommit → finalizeCommit (SaveBlock → WAL EndHeight →
ApplyBlock → updateToState → scheduleRound0).

Vote verification rides the batched TPU path via VoteSet (deferred mode flushes
one device batch per tick under vote storms; see config.defer_vote_verification).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, List, Optional

from tendermint_tpu.config.config import ConsensusConfig
from tendermint_tpu.consensus.messages import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.consensus.round_state import HeightVoteSet, RoundState, RoundStepType
from tendermint_tpu.consensus.wal import (
    WAL,
    EndHeightMessage,
    EventRoundState,
    MsgInfo,
    TimeoutInfo,
)
from tendermint_tpu.libs import fail
from tendermint_tpu.libs.trace import tracer as _tracer
from tendermint_tpu.state.execution import BlockExecutor, BlockValidationError
from tendermint_tpu.state.sm_state import State
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.event_bus import (
    EVENT_COMPLETE_PROPOSAL,
    EVENT_LOCK,
    EVENT_NEW_ROUND,
    EVENT_NEW_ROUND_STEP,
    EVENT_POLKA,
    EVENT_TIMEOUT_PROPOSE,
    EVENT_TIMEOUT_WAIT,
    EVENT_VALID_BLOCK,
    EventBus,
)
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import (
    ConflictingVotesError,
    VoteSet,
    VoteSetError,
)

logger = logging.getLogger("tendermint_tpu.consensus")


def commit_to_vote_set(chain_id: str, commit, val_set: ValidatorSet) -> VoteSet:
    """Rebuild the precommit VoteSet from a seen commit
    (reference: types/vote_set.go CommitToVoteSet). Sign-bytes for the whole
    commit are built in ONE batched pass (canonical.vote_sign_bytes_many)
    and seeded into each vote's memo, so the per-vote serial verify inside
    add_vote never runs the per-row canonical encoder."""
    vote_set = VoteSet(chain_id, commit.height, commit.round, SignedMsgType.PRECOMMIT, val_set)
    idxs = [i for i, cs_sig in enumerate(commit.signatures) if not cs_sig.absent()]
    msgs = commit.vote_sign_bytes_many(chain_id, idxs)
    for i, msg in zip(idxs, msgs):
        vote = commit.get_vote(i)
        vote.seed_sign_bytes(chain_id, msg)
        vote_set.add_vote(vote)
    return vote_set


class ConsensusState:
    def __init__(
        self,
        config: ConsensusConfig,
        state: State,
        block_exec: BlockExecutor,
        block_store,
        tx_notifier,  # mempool (set_txs_available_callback) or None
        evpool,
        wal: WAL,
        event_bus: Optional[EventBus] = None,
        priv_validator=None,
        metrics=None,
        timeline=None,
        slo=None,
        tx_tracker=None,
    ):
        self.config = config
        self.metrics = metrics
        # tx lifecycle tracker (libs/txtrace.py): consensus contributes the
        # proposed(height,round) and committed(height,index) stages; gated on
        # the tracer flag like the timeline, muted during replay
        self.tx_tracker = tx_tracker
        # per-height/round timeline ring (consensus/timeline.py), served by
        # GET /debug/consensus_timeline; recording is gated on tracer.enabled
        # so a disabled recorder costs the hot path only flag checks
        self.timeline = timeline
        # SLO engine (libs/slo.py): commit-interval and prevote-quorum-delay
        # observations feed it here; the reactor feeds proposal propagation
        # through this same reference (self.cs.slo)
        self.slo = slo
        # (height, round, step, perf_counter) of the current step, and
        # (height, round, perf_counter) of the current round — the clocks
        # behind step_duration_seconds / round_duration_seconds
        self._step_clock = None
        self._round_clock = None
        # (height, round) pairs already recorded by the prevote-delay gauges
        self._quorum_prevote_marked = None
        self._full_prevote_marked = None
        self.block_exec = block_exec
        self.block_store = block_store
        self.tx_notifier = tx_notifier
        self.evpool = evpool
        self.wal = wal
        self.event_bus = event_bus or EventBus()
        self.priv_validator = priv_validator
        self.priv_validator_pub_key = priv_validator.get_pub_key() if priv_validator else None

        self.rs = RoundState()
        self.state: Optional[State] = None
        self.replay_mode = False
        self.n_steps = 0

        self._queue: asyncio.Queue = asyncio.Queue(maxsize=1000)
        self._timer_task: Optional[asyncio.Task] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()
        self._running = False
        # hooks for byzantine tests (reference: consensus/state.go:135-137
        # function fields exist exactly for this)
        self.decide_proposal: Callable = self._default_decide_proposal
        self.do_prevote: Callable = self._default_do_prevote

        if state.last_block_height > 0:
            self._reconstruct_last_commit(state)
        self._update_to_state(state)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        self._catchup_replay(self.rs.height)
        if self.tx_notifier is not None:
            loop = asyncio.get_running_loop()
            self.tx_notifier.set_txs_available_callback(
                lambda: loop.call_soon_threadsafe(self._enqueue_nowait, ("txs_available", None))
            )
        self._loop_task = asyncio.create_task(self._receive_loop(), name="cs-receive")
        if self.rs.step == RoundStepType.NEW_HEIGHT:
            self._schedule_round0()
        elif self.rs.step == RoundStepType.COMMIT:
            # Replay re-entered COMMIT. If the block is already complete this
            # finalizes immediately (we are the only mutator until the loop
            # drains); if parts are missing only peer gossip can supply them —
            # no timeout applies (reference: enterCommit waits on gossip).
            self._try_finalize_commit(self.rs.height)
            if self.rs.step == RoundStepType.NEW_HEIGHT:
                self._schedule_round0()
        else:
            # WAL catchup left us mid-height. A NEW_HEIGHT timeout would be
            # dropped by _handle_timeout's step guard, and any timer left over
            # from replay may target an already-passed step — either way the
            # node would stall with no timer. Re-drive liveness by arming the
            # round's precommit-wait timeout: when it fires we precommit
            # (honoring locks) and advance to the next round, where peers/our
            # own proposer turn make progress (reference: consensus/replay.go:93
            # relies on gossip to re-drive; a single-node net has no gossip).
            self._schedule_timeout(
                self.config.precommit_timeout(self.rs.round),
                self.rs.height, self.rs.round, RoundStepType.PRECOMMIT_WAIT,
            )

    async def stop(self) -> None:
        self._running = False
        if self._timer_task:
            self._timer_task.cancel()
        if self._loop_task:
            await self._queue.put(("quit", None))
            try:
                await asyncio.wait_for(self._loop_task, timeout=5)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._loop_task.cancel()
        self.wal.close()

    async def wait_until_stopped(self) -> None:
        await self._stopped.wait()

    # ------------------------------------------------------------------
    # external input
    # ------------------------------------------------------------------

    def _enqueue_nowait(self, item) -> None:
        try:
            self._queue.put_nowait(item)
        except asyncio.QueueFull:
            asyncio.ensure_future(self._queue.put(item))

    async def add_peer_message(self, msg, peer_id: str) -> None:
        await self._queue.put(("peer", MsgInfo(msg, peer_id)))

    async def add_internal_message(self, msg) -> None:
        await self._queue.put(("internal", MsgInfo(msg, "")))

    def send_internal(self, msg) -> None:
        self._enqueue_nowait(("internal", MsgInfo(msg, "")))

    # ------------------------------------------------------------------
    # the receive loop (reference: consensus/state.go:684 receiveRoutine)
    # ------------------------------------------------------------------

    async def _receive_loop(self) -> None:
        defer = self.config.defer_vote_verification
        flush_interval = max(self.config.vote_flush_interval, 0.001)
        try:
            while self._running:
                # asyncio.Queue.get does not yield when items are ready; yield
                # explicitly so timers, RPC, and peers are never starved.
                await asyncio.sleep(0)
                if defer:
                    # Deferred-verification mode: wait at most one flush
                    # interval so queued unverified votes are batch-verified
                    # even when no new input arrives.
                    try:
                        kind, payload = await asyncio.wait_for(
                            self._queue.get(), timeout=flush_interval
                        )
                    except asyncio.TimeoutError:
                        try:
                            self._flush_deferred_votes()
                        except Exception:
                            logger.exception("CONSENSUS FAILURE!!! halting (halt-don't-corrupt)")
                            break
                        continue
                else:
                    kind, payload = await self._queue.get()
                # Greedy drain: take everything already queued and process it
                # in one tight batch — the per-message asyncio round trip
                # (queue await + explicit yield) was ~30-50 us/vote under a
                # vote storm, comparable to the actual bookkeeping. Message
                # ORDER is exactly the queue order, and each message is still
                # WAL-written before it is handled. With wal_group_commit on,
                # peer/timeout frames sit in the WAL's in-process buffer until
                # the drain-end flush below — a hard kill mid-drain can lose
                # up to one drain's worth of PEER frames from the replay log
                # (self-generated messages still fsync inline, so safety is
                # intact; the loss is replay/post-mortem completeness, bounded
                # by the batch size and the WAL's max-latency fsync bound).
                # Bounded so a firehose peer cannot starve timers/RPC for
                # more than one batch.
                batch = [(kind, payload)]
                while len(batch) < 512:
                    try:
                        batch.append(self._queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                quit_seen = False
                try:
                    for kind, payload in batch:
                        if kind == "quit":
                            quit_seen = True
                            break
                        if kind == "peer":
                            self.wal.write(payload)
                            self._handle_msg(payload)
                        elif kind == "internal":
                            self.wal.write_sync(payload)  # fsync self msgs
                            if isinstance(payload.msg, VoteMessage):
                                fail.fail_point("internal_vote_after_wal")
                            self._handle_msg(payload)
                        elif kind == "timeout":
                            self.wal.write(payload)
                            self._handle_timeout(payload)
                        elif kind == "txs_available":
                            self._handle_txs_available()
                    # Batch boundary — the group-commit point: everything the
                    # drain wrote lands as one buffered write, fsynced when
                    # the max-latency bound is due (no-op when
                    # wal_group_commit is off or nothing is pending).
                    if not quit_seen:
                        self.wal.flush_buffered()
                    # Then flush deferred votes in one device batch (storms
                    # accumulate while the queue is busy, then verify
                    # together). Never on quit — a shutdown must not
                    # batch-verify, commit, or publish into components that
                    # are already stopping.
                    if defer and not quit_seen and self._queue.empty():
                        self._flush_deferred_votes()
                except Exception:
                    logger.exception("CONSENSUS FAILURE!!! halting (halt-don't-corrupt)")
                    break
                if quit_seen:
                    break
        finally:
            self._stopped.set()

    def _handle_msg(self, mi: MsgInfo) -> None:
        """Per-message errors are logged and tolerated — only genuine invariant
        violations (anything that escapes this method) halt consensus
        (reference: consensus/state.go:766 handleMsg logs errors and continues).
        """
        msg, peer_id = mi.msg, mi.peer_id
        try:
            if isinstance(msg, ProposalMessage):
                msg.proposal.validate_basic()
                self._set_proposal(msg.proposal)
            elif isinstance(msg, BlockPartMessage):
                msg.part.validate_basic()
                self._add_proposal_block_part(msg, peer_id)
            elif isinstance(msg, VoteMessage):
                msg.vote.validate_basic()
                self._try_add_vote(msg.vote, peer_id)
            else:
                logger.error("unknown msg type %s", type(msg))
        except (VoteSetError, ValueError) as e:
            logger.error("error with msg %s from %s: %s", type(msg).__name__, peer_id or "self", e)

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < int(rs.step)
        ):
            return
        step = RoundStepType(ti.step)
        if step == RoundStepType.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif step == RoundStepType.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif step == RoundStepType.PROPOSE:
            if self.metrics is not None:
                self.metrics.proposal_timeout_total.inc()
            self._publish_rs(EVENT_TIMEOUT_PROPOSE)
            self._enter_prevote(ti.height, ti.round)
        elif step == RoundStepType.PREVOTE_WAIT:
            self._publish_rs(EVENT_TIMEOUT_WAIT)
            self._enter_precommit(ti.height, ti.round)
        elif step == RoundStepType.PRECOMMIT_WAIT:
            self._publish_rs(EVENT_TIMEOUT_WAIT)
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise RuntimeError(f"invalid timeout step {step}")

    def _handle_txs_available(self) -> None:
        """(reference: consensus/state.go:873 handleTxsAvailable)"""
        rs = self.rs
        if rs.round != 0:
            return
        if rs.step == RoundStepType.NEW_HEIGHT:
            if self._need_proof_block(rs.height):
                return  # enterPropose will be called by enterNewRound
            delay = max(0.0, rs.start_time_ns / 1e9 - time.time()) + 0.001
            self._schedule_timeout(delay, rs.height, 0, RoundStepType.NEW_ROUND)
        elif rs.step == RoundStepType.NEW_ROUND:
            self._enter_propose(rs.height, 0)

    # ------------------------------------------------------------------
    # timeouts
    # ------------------------------------------------------------------

    def _schedule_timeout(self, duration_s: float, height: int, round_: int, step: RoundStepType) -> None:
        """Single replaceable timer (reference: consensus/ticker.go:94)."""
        if self._timer_task is not None:
            self._timer_task.cancel()
        ti = TimeoutInfo(duration_s, height, round_, int(step))

        async def fire():
            try:
                if duration_s > 0:
                    await asyncio.sleep(duration_s)
                await self._queue.put(("timeout", ti))
            except asyncio.CancelledError:
                pass

        self._timer_task = asyncio.create_task(fire(), name="cs-timeout")

    def _schedule_round0(self) -> None:
        delay = max(0.0, self.rs.start_time_ns / 1e9 - time.time())
        self._schedule_timeout(delay, self.rs.height, 0, RoundStepType.NEW_HEIGHT)

    # ------------------------------------------------------------------
    # state update helpers
    # ------------------------------------------------------------------

    def _reconstruct_last_commit(self, state: State) -> None:
        """(reference: consensus/state.go reconstructLastCommit)"""
        seen = self.block_store.load_seen_commit(state.last_block_height)
        if seen is None:
            raise RuntimeError(
                f"failed to reconstruct last commit: seen commit for height {state.last_block_height} not found"
            )
        vote_set = commit_to_vote_set(state.chain_id, seen, state.last_validators)
        if not vote_set.has_two_thirds_majority():
            raise RuntimeError("failed to reconstruct last commit: does not have +2/3 maj")
        self.rs.last_commit = vote_set

    def _update_to_state(self, state: State) -> None:
        """(reference: consensus/state.go:564 updateToState)"""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and rs.height != state.last_block_height:
            raise RuntimeError(
                f"updateToState() expected state height of {rs.height} but found {state.last_block_height}"
            )
        if self.state is not None and not self.state.is_empty():
            if state.last_block_height <= self.state.last_block_height:
                self._new_step()
                return

        if state.last_block_height == 0:
            rs.last_commit = None
        elif rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if precommits is None or not precommits.has_two_thirds_majority():
                raise RuntimeError("wanted to form a commit, but precommits didn't have 2/3+")
            rs.last_commit = precommits

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        rs.height = height
        rs.round = 0
        rs.step = RoundStepType.NEW_HEIGHT
        now_ns = time.time_ns()
        if rs.commit_time_ns == 0:
            rs.start_time_ns = now_ns + int(self.config.timeout_commit * 1e9)
        else:
            rs.start_time_ns = rs.commit_time_ns + int(self.config.timeout_commit * 1e9)
        rs.validators = state.validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(
            state.chain_id, height, state.validators,
            defer_verification=self.config.defer_vote_verification,
        )
        rs.commit_round = -1
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = state
        if self.evpool is not None:
            self.evpool.set_state(state)
        self._new_step()

    def _new_step(self) -> None:
        rs = self.rs
        # Only log round-state transitions while actually running: the
        # constructor's updateToState must not append to the WAL (the
        # reference opens the WAL in OnStart, consensus/state.go:303, so
        # construction never writes; this also keeps the replay CLI
        # read-only).
        if self._running:
            self.wal.write(EventRoundState(rs.height, rs.round, int(rs.step)))
        self._mark_step()
        self.n_steps += 1
        self._publish_rs(EVENT_NEW_ROUND_STEP)

    def _tl(self):
        """The timeline iff recording is on — tracing disabled reduces every
        timeline call site to this one flag check (same contract as
        libs/trace.py's hoisted `tracer if tracer.enabled else None`)."""
        tl = self.timeline
        if tl is None or not _tracer.enabled or self.replay_mode:
            return None
        return tl

    def _track_block_txs(self, stage: str, height: int, round_: int, block) -> None:
        """Stamp a lifecycle stage for every tracked tx of `block` — one
        flag check when tracing is off or no tracker is wired (the hashing
        inside record_block never runs)."""
        tt = self.tx_tracker
        if (
            tt is None or not tt.enabled or self.replay_mode
            or block is None or not block.txs
        ):
            return
        tt.record_block(stage, height, round_, block.txs)

    def _mark_step(self) -> None:
        """Close the previous step's duration and open the new one — the
        analog of the reference's metrics.MarkStep (CometBFT
        consensus/metrics.go RecordConsMetrics)."""
        rs = self.rs
        cur = (rs.height, rs.round, rs.step)
        prev = self._step_clock
        if prev is not None and prev[:3] == cur:
            return  # _new_step without a step change (e.g. precommit-wait arm)
        now = time.perf_counter()
        if prev is not None and self.metrics is not None and not self.replay_mode:
            self.metrics.step_duration_seconds.labels(prev[2].name.lower()).observe(
                now - prev[3]
            )
        self._step_clock = (rs.height, rs.round, rs.step, now)
        tl = self._tl()
        if tl is not None:
            tl.record_step(rs.height, rs.round, rs.step.name)
            # also drop a point event into the flight-recorder ring so
            # /debug/trace interleaves consensus steps with verify spans
            _tracer.event(
                "consensus.step",
                height=rs.height, round=rs.round, step=rs.step.name,
            )

    def _mark_round(self, height: int, round_: int) -> None:
        """Round clock: observe the previous round's duration when the round
        escalates; _finalize_commit observes the committing round."""
        now = time.perf_counter()
        prev = self._round_clock
        if prev is not None and prev[0] == height and prev[1] == round_:
            return
        if (
            prev is not None and self.metrics is not None and not self.replay_mode
            and prev[0] == height and prev[1] < round_
        ):
            self.metrics.round_duration_seconds.observe(now - prev[2])
        self._round_clock = (height, round_, now)

    def _publish_rs(self, event_type: str) -> None:
        if self.event_bus is not None:
            self.event_bus.publish_round_state(
                event_type, self.rs.height, self.rs.round, self.rs.step.name
            )

    def _publish_vote(self, vote: Vote) -> None:
        self.event_bus.publish_vote(vote)

    def _publish_votes(self, votes: List[Vote]) -> None:
        """Batch form used by the deferred-vote drain: one subscriber-match
        pass for the whole batch (EventBus.publish_votes), and — like all
        vote publishes — free when nobody subscribed to Vote events."""
        if votes:
            self.event_bus.publish_votes(votes)

    # ------------------------------------------------------------------
    # step: new round (reference: consensus/state.go:907)
    # ------------------------------------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStepType.NEW_HEIGHT
        ):
            return
        logger.info("enterNewRound(%s/%s)", height, round_)

        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)

        self._mark_round(height, round_)
        rs.round = round_
        rs.step = RoundStepType.NEW_ROUND
        rs.validators = validators
        if round_ != 0:
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)  # track next round too
        rs.triggered_timeout_precommit = False
        self._mark_step()  # NEW_ROUND has no _new_step of its own
        if self.metrics is not None and not self.replay_mode:
            self.metrics.rounds.set(round_)
        self._publish_rs(EVENT_NEW_ROUND)

        wait_for_txs = (
            self.config.wait_for_txs() and round_ == 0 and not self._need_proof_block(height)
            and self.tx_notifier is not None and self.tx_notifier.size() == 0
        )
        if wait_for_txs:
            if self.config.create_empty_blocks_interval > 0:
                self._schedule_timeout(
                    self.config.create_empty_blocks_interval, height, round_, RoundStepType.NEW_ROUND
                )
        else:
            self._enter_propose(height, round_)

    def _need_proof_block(self, height: int) -> bool:
        if height == self.state.initial_height:
            return True
        last_meta = self.block_store.load_block_meta(height - 1)
        if last_meta is None:
            return True
        last_block = self.block_store.load_block(height - 1)
        return self.state.app_hash != last_block.header.app_hash

    # ------------------------------------------------------------------
    # step: propose (reference: consensus/state.go:989)
    # ------------------------------------------------------------------

    def _enter_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStepType.PROPOSE
        ):
            return
        logger.info("enterPropose(%s/%s)", height, round_)

        try:
            self._schedule_timeout(
                self.config.propose_timeout(round_), height, round_, RoundStepType.PROPOSE
            )
            if self.priv_validator is None or self.priv_validator_pub_key is None:
                return
            address = self.priv_validator_pub_key.address()
            if not rs.validators.has_address(address):
                return
            if rs.validators.get_proposer().address == address:
                logger.info("enterPropose: our turn to propose")
                self.decide_proposal(height, round_)
        finally:
            rs.round = round_
            rs.step = RoundStepType.PROPOSE
            self._new_step()
            if self._is_proposal_complete():
                self._enter_prevote(height, rs.round)

    def _default_decide_proposal(self, height: int, round_: int) -> None:
        """(reference: consensus/state.go:1061 defaultDecideProposal)"""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            block, block_parts = self._create_proposal_block()
            if block is None:
                return
        self.wal.flush_and_sync()

        block_id = BlockID(block.hash(), block_parts.header)
        proposal = Proposal(
            height=height, round=round_, pol_round=rs.valid_round,
            block_id=block_id, timestamp_ns=time.time_ns(),
        )
        try:
            proposal = self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:
            if not self.replay_mode:
                logger.error("enterPropose: error signing proposal: %s", e)
            return
        m = self._live_metrics()
        if m is not None:
            m.proposal_create_count.inc()
        self.send_internal(ProposalMessage(proposal))
        for i in range(block_parts.total):
            self.send_internal(BlockPartMessage(height, round_, block_parts.get_part(i)))
        logger.info("signed proposal %s/%s %s", height, round_, block.hash().hex()[:12])

    def _create_proposal_block(self):
        rs = self.rs
        if rs.height == self.state.initial_height:
            from tendermint_tpu.types.block import Commit as CommitT

            commit = CommitT(0, 0, BlockID(), ())
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            commit = rs.last_commit.make_commit()
        else:
            logger.error("propose step; cannot propose anything without commit for the previous block")
            return None, None
        proposer_addr = self.priv_validator_pub_key.address()
        block = self.block_exec.create_proposal_block(
            rs.height, self.state, commit, proposer_addr, time.time_ns()
        )
        parts = PartSet.from_data(block.encode())
        return block, parts

    def _is_proposal_complete(self) -> bool:
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    # ------------------------------------------------------------------
    # proposal / block part intake
    # ------------------------------------------------------------------

    def _set_proposal(self, proposal: Proposal) -> None:
        """(reference: consensus/state.go defaultSetProposal :1692)"""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (proposal.pol_round >= 0 and proposal.pol_round >= proposal.round):
            m = self._live_metrics()
            if m is not None:
                m.proposal_receive_count.labels("rejected").inc()
            raise VoteSetError("error invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            m = self._live_metrics()
            if m is not None:
                m.proposal_receive_count.labels("rejected").inc()
            raise VoteSetError("error invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(proposal.block_id.part_set_header)
        m = self._live_metrics()
        if m is not None:
            m.proposal_receive_count.labels("accepted").inc()
        tl = self._tl()
        if tl is not None:
            tl.record_proposal(proposal.height, proposal.round)
        logger.info("received proposal %s", proposal.height)

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> None:
        """(reference: consensus/state.go:1751 addProposalBlockPart)"""
        rs = self.rs
        if msg.height != rs.height:
            return
        if rs.proposal_block_parts is None:
            return
        try:
            added = rs.proposal_block_parts.add_part(msg.part)
        except ValueError as e:
            if msg.round != rs.round:
                return
            raise
        if not added:
            return
        if rs.proposal_block_parts.is_complete():
            data = rs.proposal_block_parts.assemble()
            rs.proposal_block = Block.decode(data)
            logger.info("received complete proposal block %s %s", rs.proposal_block.header.height,
                        rs.proposal_block.hash().hex()[:12])
            # tx lifecycle: every tracked tx of the now-complete proposal is
            # `proposed` (our own proposals land here too — their parts ride
            # internal BlockPartMessages through this same path)
            self._track_block_txs("proposed", rs.height, rs.round, rs.proposal_block)
            self._publish_rs(EVENT_COMPLETE_PROPOSAL)

            prevotes = rs.votes.prevotes(rs.round)
            block_id = prevotes.two_thirds_majority() if prevotes else None
            if block_id is not None and not block_id.is_zero() and rs.valid_round < rs.round:
                if rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = rs.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts

            if rs.step <= RoundStepType.PROPOSE and self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)
            elif rs.step == RoundStepType.COMMIT:
                self._try_finalize_commit(rs.height)

    # ------------------------------------------------------------------
    # step: prevote (reference: consensus/state.go:1160)
    # ------------------------------------------------------------------

    def _enter_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStepType.PREVOTE
        ):
            return
        logger.info("enterPrevote(%s/%s)", height, round_)
        self.do_prevote(height, round_)
        rs.round = round_
        rs.step = RoundStepType.PREVOTE
        self._new_step()

    def _default_do_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(SignedMsgType.PREVOTE, rs.locked_block.hash(), rs.locked_block_parts.header)
            return
        if rs.proposal_block is None:
            self._sign_add_vote(SignedMsgType.PREVOTE, b"", PartSetHeader())
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except (BlockValidationError, Exception) as e:
            logger.error("enterPrevote: ProposalBlock is invalid: %s", e)
            self._sign_add_vote(SignedMsgType.PREVOTE, b"", PartSetHeader())
            return
        self._sign_add_vote(
            SignedMsgType.PREVOTE, rs.proposal_block.hash(), rs.proposal_block_parts.header
        )

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStepType.PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise RuntimeError(f"enterPrevoteWait({height}/{round_}) without +2/3 prevotes")
        rs.round = round_
        rs.step = RoundStepType.PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(
            self.config.prevote_timeout(round_), height, round_, RoundStepType.PREVOTE_WAIT
        )

    # ------------------------------------------------------------------
    # step: precommit — the locking rules (reference: consensus/state.go:1255)
    # ------------------------------------------------------------------

    def _enter_precommit(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStepType.PRECOMMIT
        ):
            return
        logger.info("enterPrecommit(%s/%s)", height, round_)

        try:
            prevotes = rs.votes.prevotes(round_)
            block_id = prevotes.two_thirds_majority() if prevotes else None

            # No polka: precommit nil.
            if block_id is None:
                self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", PartSetHeader())
                return

            self._publish_rs(EVENT_POLKA)
            pol_round, _ = rs.votes.pol_info()
            if pol_round < round_:
                raise RuntimeError(f"POLRound should be {round_} but got {pol_round}")

            # +2/3 prevoted nil: unlock and precommit nil.
            if block_id.is_zero():
                if rs.locked_block is not None:
                    rs.locked_round = -1
                    rs.locked_block = None
                    rs.locked_block_parts = None
                self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", PartSetHeader())
                return

            # Already locked on that block: relock.
            if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
                rs.locked_round = round_
                self._publish_rs(EVENT_LOCK)
                self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id.hash, block_id.part_set_header)
                return

            # Polka for our proposal block: lock it.
            if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                self.block_exec.validate_block(self.state, rs.proposal_block)  # panics if invalid
                rs.locked_round = round_
                rs.locked_block = rs.proposal_block
                rs.locked_block_parts = rs.proposal_block_parts
                self._publish_rs(EVENT_LOCK)
                self._sign_add_vote(SignedMsgType.PRECOMMIT, block_id.hash, block_id.part_set_header)
                return

            # Polka for a block we don't have: unlock, fetch, precommit nil.
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.part_set_header
            ):
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet(block_id.part_set_header)
            self._sign_add_vote(SignedMsgType.PRECOMMIT, b"", PartSetHeader())
        finally:
            rs.round = round_
            rs.step = RoundStepType.PRECOMMIT
            self._new_step()

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise RuntimeError(f"enterPrecommitWait({height}/{round_}) without +2/3 precommits")
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(
            self.config.precommit_timeout(round_), height, round_, RoundStepType.PRECOMMIT_WAIT
        )

    # ------------------------------------------------------------------
    # step: commit (reference: consensus/state.go:1394)
    # ------------------------------------------------------------------

    def _enter_commit(self, height: int, commit_round: int) -> None:
        rs = self.rs
        if rs.height != height or rs.step >= RoundStepType.COMMIT:
            return
        logger.info("enterCommit(%s/%s)", height, commit_round)
        try:
            precommits = rs.votes.precommits(commit_round)
            block_id = precommits.two_thirds_majority()
            if block_id is None:
                raise RuntimeError("enterCommit expects +2/3 precommits")
            if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
                rs.proposal_block = rs.locked_block
                rs.proposal_block_parts = rs.locked_block_parts
            if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
                if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                    block_id.part_set_header
                ):
                    rs.proposal_block = None
                    rs.proposal_block_parts = PartSet(block_id.part_set_header)
                    self._publish_rs(EVENT_VALID_BLOCK)
        finally:
            rs.step = RoundStepType.COMMIT
            rs.commit_round = commit_round
            rs.commit_time_ns = time.time_ns()
            self._new_step()
            self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height:
            raise RuntimeError("tryFinalizeCommit() height mismatch")
        precommits = rs.votes.precommits(rs.commit_round)
        block_id = precommits.two_thirds_majority() if precommits else None
        if block_id is None or block_id.is_zero():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return  # don't have the block yet; keep waiting
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """(reference: consensus/state.go:1489 finalizeCommit)"""
        rs = self.rs
        if rs.height != height or rs.step != RoundStepType.COMMIT:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        block_id = precommits.two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if block_id is None:
            raise RuntimeError("cannot finalize commit: no 2/3 majority")
        if not block_parts.has_header(block_id.part_set_header):
            raise RuntimeError("expected ProposalBlockParts header to be commit header")
        if block.hash() != block_id.hash:
            raise RuntimeError("cannot finalize commit: proposal block does not hash to commit hash")
        _tv0 = time.perf_counter()
        self.block_exec.validate_block(self.state, block)
        _tv1 = time.perf_counter()
        if _tracer.enabled:
            _tracer.event(
                "consensus.commit_verify",
                height=height,
                n_sigs=len(block.last_commit.signatures),
                dur_ms=round((_tv1 - _tv0) * 1e3, 3),
            )

        logger.info("finalizing commit of block %d txs=%d hash=%s",
                    block.header.height, len(block.txs), block.hash().hex()[:12])
        tl = self._tl()
        if tl is not None:
            tl.record_commit(height, rs.commit_round, txs=len(block.txs))
        self._track_block_txs("committed", height, rs.commit_round, block)
        if self.metrics is not None:
            m = self.metrics
            if (
                not self.replay_mode
                and self._round_clock is not None
                and self._round_clock[:2] == (height, rs.commit_round)
            ):
                # replay re-runs commits at replay speed, and a commit of an
                # EARLIER round after escalation (late precommits) belongs
                # to a round the clock no longer tracks — both would record
                # bogus near-zero samples in the low buckets
                m.round_duration_seconds.observe(
                    time.perf_counter() - self._round_clock[2]
                )
            m.commit_verify_seconds.observe(_tv1 - _tv0)
            m.num_txs.set(len(block.txs))
            m.total_txs.inc(len(block.txs))
            m.block_size_bytes.set(block_parts.byte_size)
            m.rounds.set(rs.round)
            vals = rs.validators
            m.validators.set(vals.size())
            m.validators_power.set(vals.total_voting_power())
            missing = sum(1 for cs_ in block.last_commit.signatures if not cs_.for_block())
            m.missing_validators.set(missing)
            m.byzantine_validators.set(len(block.evidence))
            if self.state.last_block_height > 0:
                m.block_interval_seconds.observe(
                    max(0.0, (block.header.time_ns - self.state.last_block_time_ns) / 1e9)
                )
        if (
            self.slo is not None and not self.replay_mode
            and self.state.last_block_height > 0
        ):
            self.slo.observe(
                "commit_interval",
                max(0.0, (block.header.time_ns - self.state.last_block_time_ns) / 1e9),
            )
        fail.fail_point("cs_before_save_block")
        if self.block_store.height < block.header.height:
            seen_commit = precommits.make_commit()
            self.block_store.save_block(block, block_parts, seen_commit)
        fail.fail_point("cs_after_save_block")

        # EndHeight marker: blockstore has the block; recovery runs ApplyBlock
        # via handshake if we crash after this point.
        self.wal.write_end_height(height)
        if tl is not None:
            tl.record_end_height(height)
        fail.fail_point("cs_after_wal_endheight")

        state_copy = self.state.copy()
        new_state = self.block_exec.apply_block(
            state_copy, BlockID(block.hash(), block_parts.header), block
        )
        fail.fail_point("cs_after_apply_block")

        self._update_to_state(new_state)
        if self.metrics is not None:
            self.metrics.height.set(new_state.last_block_height)
        if self.priv_validator is not None:
            self.priv_validator_pub_key = self.priv_validator.get_pub_key()
        self._schedule_round0()

    # ------------------------------------------------------------------
    # votes
    # ------------------------------------------------------------------

    def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """(reference: consensus/state.go:1829 tryAddVote + :1880 addVote)"""
        try:
            return self._add_vote(vote, peer_id)
        except ConflictingVotesError as e:
            self._handle_vote_conflict(e)
            return False
        except VoteSetError as e:
            logger.debug("vote not added: %s", e)
            return False

    def _handle_vote_conflict(self, e: ConflictingVotesError) -> None:
        """Turn an equivocation into DuplicateVoteEvidence (also called by the
        deferred-verification flush, which surfaces conflicts in batches;
        reference: consensus/state.go:1829 tryAddVote's ErrVoteConflictingVotes
        branch)."""
        vote = e.vote_b
        if self.priv_validator_pub_key is not None and (
            vote.validator_address == self.priv_validator_pub_key.address()
        ):
            logger.error("found conflicting vote from ourselves; did you unsafe_reset a validator?")
            return
        if self.evpool is not None:
            _, val = self.rs.validators.get_by_address(vote.validator_address)
            ev = DuplicateVoteEvidence.from_votes(
                e.vote_a, e.vote_b, self.state.last_block_time_ns,
                self.rs.validators.total_voting_power(),
                val.voting_power if val else 0,
            )
            fail.fail_point("cs_evidence_from_consensus")
            try:
                self.evpool.add_evidence_from_consensus(
                    ev, time.time_ns(), self.rs.validators
                )
            except Exception as err:
                # The pool verifies before accepting (evidence/pool.py); a
                # rejected add means the evidence would never survive peer
                # validation anyway — log loudly, keep consensus running.
                logger.error(
                    "evidence pool rejected consensus-discovered equivocation "
                    "by %s at %d/%d: %s",
                    vote.validator_address.hex()[:12], vote.height, vote.round, err,
                )

    def _flush_deferred_votes(self) -> None:
        """Deferred-verification tick: batch-verify all queued votes in one
        device call, surface equivocations as evidence, and re-run the 2/3
        progress checks for every (type, round) that gained votes.

        This is the consensus-side half of config.defer_vote_verification —
        under a vote storm each flush is ONE batched kernel invocation over
        the validator axis instead of per-vote scalar verifies (the
        vectorized analog of the reference's per-vote path,
        types/vote_set.go:143,203).

        Rows that verify OK here also land in the cross-flush verified-row
        memo (crypto/batch.VerifiedRowMemo): when this height commits, the
        seen-commit's verify_commit re-presents the same (pubkey, msg, sig)
        tuples and resolves them from the memo instead of re-flushing, so
        the commit path only pays device time for signatures that were never
        deferred-verified in the first place."""
        rs = self.rs
        if rs.votes is not None and rs.votes.has_pending():
            tr = _tracer if _tracer.enabled else None
            span = None
            if tr is not None:
                span = tr.span("consensus.vote_flush", height=rs.height)
                span.__enter__()
            try:
                height_before = rs.height
                votes_before = rs.votes
                flushed = votes_before.flush_all()
                for err in votes_before.drain_conflicts():
                    self._handle_vote_conflict(err)
                if span is not None:
                    span.set(
                        committed=sum(len(c) for _, _, c, _ in flushed),
                        failed=sum(len(f) for _, _, _, f in flushed),
                    )
            finally:
                # always close: a raise between enter and here would corrupt
                # the tracer's thread-local span stack for the whole loop —
                # and pass the live exception so the span records error=...
                if span is not None:
                    import sys as _sys

                    span.__exit__(*_sys.exc_info())
            for vtype, vround, committed, failed in flushed:
                # Publish only now: enqueue time would advertise (HasVote)
                # signatures we have not verified, letting a forged vote
                # suppress gossip of the genuine one.
                self._publish_votes(committed)
                if failed:
                    logger.warning(
                        "deferred flush: %d invalid %s signatures at round %d",
                        len(failed), vtype.name, vround,
                    )
                # A progress check can COMMIT the block and advance the
                # height, replacing rs.votes with a fresh HeightVoteSet; the
                # remaining (type, round) pairs belong to the finished height
                # and must not be re-checked against the new one.
                if rs.height != height_before:
                    break
                self._check_progress_after_vote(vtype, vround)
        if rs.last_commit is not None and rs.last_commit.pending_count() > 0:
            committed, _failed = rs.last_commit.flush()
            self._publish_votes(committed)
            for err in rs.last_commit.pop_conflicts():
                self._handle_vote_conflict(err)
            if self.config.skip_timeout_commit and rs.last_commit.has_all():
                self._enter_new_round(rs.height, 0)

    def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        rs = self.rs
        # Late precommit for the previous height (during commit timeout).
        if vote.height + 1 == rs.height and vote.type == SignedMsgType.PRECOMMIT:
            if rs.step != RoundStepType.NEW_HEIGHT:
                m = self._live_metrics()
                if m is not None:
                    m.late_votes.labels(vote.type.name.lower()).inc()
                return False
            if rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if not added:
                m = self._live_metrics()
                if m is not None:
                    m.duplicate_votes.inc()
                return False
            if added != "pending":  # unverified: published at flush instead
                self._publish_vote(vote)
            if self.config.skip_timeout_commit and rs.last_commit.has_all():
                self._enter_new_round(rs.height, 0)
            return True

        if vote.height != rs.height:
            m = self._live_metrics()
            if vote.height < rs.height and m is not None:
                m.late_votes.labels(vote.type.name.lower()).inc()
            return False

        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            # VoteSet.add_vote returns falsy ONLY for exact duplicates
            # (same validator, block, signature) — everything else raises
            m = self._live_metrics()
            if m is not None:
                m.duplicate_votes.inc()
            return False
        tl = self._tl()
        if tl is not None:
            tl.record_vote(vote.height, vote.round, vote.type.name)
        if added == "pending":
            # Deferred verification: the vote is queued, not verified — do
            # NOT publish (the reactor would broadcast HasVote and peers
            # would stop gossiping the genuine vote). flush publishes the
            # ones that verify.
            return True
        self._publish_vote(vote)
        self._check_progress_after_vote(vote.type, vote.round)
        return True

    def _check_progress_after_vote(self, vtype: SignedMsgType, vround: int) -> None:
        """Run the 2/3-majority state transitions for one (type, round).

        Factored out of _add_vote so the deferred-verification flush can
        re-run the checks after a batch of votes commits at once
        (reference: consensus/state.go:1880 addVote's post-add logic)."""
        rs = self.rs
        height = rs.height
        # Rounds beyond the tracked window (set_round tracks round..round+1)
        # have no vote set; nothing to check.
        if rs.votes is None or rs.votes._get_vote_set(vround, vtype) is None:
            return
        if vtype == SignedMsgType.PREVOTE:
            prevotes = rs.votes.prevotes(vround)
            block_id = prevotes.two_thirds_majority()
            self._mark_prevote_delays(prevotes, vround, block_id)
            if block_id is not None:
                # Unlock on newer polka for a different block.
                if (
                    rs.locked_block is not None
                    and rs.locked_round < vround <= rs.round
                    and rs.locked_block.hash() != block_id.hash
                ):
                    logger.info("unlocking because of POL")
                    rs.locked_round = -1
                    rs.locked_block = None
                    rs.locked_block_parts = None
                # Update valid block.
                if not block_id.is_zero() and rs.valid_round < vround == rs.round:
                    if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                        rs.valid_round = vround
                        rs.valid_block = rs.proposal_block
                        rs.valid_block_parts = rs.proposal_block_parts
                    else:
                        rs.proposal_block = None
                    if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                        block_id.part_set_header
                    ):
                        rs.proposal_block_parts = PartSet(block_id.part_set_header)
                    self._publish_rs(EVENT_VALID_BLOCK)

            if rs.round < vround and prevotes.has_two_thirds_any():
                self._enter_new_round(height, vround)
            elif rs.round == vround and rs.step >= RoundStepType.PREVOTE:
                block_id = prevotes.two_thirds_majority()
                if block_id is not None and (self._is_proposal_complete() or block_id.is_zero()):
                    self._enter_precommit(height, vround)
                elif prevotes.has_two_thirds_any():
                    self._enter_prevote_wait(height, vround)
            elif rs.proposal is not None and 0 <= rs.proposal.pol_round == vround:
                if self._is_proposal_complete():
                    self._enter_prevote(height, rs.round)

        elif vtype == SignedMsgType.PRECOMMIT:
            precommits = rs.votes.precommits(vround)
            block_id = precommits.two_thirds_majority()
            if block_id is not None:
                self._enter_new_round(height, vround)
                self._enter_precommit(height, vround)
                if not block_id.is_zero():
                    self._enter_commit(height, vround)
                    if self.config.skip_timeout_commit and precommits.has_all():
                        self._enter_new_round(rs.height, 0)
                else:
                    self._enter_precommit_wait(height, vround)
            elif rs.round <= vround and precommits.has_two_thirds_any():
                self._enter_new_round(height, vround)
                self._enter_precommit_wait(height, vround)

    def _live_metrics(self):
        """Metrics sink, muted during WAL replay — catchup re-processes old
        messages at replay speed and must not re-count them."""
        return None if self.replay_mode else self.metrics

    def _mark_prevote_delays(self, prevotes, vround: int, block_id) -> None:
        """quorum_prevote_delay / full_prevote_delay: seconds from the
        proposal's signed timestamp to 2/3 (resp. all) prevote arrival
        (reference: CometBFT consensus/state.go addVote's
        QuorumPrevoteDelay/FullPrevoteDelay gauges). Recorded once per
        (height, round) so trailing prevotes don't inflate the value."""
        rs = self.rs
        if (
            (self.metrics is None and self.slo is None) or self.replay_mode
            or rs.proposal is None or rs.proposal.round != vround
        ):
            return
        delay = max(0.0, (time.time_ns() - rs.proposal.timestamp_ns) / 1e9)
        key = (rs.height, vround)
        if block_id is not None and self._quorum_prevote_marked != key:
            self._quorum_prevote_marked = key
            if self.metrics is not None:
                self.metrics.quorum_prevote_delay.set(delay)
            if self.slo is not None:
                self.slo.observe("prevote_quorum_delay", delay)
        if prevotes.has_all() and self._full_prevote_marked != key:
            self._full_prevote_marked = key
            if self.metrics is not None:
                self.metrics.full_prevote_delay.set(delay)

    def _sign_vote(self, msg_type: SignedMsgType, block_hash: bytes, psh: PartSetHeader) -> Optional[Vote]:
        rs = self.rs
        if self.priv_validator_pub_key is None:
            return None
        addr = self.priv_validator_pub_key.address()
        idx, _ = rs.validators.get_by_address(addr)
        if idx < 0:
            return None
        vote = Vote(
            type=msg_type,
            height=rs.height,
            round=rs.round,
            block_id=BlockID(block_hash, psh),
            timestamp_ns=self._vote_time(),
            validator_address=addr,
            validator_index=idx,
        )
        try:
            return self.priv_validator.sign_vote(self.state.chain_id, vote)
        except Exception as e:
            if not self.replay_mode:
                logger.error("failed signing vote: %s", e)
            return None

    def _vote_time(self) -> int:
        """Monotonic vote time: max(now, last block time + 1ms)
        (reference: consensus/state.go voteTime)."""
        now = time.time_ns()
        min_time = self.state.last_block_time_ns + 1_000_000
        return max(now, min_time)

    def _sign_add_vote(self, msg_type: SignedMsgType, block_hash: bytes, psh: PartSetHeader) -> Optional[Vote]:
        if self.priv_validator is None or self.replay_mode:
            return None
        if not self.rs.validators.has_address(self.priv_validator_pub_key.address()):
            return None
        vote = self._sign_vote(msg_type, block_hash, psh)
        if vote is not None:
            self.send_internal(VoteMessage(vote))
        return vote

    # ------------------------------------------------------------------
    # WAL catchup replay (reference: consensus/replay.go:93 catchupReplay)
    # ------------------------------------------------------------------

    def _catchup_replay(self, cs_height: int) -> None:
        if self.wal.search_for_end_height(cs_height) is not None:
            raise RuntimeError(f"WAL should not contain #ENDHEIGHT {cs_height}")
        msgs = self.wal.search_for_end_height(cs_height - 1)
        if msgs is None:
            return  # nothing to replay
        self.replay_mode = True
        try:
            for msg in msgs:
                if isinstance(msg, MsgInfo):
                    # Read-only replay: the messages are already durable in
                    # the WAL (reference: consensus/replay.go:93 catchupReplay
                    # only reads; re-writing would grow the WAL every restart).
                    try:
                        self._handle_msg(msg)
                    except Exception as e:
                        logger.error("replay: msg failed: %s", e)
                elif isinstance(msg, TimeoutInfo):
                    pass  # timeouts are rescheduled naturally
                elif isinstance(msg, EventRoundState):
                    pass
        finally:
            self.replay_mode = False
        logger.info("replayed WAL messages for height %d", cs_height)
