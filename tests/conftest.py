"""Test configuration.

Must run before jax initializes: force the CPU platform with 8 virtual devices
so multi-chip sharding paths (jax.sharding.Mesh over 8 devices) are exercised
without TPU hardware. Real-TPU benchmarking goes through bench.py, which does
not import this file.
"""

import os

# Force CPU even if the ambient environment points at a TPU (e.g.
# JAX_PLATFORMS=axon); override with TMTPU_TEST_PLATFORM to test on hardware.
os.environ["JAX_PLATFORMS"] = os.environ.get("TMTPU_TEST_PLATFORM", "cpu")

_platform = os.environ.get("TMTPU_TEST_PLATFORM", "cpu")

# Persistent compilation cache: the ed25519 scan kernel is expensive to compile
# on CPU; cache it across pytest runs.
# CPU-backend cache lives in its own subdirectory: sharing one dir with the
# TPU bench/tools processes produced entries that CRASHED (SIGSEGV/SIGABRT)
# the cache READ path in concurrent sessions (observed r4, twice, both in
# compilation_cache.get_executable_and_time).
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache", _platform),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The env vars alone are NOT enough: an injected sitecustomize (axon tooling)
# imports jax at interpreter start — before this file runs — so jax has
# already read its config env vars (tests silently ran against the TPU
# tunnel, and the persistent-cache vars were ignored, leaving .jax_cache
# empty and every run cold-compiling for ~40 minutes). jax.config.update
# works post-import — force all of it.
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)
_cache_dir = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache", _platform)
)
if _platform == "cpu":
    # XLA:CPU executables bake in the COMPILE host's CPU features; a cache
    # shared across heterogeneous machines produced cpu_aot_loader
    # machine-feature-mismatch failures (MULTICHIP_r05). Scope per machine.
    from tendermint_tpu.ops.cache_hardening import machine_scoped_cache_dir

    _cache_dir = machine_scoped_cache_dir(_cache_dir)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# Atomic cache-entry writes: an OOM-killed test run must never leave a
# truncated executable for the next process to SIGSEGV on (the r4 failure
# mode; see ops/cache_hardening.py).
from tendermint_tpu.ops import cache_hardening  # noqa: E402

cache_hardening.harden()


try:
    import cryptography  # noqa: F401

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # minimal containers: crypto/keys.py falls back to the
    HAVE_CRYPTOGRAPHY = False  # pure-Python ed25519 (see keys._HAVE_OPENSSL)

import pytest  # noqa: E402

# For tests that need the `cryptography` wheel itself (p2p secret
# connection, armor's ChaCha/Scrypt, signer-socket auth) or its OpenSSL
# speed — the pure-Python fallback can't stand in for those.
requires_cryptography = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="needs the `cryptography` wheel (OpenSSL)",
)


@pytest.fixture(autouse=True)
def _verified_memo_off():
    """The cross-flush verified-row memo (crypto/batch.py ISSUE 18) is
    process-global state that changes which flushes run device work — a
    repeat verify of the same rows answers from the memo. Tests assert
    path/flush-count behavior on exactly such repeats, so each test runs
    with the memo DISABLED unless it installs one itself
    (configure_verified_memo / node config)."""
    from tendermint_tpu.crypto import batch

    prev = batch._MEMO
    batch._MEMO = batch.VerifiedRowMemo(0)
    yield
    batch._MEMO = prev


def free_compile_memory() -> None:
    """Drop every previously-compiled executable in this process. Used as a
    module fixture by the heavyweight kernel test modules: XLA ABORTED
    (SIGABRT in backend_compile r4, in the persistent-cache read path r5)
    compiling/deserializing their multi-hundred-MB executables in a process
    already holding many earlier tests' executables. Later tests reload
    from the persistent cache."""
    import gc

    import jax as _jax

    _jax.clear_caches()
    gc.collect()
