"""Fused MSM pipeline stages as Pallas TPU kernels (one packed limb layout).

Why this exists (PERF.md rounds 4-6): the Pippenger MSM's curve arithmetic
is ~10 ms of Pallas kernels at 10k validators, but the PIPELINE around it
burns ~3-4x that in HBM traffic — every tree level materializes through HBM
between per-level `padd` calls, every Pallas wrapper re-packs (stack +
reshape + pad) its inputs and unpacks its outputs, and the stride-2
even/odd halving slices relayout each level before the kernel even starts.
This module removes the inter-kernel traffic for the three memory-bound MSM
stages by (a) standardizing ONE packed layout — int32[4, NL, S, 128], limb
rows split into (sublane-group, 128-lane) tiles, the same layout
ops/pallas_fe.py uses INSIDE its kernels — across kernel boundaries, and
(b) fusing whole stages into single kernels that keep every intermediate
level in VMEM:

  uptree          chunk-local pair-tree up-sweep: one kernel computes ALL
                  tree levels of a 2048-lane (or 1024-lane) chunk in VMEM
                  and writes the concatenated levels once. Lanes arrive
                  BIT-REVERSED within each chunk (the host perm composes the
                  reversal for free), which turns the stride-2 even/odd
                  pairing into contiguous-half adds: fold(v) = first half +
                  second half, expressible as offset-0 slices + tpu rolls —
                  no in-kernel shuffle-heavy strided slicing, no per-level
                  HBM round trip.
  fenwick_reduce  the Fenwick prefix extraction: the K gathered tree nodes
                  per bucket boundary reduce in-kernel via the standard
                  grid-accumulation pattern (output block revisited across
                  the K grid steps) — the unfused form materialized a
                  (T, 256, K) point tensor and five padd levels through HBM.
  bucket_fold     the weighted bucket sum's big reduction: masks bucket 255,
                  folds the 256*T prefix points (v-major layout) down to
                  per-window sums, and extracts P_255 — one kernel replacing
                  eight padd calls + slice plumbing.

Pairing correctness relies on the bit-reversal invariant: placing sorted
lane j of a chunk at physical position rev(j) makes every fold level
"first half + second half" compute exactly the aligned-block sums the
Fenwick decomposition needs, with level-l node k stored at position
rev_{lc-l}(k) (fused_node_position below; lc = log2(chunk)). Chunks are
powers of two even though lane buckets are not — any bucket divisible by
1024 fuses (all production buckets; smaller batches keep the unfused path).

Every stage has a pure-jnp twin selected when Pallas is off: the SAME fold
schedule over the SAME packed layout, but with the compact fe25519/XLA point
add instead of the in-kernel row convolution (the row math traces to ~8k HLO
per point add — fine inside one Mosaic kernel, a compile-memory explosion as
an XLA:CPU graph; PERF.md "what was tried and rejected"). Schedule equality
between kernel body and twin is pinned by running both with a mocked integer
add (tests/test_fused_msm.py), and the row math itself is pinned to the fe
ops by tests/test_pallas_fe.py — so the CPU differential covers the fused
schedule end to end without the Mosaic interpreter.

Enabled with ops/pallas_fe.py (TMTPU_PALLAS); the pipeline-level flag lives
in ops/msm_jax.py (TMTPU_FUSED_MSM).
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tendermint_tpu.ops import fe25519 as fe
from tendermint_tpu.ops import pallas_fe
from tendermint_tpu.ops.pallas_fe import LANE, NL, _padd_rows

# Observability counters (tests/test_flush_budget.py pins these): layout
# conversions between the packed kernel layout and limb-major, per process.
# The whole point of the packed pipeline is that these do NOT scale with
# the number of point-op calls.
LAYOUT_CONVERSIONS = [0]


def chunk_for_lanes(n_lanes: int) -> int | None:
    """Largest supported chunk that tiles n_lanes, or None (-> unfused).
    2048 preferred (deeper in-VMEM tree); 1024 covers the Na=1536 bucket."""
    for ch in (2048, 1024):
        if n_lanes >= ch and n_lanes % ch == 0:
            return ch
    return None


# ---------------------------------------------------------------------------
# Bit reversal (host + device twins; m <= 11 bits).


def brev_np(x: np.ndarray, m: int) -> np.ndarray:
    x = x.astype(np.int64)
    r = np.zeros_like(x)
    for b in range(m):
        r |= ((x >> b) & 1) << (m - 1 - b)
    return r


def _brev16_jnp(x: jnp.ndarray) -> jnp.ndarray:
    """Bit-reverse the low 16 bits of an int32 (elementwise)."""
    x = x & 0xFFFF
    x = ((x & 0x5555) << 1) | ((x >> 1) & 0x5555)
    x = ((x & 0x3333) << 2) | ((x >> 2) & 0x3333)
    x = ((x & 0x0F0F) << 4) | ((x >> 4) & 0x0F0F)
    x = ((x & 0x00FF) << 8) | ((x >> 8) & 0x00FF)
    return x


def brev_jnp(x: jnp.ndarray, m) -> jnp.ndarray:
    """rev_m(x) for m bits; m may be a (broadcastable) array of bit counts."""
    return _brev16_jnp(x) >> (16 - jnp.asarray(m, dtype=jnp.int32))


@functools.lru_cache(maxsize=32)
def brev_positions(n_lanes: int, ch: int) -> np.ndarray:
    """Within-window gather order for the fused tree: position p reads the
    sorted lane (p & ~(ch-1)) | rev(p & (ch-1)) — so each chunk's lanes land
    bit-reversed and every fold level pairs contiguous halves."""
    lc = ch.bit_length() - 1
    i = np.arange(n_lanes, dtype=np.int64)
    out = (i & ~(ch - 1)) | brev_np(i & (ch - 1), lc)
    return out.astype(np.int32)


# ---------------------------------------------------------------------------
# Chunk-tree geometry. The uptree kernel writes, per chunk, the concatenated
# levels 1..lc as ROWS of 128 lanes: levels with width >= 128 are row-packed
# (width/128 rows, node at flat position q -> row q>>7, lane q&127); levels
# with width < 128 occupy one row each with the valid nodes in lanes
# [0, width) (roll-fold garbage beyond). Node (l, k) sits at position
# q = rev_{lc-l}(k) — see fused_node_position.


class ChunkGeometry(NamedTuple):
    ch: int  # lanes per chunk (power of two)
    lc: int  # log2(ch): levels computed in-kernel
    rows_in: int  # ch // 128
    rows_out: int  # output rows per chunk (padded to a multiple of 8)
    row_off: Tuple[int, ...]  # row_off[l] = first output row of level l (l>=1)


@functools.lru_cache(maxsize=8)
def chunk_geometry(ch: int) -> ChunkGeometry:
    lc = ch.bit_length() - 1
    assert ch == 1 << lc and ch >= 256
    offs = [0]  # index 0 unused (level 0 lives in the gather output)
    total = 0
    for lvl in range(1, lc + 1):
        offs.append(total)
        width = ch >> lvl
        total += max(width // LANE, 1)
    rows_out = -(-total // 8) * 8
    return ChunkGeometry(ch, lc, ch // LANE, rows_out, tuple(offs))


def fused_node_position(g: ChunkGeometry, lvl: int, k) -> "np.ndarray":
    """Flat in-level position of chunk-tree node k at level lvl (numpy)."""
    return brev_np(np.asarray(k), g.lc - lvl)


# ---------------------------------------------------------------------------
# Packed-layout conversions (the ONLY layout changes in the fused pipeline;
# each is one XLA transpose of contiguous data, not a per-point-op repack).


def rows_to_packed(rows: jnp.ndarray) -> jnp.ndarray:
    """(M, 4*NL) point rows -> packed (4, NL, M//128, 128). M % 128 == 0."""
    LAYOUT_CONVERSIONS[0] += 1
    m = rows.shape[0]
    return rows.T.reshape(4, NL, m // LANE, LANE)


def packed_to_rows(packed: jnp.ndarray) -> jnp.ndarray:
    """Packed (4, NL, R, 128) -> (R*128, 4*NL) point rows."""
    LAYOUT_CONVERSIONS[0] += 1
    r = packed.shape[2]
    return packed.reshape(4 * NL, r * LANE).T


# ---------------------------------------------------------------------------
# fe25519-based point add for the CPU twins (same unified a=-1 formula as
# msm_jax._padd; coordinates are 4-tuples of (NL, ...) arrays). The twins
# must NOT use the in-kernel row convolution: it inlines to ~8k HLO per add,
# which is the exact XLA:CPU compile explosion PERF.md documents.

_COMP_NP = np.asarray(fe.COMP)
_CORR_NP = np.asarray(fe.CORR)
_D2_NP = np.asarray(fe.from_int(fe.D2))


def _rs_c(c: np.ndarray, ndim: int) -> np.ndarray:
    return c.reshape((NL,) + (1,) * (ndim - 1))


def _fe_sub(a, b):
    return fe.sub(a, b, _rs_c(_COMP_NP, a.ndim), _rs_c(_CORR_NP, a.ndim))


def _padd_fe(p, q):
    """Unified extended add on 4-tuples of (NL, ...batch) coordinates."""
    a = fe.mul(_fe_sub(p[1], p[0]), _fe_sub(q[1], q[0]))
    b = fe.mul(fe.add(p[1], p[0]), fe.add(q[1], q[0]))
    c = fe.mul(fe.mul(p[3], q[3]), _rs_c(_D2_NP, p[3].ndim))
    d = fe.mul_small(fe.mul(p[2], q[2]), 2)
    e = _fe_sub(b, a)
    f = _fe_sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


# ---------------------------------------------------------------------------
# Fold primitives. Values inside kernels are per-coordinate lists of NL limb
# rows, each row a (R, 128) int32 — exactly pallas_fe's in-kernel form with
# a sublane-group axis. Folds pair position p with p + half:
#   sublane fold: (2h, 128) rows -> roll the top half down and add -> (h, 128)
#   lane fold:    one (1, 128) row -> roll lanes left by w and add; valid
#                 lanes shrink to [0, w) with garbage beyond (never indexed).
# Only offset-0 static slices and tpu rolls — no strided slicing in-kernel.


def _roll(real: bool, v, shift: int, axis: int):
    if shift == 0:
        return v
    if real:
        return pltpu.roll(v, shift, axis)
    return jnp.roll(v, shift, axis=axis)


def _fold_rows_coords(coords, h: int, real: bool):
    """coords: 4-tuple of NL-lists of (2h, 128) rows -> same with (h, 128):
    out[s] = v[s] + v[s + h] for s < h."""
    lo = tuple([r[:h] for r in rows] for rows in coords)
    hi = tuple([_roll(real, r, h, 0)[:h] for r in rows] for rows in coords)
    return _padd_rows(lo, hi)


def _fold_lanes_coords(coords, w: int, real: bool):
    """coords rows are (1, 128); out[q] = v[q] + v[q + w] for q < w."""
    rolled = tuple(
        [_roll(real, r, LANE - w, 1) for r in rows] for rows in coords
    )
    return _padd_rows(coords, rolled)


def _read_coords(block) -> Tuple[List, List, List, List]:
    return tuple([block[c, i] for i in range(NL)] for c in range(4))


def _stack_coords(coords) -> jnp.ndarray:
    return jnp.stack([jnp.stack(rows) for rows in coords])


# ---------------------------------------------------------------------------
# Stage 1: chunk-local pair-tree up-sweep.


def _uptree_block(block: jnp.ndarray, g: ChunkGeometry, real: bool) -> jnp.ndarray:
    """One chunk: (4, NL, rows_in, 128) bit-reversed level-0 lanes ->
    (4, NL, rows_out, 128) concatenated levels 1..lc (see chunk_geometry)."""
    cur = _read_coords(block)
    out_rows: List = [[] for _ in range(4)]  # per coord: list of NL row-lists

    def emit(coords):
        for c in range(4):
            out_rows[c].append(coords[c])

    rows = g.rows_in
    while rows > 1:  # levels down to width 128: sublane folds
        rows //= 2
        cur = _fold_rows_coords(cur, rows, real)
        emit(cur)
    w = LANE // 2  # remaining levels fold within the single (1, 128) row
    while w >= 1:
        cur = _fold_lanes_coords(cur, w, real)
        emit(cur)
        w //= 2
    # assemble: concat emitted levels per (coord, limb), zero-pad to rows_out
    used = sum(r[0].shape[0] for r in out_rows[0])
    pad = g.rows_out - used
    coords_out = []
    for c in range(4):
        limb_rows = []
        for i in range(NL):
            parts = [lvl[i] for lvl in out_rows[c]]
            if pad:
                parts.append(jnp.zeros((pad, LANE), jnp.int32))
            limb_rows.append(jnp.concatenate(parts, axis=0))
        coords_out.append(jnp.stack(limb_rows))
    return jnp.stack(coords_out)


def _uptree_kernel(g: ChunkGeometry):
    def kernel(x_ref, o_ref):
        o_ref[:] = _uptree_block(x_ref[:], g, real=not pallas_fe._interpret())

    return kernel


@functools.lru_cache(maxsize=64)
def _uptree_call(total_rows: int, ch: int):
    g = chunk_geometry(ch)
    nchunks = total_rows // g.rows_in
    return pl.pallas_call(
        _uptree_kernel(g),
        grid=(nchunks,),
        in_specs=[pl.BlockSpec((4, NL, g.rows_in, LANE), lambda i: (0, 0, i, 0))],
        out_specs=pl.BlockSpec((4, NL, g.rows_out, LANE), lambda i: (0, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (4, NL, nchunks * g.rows_out, LANE), jnp.int32
        ),
        interpret=pallas_fe._interpret(),
    )


def _uptree_jnp(lvl0_packed: jnp.ndarray, g: ChunkGeometry) -> jnp.ndarray:
    """CPU twin of _uptree_block over ALL chunks at once: identical fold
    schedule (slices for row folds, rolls for lane folds — garbage included,
    so outputs match the kernel positionally), fe25519 point math."""
    s = lvl0_packed.shape[2]
    nchunks = s // g.rows_in
    v = lvl0_packed.reshape(4, NL, nchunks, g.rows_in, LANE)
    cur = tuple(v[c] for c in range(4))  # (NL, nchunks, R, 128)
    levels = []
    rows = g.rows_in
    while rows > 1:
        rows //= 2
        cur = _padd_fe(
            tuple(c[:, :, :rows] for c in cur),
            tuple(c[:, :, rows:] for c in cur),
        )
        levels.append(cur)
    w = LANE // 2
    while w >= 1:
        rolled = tuple(jnp.roll(c, LANE - w, axis=-1) for c in cur)
        cur = _padd_fe(cur, rolled)
        levels.append(cur)
        w //= 2
    used = sum(lv[0].shape[2] for lv in levels)
    pad = jnp.zeros((NL, nchunks, g.rows_out - used, LANE), jnp.int32)
    out = jnp.stack(
        [
            jnp.concatenate([lv[c] for lv in levels] + [pad], axis=2)
            for c in range(4)
        ]
    )  # (4, NL, nchunks, rows_out, 128)
    return out.reshape(4, NL, nchunks * g.rows_out, LANE)


def uptree(lvl0_packed: jnp.ndarray, ch: int) -> jnp.ndarray:
    """Packed bit-reversed level-0 lanes (4, NL, S, 128), S*128 a multiple of
    ch -> packed chunk trees (4, NL, (S*128//ch)*rows_out, 128)."""
    g = chunk_geometry(ch)
    s = lvl0_packed.shape[2]
    assert s % g.rows_in == 0
    if pallas_fe.enabled():
        return _uptree_call(s, ch)(lvl0_packed)
    return _uptree_jnp(lvl0_packed, g)


# ---------------------------------------------------------------------------
# Stage 2: Fenwick prefix reduce — accumulate K gathered node planes.


def _padd_block(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _stack_coords(_padd_rows(_read_coords(a), _read_coords(b)))


def _fenwick_kernel(p_ref, o_ref):
    k = pl.program_id(1)
    node = p_ref[:][0]  # (4, NL, blk, 128)

    @pl.when(k == 0)
    def _init():
        o_ref[:] = node

    @pl.when(k != 0)
    def _acc():
        o_ref[:] = _padd_block(o_ref[:], node)


@functools.lru_cache(maxsize=64)
def _fenwick_call(kf: int, s: int, blk: int):
    return pl.pallas_call(
        _fenwick_kernel,
        grid=(s // blk, kf),
        in_specs=[
            pl.BlockSpec((1, 4, NL, blk, LANE), lambda c, k: (k, 0, 0, c, 0))
        ],
        out_specs=pl.BlockSpec((4, NL, blk, LANE), lambda c, k: (0, 0, c, 0)),
        out_shape=jax.ShapeDtypeStruct((4, NL, s, LANE), jnp.int32),
        interpret=pallas_fe._interpret(),
    )


def fenwick_reduce(nodes: jnp.ndarray) -> jnp.ndarray:
    """(K, 4, NL, S, 128) gathered node planes -> (4, NL, S, 128) sums.
    In-kernel sequential accumulation: the output block stays in VMEM across
    the K grid steps (standard revisiting-accumulator pattern)."""
    kf, _, _, s, _ = nodes.shape
    if pallas_fe.enabled():
        # block rows must divide S exactly — grid=(s // blk, kf) would
        # silently truncate otherwise, leaving output rows uninitialized
        # (production S=64 uses 8; reduced-T tests can hit S=4)
        import math

        return _fenwick_call(kf, s, math.gcd(8, s))(nodes)
    acc = tuple(nodes[0, c] for c in range(4))
    for k in range(1, kf):
        acc = _padd_fe(acc, tuple(nodes[k, c] for c in range(4)))
    return jnp.stack(acc)


# ---------------------------------------------------------------------------
# Stage 3: bucket fold. Input: prefix points P_v per (bucket v, window t) in
# packed V-MAJOR order (flat lane index = v*T + t). Output rows:
#   row 0, lanes [0, T): sum over v in [0, 255) of P_v   (per window)
#   row 1, lanes [0, T): P_255                            (per window)
# The caller finishes W = [255]P_255 - sum on tiny (20, T) data.


def _bucket_block(block: jnp.ndarray, t_windows: int, real: bool) -> jnp.ndarray:
    n_rows = block.shape[2]
    nb = n_rows * LANE // t_windows  # buckets (256)
    coords = _read_coords(block)

    # P_255 row: flat positions [ (nb-1)*T, nb*T ) live in the last row at
    # lanes [128 - T, 128): roll rows down by 1 (last row -> row 0), then
    # lanes left so window t lands at lane t.
    def extract_last(r):
        top = _roll(real, r, 1, 0)[:1]
        return _roll(real, top, t_windows, 1)

    p255 = tuple([extract_last(r) for r in rows] for rows in coords)

    # mask bucket 255 to the identity so the fold sums v in [0, 255)
    sub = jax.lax.broadcasted_iota(jnp.int32, (n_rows, LANE), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (n_rows, LANE), 1)
    keep = (sub * LANE + lane) < (nb - 1) * t_windows
    one = jnp.where(keep, 0, 1).astype(jnp.int32)  # identity limb-0 rows

    def mask_coord(rows, is_one):
        out = [jnp.where(keep, r, 0) for r in rows]
        if is_one:
            out[0] = out[0] + one
        return out

    cur = (
        mask_coord(coords[0], False),  # x -> 0
        mask_coord(coords[1], True),  # y -> 1
        mask_coord(coords[2], True),  # z -> 1
        mask_coord(coords[3], False),  # t -> 0
    )

    rows = n_rows
    while rows > 1:
        rows //= 2
        cur = _fold_rows_coords(cur, rows, real)
    w = LANE // 2
    while w >= t_windows:
        cur = _fold_lanes_coords(cur, w, real)
        w //= 2

    pad = 8 - 2
    out = []
    for c in range(4):
        limb_rows = []
        for i in range(NL):
            limb_rows.append(
                jnp.concatenate(
                    [cur[c][i], p255[c][i], jnp.zeros((pad, LANE), jnp.int32)],
                    axis=0,
                )
            )
        out.append(jnp.stack(limb_rows))
    return jnp.stack(out)


def _bucket_kernel(t_windows: int):
    def kernel(x_ref, o_ref):
        o_ref[:] = _bucket_block(
            x_ref[:], t_windows, real=not pallas_fe._interpret()
        )

    return kernel


@functools.lru_cache(maxsize=16)
def _bucket_call(s: int, t_windows: int):
    return pl.pallas_call(
        _bucket_kernel(t_windows),
        grid=(1,),
        in_specs=[pl.BlockSpec((4, NL, s, LANE), lambda i: (0, 0, 0, 0))],
        out_specs=pl.BlockSpec((4, NL, 8, LANE), lambda i: (0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((4, NL, 8, LANE), jnp.int32),
        interpret=pallas_fe._interpret(),
    )


def _bucket_jnp(block: jnp.ndarray, t_windows: int) -> jnp.ndarray:
    """CPU twin of _bucket_block: identical mask/fold/extract schedule,
    fe25519 point math."""
    n_rows = block.shape[2]
    nb = n_rows * LANE // t_windows
    coords = tuple(block[c] for c in range(4))  # (NL, R, 128)

    def extract_last(c):
        top = jnp.roll(c, 1, axis=1)[:, :1]
        return jnp.roll(top, t_windows, axis=-1)

    p255 = tuple(extract_last(c) for c in coords)

    sub = jax.lax.broadcasted_iota(jnp.int32, (n_rows, LANE), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (n_rows, LANE), 1)
    keep = (sub * LANE + lane) < (nb - 1) * t_windows
    idc = np.zeros((NL, 1, 1), dtype=np.int32)
    idc_one = idc.copy()
    idc_one[0] = 1
    cur = (
        jnp.where(keep, coords[0], idc),
        jnp.where(keep, coords[1], idc_one),
        jnp.where(keep, coords[2], idc_one),
        jnp.where(keep, coords[3], idc),
    )

    rows = n_rows
    while rows > 1:
        rows //= 2
        cur = _padd_fe(
            tuple(c[:, :rows] for c in cur), tuple(c[:, rows:] for c in cur)
        )
    w = LANE // 2
    while w >= t_windows:
        rolled = tuple(jnp.roll(c, LANE - w, axis=-1) for c in cur)
        cur = _padd_fe(cur, rolled)
        w //= 2

    pad = jnp.zeros((NL, 8 - 2, LANE), jnp.int32)
    return jnp.stack(
        [
            jnp.concatenate([cur[c], p255[c], pad], axis=1)
            for c in range(4)
        ]
    )


def bucket_fold(prefix_packed: jnp.ndarray, t_windows: int):
    """Packed v-major prefix points -> (sum_{v<255} P_v, P_255), each a
    4-tuple of (NL, T) coordinate arrays (limb-major, ready for the tiny
    window-combine tail)."""
    s = prefix_packed.shape[2]
    assert (s * LANE) % t_windows == 0
    assert t_windows <= LANE and LANE % t_windows == 0
    if pallas_fe.enabled():
        out = _bucket_call(s, t_windows)(prefix_packed)
    else:
        out = _bucket_jnp(prefix_packed, t_windows)
    LAYOUT_CONVERSIONS[0] += 1
    s_pt = tuple(out[c, :, 0, :t_windows] for c in range(4))
    p255 = tuple(out[c, :, 1, :t_windows] for c in range(4))
    return s_pt, p255
