"""Fleet referee: one machine-readable verdict over a whole fleet soak.

The chain observatory (tools/chain_observatory.py) merges per-node dumps
into a descriptive report; the referee turns that evidence — plus a
cross-node **safety audit** it runs itself — into a single release-gate
verdict with a pinned exit code:

    verdict            exit   meaning
    pass                0     safety held, no SLO guard tripped, full coverage
    safety_violation    2     two nodes committed different hashes at a height
                              (the non-negotiable core — named per height)
    slo_tripped         3     some node's SLO burn-rate guard tripped
    partial             4     coverage gaps: dumps missing/corrupt, or nodes
                              the manifest expected that never dumped
    no_data             1     nothing to audit (no usable dumps at all)

Severity strictly orders the verdicts: a fork outranks a tripped SLO
outranks a coverage gap. The safety audit reads the bounded `chain`
sections `capture_node_dump` embeds in every dump (last N committed block
hashes per node) and compares every height two or more nodes share — a
disagreement is never averaged away, it IS the verdict.

The optional `fleet_manifest.json` (chaos/fleet.py writes one next to the
dumps) is the referee's ground truth for coverage and roles: nodes the
harness says survived MUST appear in the dumps (missing ones are named),
and SLO verdicts fold per role (validator / full / light_edge) so "the
light edges blew their budget" reads directly off the report.

Usage:

    python tools/fleet_referee.py --dumps ./observatory --check
    python tools/fleet_referee.py --dumps ./observatory \
        --manifest ./observatory/fleet_manifest.json --out ./observatory
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import Any, Dict, List, Optional

from tendermint_tpu.tools import chain_observatory as obs

MANIFEST_NAME = "fleet_manifest.json"

VERDICT_PASS = "pass"
VERDICT_SAFETY = "safety_violation"
VERDICT_SLO = "slo_tripped"
VERDICT_PARTIAL = "partial"
VERDICT_NO_DATA = "no_data"

EXIT_CODES = {
    VERDICT_PASS: 0,
    VERDICT_NO_DATA: 1,
    VERDICT_SAFETY: 2,
    VERDICT_SLO: 3,
    VERDICT_PARTIAL: 4,
}


# -- inputs -------------------------------------------------------------------


def load_manifest(path_or_dir: str) -> Optional[dict]:
    """The fleet manifest at `path` (or `<dir>/fleet_manifest.json`), or
    None — the referee works manifest-less, it just can't see nodes that
    never produced a dump."""
    path = path_or_dir
    if os.path.isdir(path_or_dir):
        path = os.path.join(path_or_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and doc.get("fleet_manifest") else None


def _roles_by_label(manifest: Optional[dict]) -> Dict[str, str]:
    if not manifest:
        return {}
    out = {}
    for n in manifest.get("nodes") or []:
        if n.get("label"):
            out[n["label"]] = n.get("role") or "?"
    return out


# -- the safety auditor -------------------------------------------------------


def safety_audit(dumps: List[dict]) -> dict:
    """Compare committed block hashes per height across every dump's `chain`
    section. Any height where two nodes disagree is a violation naming the
    height and each node's hash — THE BFT safety invariant, audited offline
    from the evidence files alone."""
    by_height: Dict[int, Dict[str, str]] = {}
    audited_nodes = 0
    for dump in dumps:
        chain = dump.get("chain") or {}
        hashes = chain.get("hashes")
        if not isinstance(hashes, dict) or not hashes:
            continue
        audited_nodes += 1
        label = obs._node_label(dump)
        for h_str, hx in hashes.items():
            try:
                h = int(h_str)
            except (TypeError, ValueError):
                continue
            by_height.setdefault(h, {})[label] = str(hx)

    violations = []
    checked = 0
    for h in sorted(by_height):
        entries = by_height[h]
        if len(entries) < 2:
            continue
        checked += 1
        if len(set(entries.values())) > 1:
            violations.append({"height": h, "hashes": dict(sorted(entries.items()))})
    try:
        from tendermint_tpu.libs.metrics import fleet_metrics

        if checked:
            fleet_metrics().safety_checks.inc(checked)
    except Exception:
        pass
    return {
        "nodes_audited": audited_nodes,
        "heights_checked": checked,
        "violations": violations,
    }


# -- the report ---------------------------------------------------------------


def build_report(
    dumps: List[dict],
    manifest: Optional[dict] = None,
    max_heights: Optional[int] = None,
) -> dict:
    """Fold the observatory merge, the safety audit, manifest-aware
    coverage, per-role SLO verdicts, waterfall coverage, and terminal
    accounting into one report with a single `verdict`."""
    merged = obs.merge(dumps, max_heights=max_heights)
    safety = safety_audit(dumps)
    roles = _roles_by_label(manifest)

    # coverage: dumps that failed to load/scrape, plus manifest-expected
    # survivors that produced NO dump at all
    present = {obs._node_label(d) for d in dumps}
    failed = list(merged["coverage"]["missing"])
    expected = [
        n["label"]
        for n in (manifest.get("nodes") if manifest else []) or []
        if n.get("live") and n.get("label")
    ]
    never_dumped = sorted(set(expected) - present)
    usable = merged["coverage"]["merged"]
    coverage = {
        "dumps": len(dumps),
        "merged": usable,
        "expected_live": len(expected) if manifest else None,
        "missing": sorted(set(failed) | set(never_dumped)),
        "failed_dumps": sorted(failed),
        "never_dumped": never_dumped,
        "partial": bool(failed or never_dumped),
    }

    # per-node waterfall coverage: on how many merged heights does each
    # node's milestone row appear? ("fleet_report covers every surviving
    # node's waterfall" is checked right off this map)
    n_heights = len(merged["heights"])
    waterfall_cov: Dict[str, int] = {}
    for rec in merged["heights"]:
        for label in rec["nodes"]:
            waterfall_cov[label] = waterfall_cov.get(label, 0) + 1
    waterfall = {
        "heights_merged": n_heights,
        "per_node": dict(sorted(waterfall_cov.items())),
        "uncovered": sorted(
            lbl for lbl in (expected or sorted(present - set(failed)))
            if not waterfall_cov.get(lbl)
        ),
    }

    # per-role SLO fold: worst verdict + trip/breach totals per role
    by_role: Dict[str, dict] = {}
    for row in merged["slo"]:
        role = roles.get(row["node"], "?")
        ent = by_role.setdefault(
            role, {"nodes": set(), "objectives": 0, "tripped": 0, "breaches": 0}
        )
        ent["nodes"].add(row["node"])
        ent["objectives"] += 1
        ent["breaches"] += row.get("breaches") or 0
        if row.get("tripped"):
            ent["tripped"] += 1
    role_slo = {
        role: {
            "nodes": len(ent["nodes"]),
            "objectives": ent["objectives"],
            "tripped": ent["tripped"],
            "breaches": ent["breaches"],
            "verdict": "TRIPPED" if ent["tripped"] else "ok",
        }
        for role, ent in sorted(by_role.items())
    }

    # elastic-mesh degrade column (ISSUE 19): each dump's `mesh` section
    # (/debug/mesh) carries the degrade-ladder rung, rebuild count and
    # per-device health — fold the worst rung fleet-wide so a soak that
    # silently limped on a survivor mesh (or fell to host-RLC) reads
    # straight off the report instead of hiding in per-node dumps
    ladder_rank = {"full": 0, "survivor": 1, "single": 2, "host": 3}
    mesh_nodes: Dict[str, dict] = {}
    for dump in dumps:
        mesh = dump.get("mesh")
        if not isinstance(mesh, dict) or mesh.get("error"):
            continue
        health = mesh.get("health") or {}
        devices = health.get("devices") or {}
        dead = sorted(
            k for k, st in devices.items()
            if isinstance(st, dict) and st.get("state") == "dead"
        )
        ladder = mesh.get("ladder")
        rebuilds = mesh.get("rebuilds") or 0
        if ladder is None and not rebuilds and not dead:
            continue  # node never exercised the elastic mesh: no column
        mesh_nodes[obs._node_label(dump)] = {
            "ladder": ladder,
            "rebuilds": int(rebuilds),
            "dead_devices": dead,
        }
    mesh_degrade = None
    if mesh_nodes:
        worst = max(
            (e["ladder"] for e in mesh_nodes.values() if e["ladder"]),
            key=lambda l: ladder_rank.get(l, 0),
            default=None,
        )
        mesh_degrade = {
            "worst_ladder": worst,
            "rebuilds_total": sum(e["rebuilds"] for e in mesh_nodes.values()),
            "nodes": dict(sorted(mesh_nodes.items())),
        }

    # quarantine/recovery column (ISSUE 20): each dump's `suspicion` section
    # snapshots the process-global SuspicionScorer and its verify_stats
    # counters carry the recovery-flush total — fold with a UNION (and max
    # over the shared counters), never a sum: in-process fleets share one
    # scorer, so every dump repeats the same snapshot. A soak where a
    # poisoner got quarantined (or punished) reads straight off the report.
    quarantined_union: set = set()
    punished_max = 0
    paroles_max = 0
    recovery_max = 0
    quarantined_rows_max = 0
    saw_suspicion = False
    for dump in dumps:
        sus = dump.get("suspicion")
        if isinstance(sus, dict) and not sus.get("error"):
            saw_suspicion = True
            quarantined_union.update(sus.get("quarantined") or [])
            punished_max = max(punished_max, int(sus.get("punished") or 0))
            paroles_max = max(paroles_max, int(sus.get("paroles") or 0))
        vs = dump.get("verify_stats")
        counters = (vs or {}).get("counters") if isinstance(vs, dict) else None
        if isinstance(counters, dict):
            recovery_max = max(
                recovery_max, int(counters.get("recovery_flushes") or 0)
            )
            quarantined_rows_max = max(
                quarantined_rows_max, int(counters.get("quarantined_rows") or 0)
            )
    quarantine = None
    if saw_suspicion:
        quarantine = {
            "quarantined_sources": sorted(quarantined_union),
            "punished": punished_max,
            "paroles": paroles_max,
            "recovery_flushes": recovery_max,
            "quarantined_rows": quarantined_rows_max,
        }

    # fleet-wide terminal accounting (delivered/rejected/evicted/expired)
    terminals: Dict[str, int] = {}
    for terms in (merged.get("tx_terminals") or {}).values():
        for outcome, count in terms.items():
            try:
                terminals[outcome] = terminals.get(outcome, 0) + int(count)
            except (TypeError, ValueError):
                continue

    if usable == 0:
        verdict = VERDICT_NO_DATA
    elif safety["violations"]:
        verdict = VERDICT_SAFETY
    elif merged["slo_any_tripped"]:
        verdict = VERDICT_SLO
    elif coverage["partial"]:
        verdict = VERDICT_PARTIAL
    else:
        verdict = VERDICT_PASS
    try:
        from tendermint_tpu.libs.metrics import fleet_metrics

        fleet_metrics().referee_verdicts.labels(verdict).inc()
    except Exception:
        pass

    report: Dict[str, Any] = {
        "fleet_report": 1,
        "generated_ts": round(time.time(), 3),
        "verdict": verdict,
        "exit_code": EXIT_CODES[verdict],
        "coverage": coverage,
        "safety": safety,
        "roles": {
            lbl: roles.get(lbl, "?") for lbl in sorted(present)
        } if roles else {},
        "role_slo": role_slo,
        "slo_any_tripped": merged["slo_any_tripped"],
        "waterfall": waterfall,
        "mesh_degrade": mesh_degrade,
        "quarantine": quarantine,
        "terminals": terminals,
        "slowest_link_counts": merged["slowest_link_counts"],
        "worst_offender": merged["worst_offender"],
        "peer_lag_worst": merged["peer_lag"][:5],
        "manifest": {
            "seed": manifest.get("seed"),
            "fingerprint": manifest.get("fingerprint"),
            "schedule_fingerprint": manifest.get("schedule_fingerprint"),
            "chaos": manifest.get("chaos"),
            "workload_counters": manifest.get("workload_counters"),
        } if manifest else None,
        "observatory": merged,
    }
    return report


# -- rendering ----------------------------------------------------------------


def render_markdown(report: dict) -> str:
    lines: List[str] = []
    lines.append("# Fleet referee report")
    lines.append("")
    v = report["verdict"]
    lines.append(f"## VERDICT: **{v.upper()}** (exit {report['exit_code']})")
    lines.append("")
    man = report.get("manifest")
    if man:
        lines.append(
            f"fleet seed `{man['seed']}` · spec fingerprint "
            f"`{man['fingerprint']}` · schedule `{man['schedule_fingerprint']}`"
        )
        lines.append("")

    cov = report["coverage"]
    lines.append("## Coverage")
    lines.append("")
    exp = cov["expected_live"]
    lines.append(
        f"{cov['merged']}/{cov['dumps']} dumps merged"
        + (f", {exp} live nodes expected by the manifest" if exp is not None else "")
        + "."
    )
    if cov["partial"]:
        lines.append("")
        lines.append(
            f"**PARTIAL**: missing nodes: {', '.join(cov['missing'])}"
            + (
                f" (failed dumps: {', '.join(cov['failed_dumps'])})"
                if cov["failed_dumps"]
                else ""
            )
        )
    lines.append("")

    safety = report["safety"]
    lines.append("## Safety audit (cross-node block hashes)")
    lines.append("")
    lines.append(
        f"{safety['heights_checked']} shared heights compared across "
        f"{safety['nodes_audited']} nodes."
    )
    if safety["violations"]:
        for viol in safety["violations"]:
            lines.append("")
            lines.append(f"**SAFETY VIOLATION at height {viol['height']}**:")
            for label, hx in viol["hashes"].items():
                lines.append(f"- {label}: `{hx[:16]}…`")
    else:
        lines.append("")
        lines.append("No conflicting commits — safety held.")
    lines.append("")

    lines.append("## Per-role SLO verdicts")
    lines.append("")
    if report["role_slo"]:
        lines.append("| role | nodes | objectives | tripped | breaches | verdict |")
        lines.append("|---|---|---|---|---|---|")
        for role, ent in report["role_slo"].items():
            lines.append(
                f"| {role} | {ent['nodes']} | {ent['objectives']} | "
                f"{ent['tripped']} | {ent['breaches']} | {ent['verdict']} |"
            )
    else:
        lines.append("no SLO engines enabled")
    lines.append("")

    wf = report["waterfall"]
    lines.append("## Waterfall coverage")
    lines.append("")
    lines.append(
        f"{wf['heights_merged']} heights merged; per-node appearance counts:"
    )
    lines.append("")
    lines.append("| node | role | heights covered | mesh degrade |")
    lines.append("|---|---|---|---|")
    roles = report.get("roles") or {}
    mesh_nodes = (report.get("mesh_degrade") or {}).get("nodes") or {}
    for label, count in wf["per_node"].items():
        me = mesh_nodes.get(label)
        if me:
            mesh_cell = f"{me.get('ladder') or '?'}·{me.get('rebuilds', 0)}rb"
            if me.get("dead_devices"):
                mesh_cell += f"·{len(me['dead_devices'])}dead"
        else:
            mesh_cell = "—"
        lines.append(
            f"| {label} | {roles.get(label, '?')} | {count} | {mesh_cell} |"
        )
    if wf["uncovered"]:
        lines.append("")
        lines.append(
            f"**uncovered nodes** (no waterfall row on any merged height): "
            f"{', '.join(wf['uncovered'])}"
        )
    lines.append("")

    md = report.get("mesh_degrade")
    if md:
        lines.append("## Elastic mesh degrade")
        lines.append("")
        worst = md.get("worst_ladder")
        mark = "**" if worst and worst != "full" else ""
        lines.append(
            f"worst ladder rung: {mark}{worst or '?'}{mark} · "
            f"{md.get('rebuilds_total', 0)} mesh rebuild(s) fleet-wide"
        )
        lines.append("")

    q = report.get("quarantine")
    if q:
        lines.append("## Adversarial flush defense")
        lines.append("")
        srcs = q.get("quarantined_sources") or []
        mark = "**" if srcs else ""
        lines.append(
            f"quarantined sources: {mark}{', '.join(srcs) or 'none'}{mark} · "
            f"{q.get('punished', 0)} punished · {q.get('paroles', 0)} paroled · "
            f"{q.get('recovery_flushes', 0)} recovery flush(es) · "
            f"{q.get('quarantined_rows', 0)} quarantined row(s)"
        )
        lines.append("")

    lines.append("## Terminal outcomes (fleet-wide)")
    lines.append("")
    if report["terminals"]:
        lines.append(
            ", ".join(f"{k}={v}" for k, v in sorted(report["terminals"].items()))
        )
    else:
        lines.append("no tx lifecycle terminals recorded")
    lines.append("")

    if report.get("worst_offender"):
        lines.append(
            f"Habitual slowest link: **{report['worst_offender']}** "
            f"({report['slowest_link_counts'][report['worst_offender']]} heights)"
        )
        lines.append("")
    return "\n".join(lines)


def write_report(report: dict, out_dir: str) -> tuple:
    os.makedirs(out_dir, exist_ok=True)
    json_path = os.path.join(out_dir, "fleet_report.json")
    md_path = os.path.join(out_dir, "fleet_report.md")
    with open(json_path, "w") as f:
        json.dump(report, f, indent=1, default=repr)
    with open(md_path, "w") as f:
        f.write(render_markdown(report))
    return json_path, md_path


# -- CLI ----------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--dumps", required=True,
        help=f"directory of {obs.DUMP_PREFIX}*.json dumps (+ optional manifest)",
    )
    ap.add_argument(
        "--manifest",
        help=f"fleet manifest path (default <dumps>/{MANIFEST_NAME} if present)",
    )
    ap.add_argument(
        "--out", help="output directory for fleet_report.{json,md} (default --dumps)"
    )
    ap.add_argument(
        "--heights", type=int, default=0,
        help="most recent heights to merge (0 = all; default all)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="exit with the verdict's code (see EXIT_CODES) instead of 0",
    )
    args = ap.parse_args(argv)

    dumps = obs.load_dumps(args.dumps)
    manifest = load_manifest(args.manifest or args.dumps)
    report = build_report(dumps, manifest=manifest, max_heights=args.heights or None)
    json_path, md_path = write_report(report, args.out or args.dumps)
    print(render_markdown(report))
    print(f"wrote {json_path} and {md_path}")
    if args.check:
        return report["exit_code"]
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
