"""Per-height/round consensus timeline ring.

The distributed-system complement of libs/trace.py's device-side flight
recorder: a bounded, thread-safe record of WHERE each height spent its time
— step entries, round escalations, proposal/vote arrival, commit — kept as
structured per-height records instead of a flat span ring, so one GET of
`/debug/consensus_timeline` answers "why was height H slow?" without
grepping logs. The reference exposes only the *current* round state
(rpc/core/consensus.go DumpConsensusState); history dies with the round.

Two producers share this format:

- the live ConsensusState (consensus/cs_state.py) feeds wall-clock events
  while running (gated on `tracer.enabled`: with tracing off the hot path
  pays only flag checks and the ring stays empty);
- the offline WAL inspector (tools/wal_inspect.py) replays a crashed or
  slow node's WAL into the same structure, deriving timestamps from the
  signed vote/proposal times embedded in the messages.

Overhead contract: every record_* call is a few dict/list operations under
one lock; per-round vote arrivals aggregate into a fixed bucket histogram
(VOTE_ARRIVAL_BUCKETS_MS), never an unbounded list.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

DEFAULT_MAX_HEIGHTS = 128

# vote-arrival offsets from round start, cumulative buckets in milliseconds
VOTE_ARRIVAL_BUCKETS_MS = (5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000)

# default for record_* ts args: "stamp with wall-clock now". The offline WAL
# inspector instead passes an explicit float (derived from signed message
# timestamps) or None ("no time reference yet" — the record is kept, its
# durations stay undefined).
_NOW = object()


class ConsensusTimeline:
    """Bounded ring of per-height consensus records, oldest evicted first."""

    def __init__(self, max_heights: int = DEFAULT_MAX_HEIGHTS):
        self.max_heights = max(1, int(max_heights))
        self._lock = threading.Lock()
        self._heights: "OrderedDict[int, dict]" = OrderedDict()

    # -- recording ----------------------------------------------------------

    def _rec(self, height: int) -> dict:
        rec = self._heights.get(height)
        if rec is None:
            rec = {
                "height": height,
                "steps": [],  # [{"round", "step", "ts"}] in arrival order
                "round_start": {},  # round -> ts of its first step
                "proposals": [],  # [{"round", "ts"}]
                "votes": {},  # round -> {"prevote", "precommit", "arrival_ms"}
                "commit": None,  # {"round", "ts", "txs"}
                "end_height_ts": None,
            }
            self._heights[height] = rec
            while len(self._heights) > self.max_heights:
                self._heights.popitem(last=False)
        return rec

    def record_step(self, height: int, round_: int, step: str, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            rec = self._rec(height)
            rec["steps"].append({"round": round_, "step": step, "ts": ts})
            if ts is not None:
                rec["round_start"].setdefault(round_, ts)

    def record_proposal(self, height: int, round_: int, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            self._rec(height)["proposals"].append({"round": round_, "ts": ts})

    def record_vote(self, height: int, round_: int, vote_type: str, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        key = "prevote" if "PREVOTE" in vote_type.upper() else "precommit"
        with self._lock:
            rec = self._rec(height)
            votes = rec["votes"].get(round_)
            if votes is None:
                votes = rec["votes"][round_] = {
                    "prevote": 0,
                    "precommit": 0,
                    "arrival_ms": [0] * (len(VOTE_ARRIVAL_BUCKETS_MS) + 1),
                }
            votes[key] += 1
            start = rec["round_start"].get(round_)
            if start is not None and ts is not None:
                off_ms = max(0.0, (ts - start) * 1e3)
                for i, b in enumerate(VOTE_ARRIVAL_BUCKETS_MS):
                    if off_ms <= b:
                        votes["arrival_ms"][i] += 1
                        break
                else:
                    votes["arrival_ms"][-1] += 1

    def record_commit(self, height: int, round_: int, txs: int = 0, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            self._rec(height)["commit"] = {"round": round_, "ts": ts, "txs": txs}

    def record_end_height(self, height: int, ts=_NOW) -> None:
        ts = time.time() if ts is _NOW else ts
        with self._lock:
            self._rec(height)["end_height_ts"] = ts

    # -- introspection ------------------------------------------------------

    def dump(self, limit: Optional[int] = None) -> List[dict]:
        """Time-ordered per-height records (ascending height; the most
        recent `limit` heights if given). Step durations are derived on the
        way out: each step's `dur_s` is the gap to the next recorded step of
        the same height (the last step stays open-ended)."""
        with self._lock:
            heights = [self._copy_rec(r) for r in self._heights.values()]
        heights.sort(key=lambda r: r["height"])
        if limit is not None and limit >= 0:
            heights = heights[-limit:] if limit else []
        for rec in heights:
            steps = rec["steps"]
            for i, st in enumerate(steps):
                nxt = steps[i + 1]["ts"] if i + 1 < len(steps) else None
                if nxt is not None and st["ts"] is not None:
                    # clamp: WAL-reconstructed timestamps come from different
                    # validators' clocks, so skew could make the gap negative
                    st["dur_s"] = round(max(0.0, nxt - st["ts"]), 6)
            # rounds the state machine actually ENTERED (steps/commit) —
            # votes are excluded: next-round and peer-catchup votes arrive
            # for rounds this node never escalated to, and counting them
            # would fabricate round escalations in the report
            rounds = {s["round"] for s in steps}
            if rec["commit"] is not None:
                rounds.add(rec["commit"]["round"])
            rec["round_count"] = (max(rounds) + 1) if rounds else 0
            commit = rec["commit"]
            start = rec["round_start"].get(0)
            if commit is not None and commit["ts"] is not None and start is not None:
                rec["total_s"] = round(max(0.0, commit["ts"] - start), 6)
            # internal bookkeeping, derivable from steps[] — not API surface
            rec.pop("round_start", None)
        return heights

    def _copy_rec(self, rec: dict) -> dict:
        out = dict(rec)
        out["steps"] = [dict(s) for s in rec["steps"]]
        out["proposals"] = [dict(p) for p in rec["proposals"]]
        out["votes"] = {
            r: {**v, "arrival_ms": list(v["arrival_ms"])}
            for r, v in rec["votes"].items()
        }
        out["round_start"] = dict(rec["round_start"])
        if rec["commit"] is not None:
            out["commit"] = dict(rec["commit"])
        return out

    def heights(self) -> List[int]:
        with self._lock:
            return sorted(self._heights)

    def clear(self) -> None:
        with self._lock:
            self._heights.clear()
