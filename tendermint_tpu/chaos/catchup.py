"""Catch-up-level fault injection (ISSUE 12): misbehaving SERVING peers.

The device/network/process injectors fault the node under test; these fault
the peers it syncs FROM. A `ServeFaults` instance installed on a node's
blocksync/statesync reactor (`reactor.serve_faults = ServeFaults()`) makes
that node's serving side misbehave on demand:

  arm_block_stall(seconds)  block requests are silently swallowed for the
                            window (a live-but-unresponsive peer: the
                            syncer's pool must time out, back off, and
                            route around it);
  arm_block_lies(count)     the next `count` served blocks have one commit
                            signature flipped (a lying peer: the syncer's
                            super-batch verify must fail the height, redo
                            it, and punish the sender);
  arm_chunk_corrupt(count)  the next `count` served snapshot chunks have a
                            byte flipped (the restoring app refuses them;
                            the syncer must punish + re-queue from another
                            peer).

Thread-safety matters only as far as the event loop: reactors consult these
from their receive coroutines, the chaos engine arms them from its own task
on the same loop — plain attributes suffice.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


class ServeFaults:
    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._stall_until = 0.0
        self._block_lies = 0
        self._chunk_corrupt = 0
        # forensics: what actually fired, for soak assertions
        self.fired = []  # ("stall_drop"|"block_lie"|"chunk_corrupt", detail)

    # -- arming --------------------------------------------------------------

    def arm_block_stall(self, seconds: float) -> None:
        self._stall_until = max(self._stall_until, self._clock() + float(seconds))

    def arm_block_lies(self, count: int) -> None:
        self._block_lies += max(0, int(count))

    def arm_chunk_corrupt(self, count: int) -> None:
        self._chunk_corrupt += max(0, int(count))

    def heal(self) -> None:
        self._stall_until = 0.0
        self._block_lies = 0
        self._chunk_corrupt = 0

    # -- reactor-side hooks --------------------------------------------------

    def block_stalled(self) -> bool:
        if self._clock() < self._stall_until:
            self.fired.append(("stall_drop", ""))
            return True
        return False

    def take_block_lie(self) -> bool:
        if self._block_lies > 0:
            self._block_lies -= 1
            return True
        return False

    def take_chunk_corrupt(self) -> bool:
        if self._chunk_corrupt > 0:
            self._chunk_corrupt -= 1
            return True
        return False

    def corrupt_block(self, block):
        """A commit-tampered copy of `block`: one for_block signature in
        last_commit gets a flipped byte, so the RECEIVER's cross-height
        super-batch verification fails the previous height's 2/3 tally and
        walks the redo/punish path (the block still decodes and its header
        still hashes — this is a lie, not line noise)."""
        sigs = list(block.last_commit.signatures)
        for i, cs in enumerate(sigs):
            if cs.for_block() and cs.signature:
                flipped = bytes([cs.signature[0] ^ 0xFF]) + cs.signature[1:]
                sigs[i] = dataclasses.replace(cs, signature=flipped)
                break
        else:
            return block  # nothing to tamper (height-1 empty commit)
        commit = dataclasses.replace(block.last_commit, signatures=tuple(sigs))
        self.fired.append(("block_lie", f"height={block.header.height}"))
        return dataclasses.replace(block, last_commit=commit)

    def corrupt_chunk(self, chunk: bytes) -> bytes:
        """A bit-rotted copy of a snapshot chunk."""
        self.fired.append(("chunk_corrupt", f"len={len(chunk)}"))
        if not chunk:
            return chunk
        return bytes([chunk[0] ^ 0xFF]) + chunk[1:]


def install(node, faults: Optional[ServeFaults] = None) -> ServeFaults:
    """Attach one ServeFaults to every catch-up-serving reactor of `node`."""
    sf = faults or ServeFaults()
    if getattr(node, "blocksync_reactor", None) is not None:
        node.blocksync_reactor.serve_faults = sf
    if getattr(node, "statesync_reactor", None) is not None:
        node.statesync_reactor.serve_faults = sf
    return sf
