"""Offline WAL post-mortem inspector.

Replays a consensus WAL (consensus/wal.py framing) into the SAME
per-height/round timeline the live node serves at
`GET /debug/consensus_timeline` (consensus/timeline.py), entirely offline
and strictly read-only — the ONLY WAL consumer that must never append an
EndHeight(0) anchor to the artifact it is examining (hence
wal.iter_wal_messages, not the WAL class).

WAL frames carry no wall-clock timestamps; time is reconstructed from the
SIGNED timestamps embedded in votes and proposals (Vote.timestamp_ns /
Proposal.timestamp_ns — the only clocks that survive a crash). Every
timeline entry is stamped with the most recent such timestamp, so step
durations are vote-arrival-granular approximations: exact enough to answer
"which step did height H sit in for 30 s" and "how many rounds did it
burn", which is what a post-mortem of a crashed or slow node needs.

Report contents (`inspect_wal`):
- per-height timeline records (heights/rounds/steps, identical shape to
  /debug/consensus_timeline) — the cross-check the integration test runs;
- per-step duration summary (count/total/max seconds);
- round escalations: heights that needed round > 0;
- aggregate vote-arrival histogram (offset from round start, ms buckets);
- EndHeight gaps: heights whose completion marker never made it to disk —
  the crash frontier;
- message counts by type, timeout counts by step.

CLI: `python -m tendermint_tpu.cli wal-inspect [--wal PATH]` or the
standalone `tools/wal_inspect.py PATH`.
"""

from __future__ import annotations

from typing import List, Optional

from tendermint_tpu.consensus.messages import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.consensus.round_state import RoundStepType
from tendermint_tpu.consensus.timeline import (
    VOTE_ARRIVAL_BUCKETS_MS,
    ConsensusTimeline,
)
from tendermint_tpu.consensus.wal import (
    EndHeightMessage,
    EventRoundState,
    MsgInfo,
    TimeoutInfo,
    iter_wal_messages,
)


def _step_name(step: int) -> str:
    try:
        return RoundStepType(step).name
    except ValueError:
        return f"STEP_{step}"


def _scan(path: str, max_heights: int = 0):
    """ONE decode pass over a WAL group (crashed-node groups can be many
    rotated files — don't read/CRC/decode them twice): feeds the timeline
    AND accumulates the count aggregates. Returns
    (timeline, msg_counts, timeout_steps, end_heights)."""
    tl = ConsensusTimeline(max_heights or 1_000_000)
    cur_ts: Optional[float] = None  # last signed timestamp seen, seconds
    msg_counts: dict = {}
    timeout_steps: dict = {}
    end_heights = set()
    for msg in iter_wal_messages(path):
        if isinstance(msg, EventRoundState):
            name = "EventRoundState"
            tl.record_step(msg.height, msg.round, _step_name(msg.step), ts=cur_ts)
        elif isinstance(msg, EndHeightMessage):
            name = "EndHeightMessage"
            end_heights.add(msg.height)
            if msg.height > 0:  # height 0 is the fresh-WAL anchor, not a height
                tl.record_end_height(msg.height, ts=cur_ts)
        elif isinstance(msg, TimeoutInfo):
            name = "TimeoutInfo"
            step = _step_name(msg.step)
            timeout_steps[step] = timeout_steps.get(step, 0) + 1
        elif isinstance(msg, MsgInfo):
            m = msg.msg
            name = type(m).__name__
            if isinstance(m, VoteMessage):
                cur_ts = m.vote.timestamp_ns / 1e9
                tl.record_vote(m.vote.height, m.vote.round, m.vote.type.name, ts=cur_ts)
            elif isinstance(m, ProposalMessage):
                cur_ts = m.proposal.timestamp_ns / 1e9
                tl.record_proposal(m.proposal.height, m.proposal.round, ts=cur_ts)
        else:
            name = type(msg).__name__
        msg_counts[name] = msg_counts.get(name, 0) + 1
    return tl, msg_counts, timeout_steps, end_heights


def build_timeline(path: str, max_heights: int = 0) -> ConsensusTimeline:
    """Replay one WAL group into a ConsensusTimeline. max_heights=0 keeps
    every height found (post-mortems want the full history)."""
    return _scan(path, max_heights)[0]


def inspect_wal(path: str, limit: Optional[int] = None) -> dict:
    """Full post-mortem report for one WAL group (see module docstring)."""
    tl, msg_counts, timeout_steps, end_heights = _scan(path)
    heights = tl.dump(limit)

    step_durations: dict = {}
    escalated: List[dict] = []
    arrival = [0] * (len(VOTE_ARRIVAL_BUCKETS_MS) + 1)
    for rec in heights:
        for st in rec["steps"]:
            dur = st.get("dur_s")
            if dur is None:
                continue
            agg = step_durations.setdefault(
                st["step"], {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            agg["count"] += 1
            agg["total_s"] = round(agg["total_s"] + dur, 6)
            agg["max_s"] = max(agg["max_s"], dur)
        if rec["round_count"] > 1:
            escalated.append(
                {"height": rec["height"], "rounds": rec["round_count"]}
            )
        for votes in rec["votes"].values():
            for i, n in enumerate(votes["arrival_ms"]):
                arrival[i] += n

    # EndHeight gaps: completed heights per the timeline that never got
    # their durable marker — everything at/after the first gap replays on
    # restart; the LAST height is expected to be open (the crash frontier)
    seen = [r["height"] for r in heights]
    frontier = max(seen) if seen else None
    gaps = [h for h in seen if h not in end_heights and h != frontier]
    return {
        "wal": path,
        "messages": msg_counts,
        "timeouts_by_step": timeout_steps,
        "height_range": [min(seen), max(seen)] if seen else None,
        "heights_seen": len(seen),
        "end_height_markers": len(end_heights),
        "end_height_gaps": gaps,
        "round_escalations": escalated,
        "step_durations": step_durations,
        "vote_arrival_ms_buckets": list(VOTE_ARRIVAL_BUCKETS_MS) + ["+Inf"],
        "vote_arrival_counts": arrival,
        "heights": heights,
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("wal", help="path to the WAL head file (rotated .NNN siblings are included)")
    p.add_argument("--limit", type=int, default=None, help="only the most recent N heights")
    args = p.parse_args(argv)
    print(json.dumps(inspect_wal(args.wal, limit=args.limit), indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
