"""On-demand TPU/device profiler capture (a thin jax.profiler session wrapper).

PERF.md's round-4 per-stage device attribution came from a one-off manual
perfetto trace — an afternoon of ad-hoc scripting that no later round
repeated, which is why the fused-MSM work (PR 6) shipped with CPU-only
evidence. This module makes capture a first-class operation with three
entry points:

- `GET /debug/device_profile?action=start|stop|status` (rpc/server.py): an
  operator profiles a LIVE node's flushes without restarting it;
- `bench.py --profile <scenario>`: one command captures a scenario and
  renders the per-stage table (tools/profile_report.py);
- `trace_function(fn, *args)`: one-flush capture for tests/tools.

A capture session is PROCESS-GLOBAL (jax.profiler supports one active trace
per process) and writes into a fresh run directory
`<base>/tmtpu_profile_<utcstamp>_<pid>_<seq>/`; jax drops the TensorBoard-layout
artifacts under `plugins/profile/<ts>/` — a `*.xplane.pb` (always) and a
`*.trace.json.gz` (perfetto/chrome form). `tools/profile_report.py` parses
either into a per-kernel / per-fused-stage (uptree, fenwick_reduce,
bucket_fold, persig) time table.

CPU-backend caveat (docs/OBSERVABILITY.md): on `JAX_PLATFORMS=cpu` the
capture contains host Python spans, XLA:CPU compile passes and runtime
thunks, but no device plane — stage attribution of the *device* kind needs
a real accelerator. The capture/report PIPELINE is identical on both, which
is what the tier-1 round-trip test pins.
"""

from __future__ import annotations

import glob
import os
import tempfile
import threading
import time
from typing import Any, Dict, Optional


class ProfilerError(RuntimeError):
    """start when active / stop when idle / profiler unavailable."""


_LOCK = threading.Lock()
_STATE: Dict[str, Any] = {
    "active": False,
    "dir": None,
    "started_at": None,
    "last_capture": None,  # {"dir", "started_at", "stopped_at", "artifacts"}
}
_RUN_SEQ = 0  # uniquifies run dirs within one wall-clock second


def default_base_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "tmtpu_profiles")


def _artifacts(run_dir: str) -> list:
    """Capture artifacts under a run dir, relative paths + sizes."""
    out = []
    for pat in ("**/*.xplane.pb", "**/*.trace.json.gz", "**/*.json.gz"):
        for p in glob.glob(os.path.join(run_dir, pat), recursive=True):
            rel = os.path.relpath(p, run_dir)
            if not any(a["file"] == rel for a in out):
                try:
                    size = os.path.getsize(p)
                except OSError:
                    size = None
                out.append({"file": rel, "bytes": size})
    return sorted(out, key=lambda a: a["file"])


def _metrics_inc(action: str) -> None:
    try:
        from tendermint_tpu.libs import metrics as _metrics

        _metrics.observatory_metrics().profiler_actions.labels(action).inc()
    except Exception:
        pass


def start(base_dir: Optional[str] = None) -> dict:
    """Begin a capture into a fresh run directory; returns {"dir", ...}.
    Raises ProfilerError if a capture is already active (jax supports one
    trace per process) or the profiler backend is unavailable."""
    import jax

    with _LOCK:
        if _STATE["active"]:
            raise ProfilerError(
                f"profiler capture already active (dir={_STATE['dir']})"
            )
        global _RUN_SEQ
        _RUN_SEQ += 1
        # pid+seq suffix: two captures in the same wall-clock second (easy
        # with sub-second trace_function calls) must not share a run dir —
        # _artifacts() and profile_report would silently merge their events
        run_dir = os.path.join(
            base_dir or default_base_dir(),
            time.strftime("tmtpu_profile_%Y%m%d_%H%M%S", time.gmtime())
            + f"_{os.getpid()}_{_RUN_SEQ}",
        )
        os.makedirs(run_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(run_dir)
        except Exception as e:
            raise ProfilerError(f"jax.profiler.start_trace failed: {e!r}") from e
        _STATE.update(active=True, dir=run_dir, started_at=time.time())
    _metrics_inc("start")
    try:
        from tendermint_tpu.libs.trace import tracer

        if tracer.enabled:
            tracer.event("profiler.start", dir=run_dir)
    except Exception:
        pass
    return {"active": True, "dir": run_dir, "backend": jax.default_backend()}


def stop() -> dict:
    """End the active capture; returns {"dir", "artifacts", "duration_s"}.
    Raises ProfilerError when no capture is active.

    stop_trace serializes the whole capture (tens of MB, seconds) — it runs
    OUTSIDE _LOCK so a concurrent status() (served synchronously on the
    node's event loop) never blocks behind it. The "stopping" phase keeps
    start() refused for the whole window."""
    import jax

    with _LOCK:
        if not _STATE["active"] or _STATE.get("stopping"):
            raise ProfilerError("no profiler capture active")
        run_dir, started = _STATE["dir"], _STATE["started_at"]
        _STATE["stopping"] = True
    try:
        jax.profiler.stop_trace()
    finally:
        # even a failed stop leaves no active session to stop again
        with _LOCK:
            _STATE.update(active=False, dir=None, started_at=None,
                          stopping=False)
    cap = {
        "dir": run_dir,
        "started_at": started,
        "stopped_at": time.time(),
        "artifacts": _artifacts(run_dir),
    }
    with _LOCK:
        _STATE["last_capture"] = cap
    _metrics_inc("stop")
    try:
        from tendermint_tpu.libs.trace import tracer

        if tracer.enabled:
            tracer.event(
                "profiler.stop", dir=run_dir, artifacts=len(cap["artifacts"])
            )
    except Exception:
        pass
    return {
        "active": False,
        "dir": run_dir,
        "duration_s": round(cap["stopped_at"] - started, 3) if started else None,
        "artifacts": cap["artifacts"],
    }


def status() -> dict:
    """Session snapshot — safe to call any time, never raises. Served
    synchronously on the node's event loop, so it must stay cheap: no lock
    held across serialization (see stop()) and no jax import/init here —
    backend is reported only when jax is already loaded."""
    import sys

    with _LOCK:
        st = {
            "active": _STATE["active"],
            "stopping": bool(_STATE.get("stopping")),
            "dir": _STATE["dir"],
            "started_at": _STATE["started_at"],
            "last_capture": _STATE["last_capture"],
        }
    if st["active"] and st["started_at"]:
        st["running_s"] = round(time.time() - st["started_at"], 3)
    try:
        jax = sys.modules.get("jax")
        st["backend"] = jax.default_backend() if jax is not None else None
    except Exception as e:  # profiler surface useless without jax
        st["backend"] = None
        st["error"] = repr(e)
    return st


def trace_function(fn, *args, base_dir: Optional[str] = None, **kwargs):
    """One-flush capture: start → fn(*args) → block on the result → stop.
    Returns (result, run_dir). The result is block_until_ready'd when it
    supports it so the device work lands INSIDE the capture window."""
    info = start(base_dir)
    try:
        out = fn(*args, **kwargs)
        try:
            import jax

            out = jax.block_until_ready(out)
        except Exception:
            pass
    finally:
        stop()
    _metrics_inc("trace_function")
    return out, info["dir"]
