"""Peer: a connected remote node (reference: p2p/peer.go), and PeerSet
(reference: p2p/peer_set.go)."""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from tendermint_tpu.p2p.conn.connection import MConnection
from tendermint_tpu.p2p.node_info import NodeInfo


class Peer:
    def __init__(
        self,
        node_info: NodeInfo,
        mconn: MConnection,
        outbound: bool,
        persistent: bool = False,
        socket_addr: str = "",
        metrics=None,
    ):
        self.metrics = metrics
        self.node_info = node_info
        self.mconn = mconn
        self.outbound = outbound
        self.persistent = persistent
        self.socket_addr = socket_addr
        self._data: Dict[str, object] = {}  # reactor-attached state (PeerState)

    @property
    def id(self) -> str:
        return self.node_info.node_id

    async def send(self, chan_id: int, msg: bytes) -> bool:
        if self.metrics is not None:
            self.metrics.peer_send_bytes_total.labels(f"{chan_id:#x}").inc(len(msg))
        return await self.mconn.send(chan_id, msg)

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        return self.mconn.try_send(chan_id, msg)

    def status(self) -> dict:
        """Per-connection flowrate/queue snapshot (reference: p2p/peer.go
        Status -> ConnectionStatus); surfaced in net_info."""
        return self.mconn.status()

    def clock_skew(self):
        """Estimated remote-minus-local wall-clock offset (seconds) from the
        connection's timestamped ping/pong, or None before the first sample."""
        return self.mconn.clock_skew()

    def set(self, key: str, value) -> None:
        self._data[key] = value

    def get(self, key: str):
        return self._data.get(key)

    async def stop(self) -> None:
        await self.mconn.stop()

    def __repr__(self) -> str:
        return f"Peer({self.id[:10]}, {'out' if self.outbound else 'in'})"


class PeerSet:
    def __init__(self):
        self._peers: Dict[str, Peer] = {}

    def add(self, peer: Peer) -> None:
        if peer.id in self._peers:
            raise ValueError(f"duplicate peer {peer.id}")
        self._peers[peer.id] = peer

    def has(self, peer_id: str) -> bool:
        return peer_id in self._peers

    def get(self, peer_id: str) -> Optional[Peer]:
        return self._peers.get(peer_id)

    def remove(self, peer_id: str) -> Optional[Peer]:
        return self._peers.pop(peer_id, None)

    def list(self) -> List[Peer]:
        return list(self._peers.values())

    def size(self) -> int:
        return len(self._peers)
