"""State store (reference: state/store.go:42).

Persists: the State blob, per-height validator sets with lastHeightChanged
dedup (reference: state/store.go:412 LoadValidators), per-height consensus
params, and ABCI responses per height (for /block_results and replay)."""

from __future__ import annotations

import json
import struct
from dataclasses import replace
from typing import List, Optional

from tendermint_tpu.libs.kvdb import KVDB
from tendermint_tpu.state.sm_state import State, _valset_from_json, _valset_to_json
from tendermint_tpu.types.validator_set import ValidatorSet

_STATE_KEY = b"SS:state"


def _vkey(height: int) -> bytes:
    return b"SS:validators:" + struct.pack(">q", height)


def _akey(height: int) -> bytes:
    return b"SS:abci_responses:" + struct.pack(">q", height)


class ABCIResponses:
    """DeliverTx results + EndBlock/BeginBlock for one height."""

    def __init__(self, deliver_txs=None, begin_block=None, end_block=None):
        self.deliver_txs = deliver_txs or []
        self.begin_block = begin_block
        self.end_block = end_block

    def to_json(self) -> str:
        from tendermint_tpu.abci.types import ValidatorUpdate

        end = self.end_block
        return json.dumps(
            {
                "deliver_txs": [
                    {"code": r.code, "data": r.data.hex(), "log": r.log, "gas_wanted": r.gas_wanted, "gas_used": r.gas_used}
                    for r in self.deliver_txs
                ],
                "validator_updates": [
                    {"type": u.pub_key_type, "pub_key": u.pub_key_bytes.hex(), "power": u.power}
                    for u in (end.validator_updates if end else [])
                ],
            }
        )

    @classmethod
    def from_json(cls, data: str) -> "ABCIResponses":
        from tendermint_tpu.abci.types import (
            ResponseDeliverTx,
            ResponseEndBlock,
            ValidatorUpdate,
        )

        o = json.loads(data)
        dts = [
            ResponseDeliverTx(
                code=r["code"], data=bytes.fromhex(r["data"]), log=r["log"],
                gas_wanted=r["gas_wanted"], gas_used=r["gas_used"],
            )
            for r in o["deliver_txs"]
        ]
        end = ResponseEndBlock(
            validator_updates=[
                ValidatorUpdate(u["type"], bytes.fromhex(u["pub_key"]), u["power"])
                for u in o.get("validator_updates", [])
            ]
        )
        return cls(deliver_txs=dts, end_block=end)


class StateStore:
    def __init__(self, db: KVDB):
        self.db = db

    def load(self) -> Optional[State]:
        raw = self.db.get(_STATE_KEY)
        return State.from_json(raw.decode()) if raw else None

    def save(self, state: State) -> None:
        """Also saves next_validators at their effective height
        (reference: state/store.go:149 Save → saveValidatorsInfo)."""
        next_height = state.last_block_height + 1
        if state.last_block_height == 0:
            # genesis bootstrap: save both current (initial) and next
            self._save_validators(state.initial_height, state.last_height_validators_changed, state.validators)
            self._save_validators(state.initial_height + 1, state.last_height_validators_changed, state.next_validators)
        else:
            self._save_validators(next_height + 1, state.last_height_validators_changed, state.next_validators)
        self.db.set(_STATE_KEY, state.to_json().encode())

    def bootstrap(self, state: State) -> None:
        """State-sync entry (reference: state/store.go:182)."""
        height = state.last_block_height
        if height == 0:
            height = state.initial_height - 1
        if state.last_validators is not None:
            self._save_validators(height, height, state.last_validators)
        self._save_validators(height + 1, height + 1, state.validators)
        self._save_validators(height + 2, height + 2, state.next_validators)
        self.db.set(_STATE_KEY, state.to_json().encode())

    def _save_validators(self, height: int, last_changed: int, valset: Optional[ValidatorSet]) -> None:
        if valset is None:
            return
        payload = {"last_height_changed": last_changed}
        if height == last_changed or height % 100000 == 0:
            payload["valset"] = _valset_to_json(valset)
        self.db.set(_vkey(height), json.dumps(payload).encode())

    def load_validators(self, height: int) -> Optional[ValidatorSet]:
        """Follows the lastHeightChanged indirection
        (reference: state/store.go:412)."""
        raw = self.db.get(_vkey(height))
        if raw is None:
            return None
        o = json.loads(raw)
        if "valset" in o:
            return _valset_from_json(o["valset"])
        last_changed = o["last_height_changed"]
        raw2 = self.db.get(_vkey(last_changed))
        if raw2 is None:
            return None
        o2 = json.loads(raw2)
        if "valset" not in o2:
            return None
        vs = _valset_from_json(o2["valset"])
        if vs is not None and height > last_changed:
            vs.increment_proposer_priority(height - last_changed)
        return vs

    def save_abci_responses(self, height: int, responses: ABCIResponses) -> None:
        self.db.set(_akey(height), responses.to_json().encode())

    def load_abci_responses(self, height: int) -> Optional[ABCIResponses]:
        raw = self.db.get(_akey(height))
        return ABCIResponses.from_json(raw.decode()) if raw else None

    def prune_states(self, retain_height: int) -> None:
        """(reference: state/store.go:217)"""
        if retain_height <= 0:
            raise ValueError("height must be greater than 0")
        # Keep the indirection target alive: materialize the full valset at the
        # retain height before deleting older entries (reference:
        # state/store.go:217 PruneStates does the same).
        vs = self.load_validators(retain_height)
        if vs is not None:
            self.db.set(
                _vkey(retain_height),
                json.dumps(
                    {"last_height_changed": retain_height, "valset": _valset_to_json(vs)}
                ).encode(),
            )
        deletes: List[bytes] = []
        for key, _ in self.db.iterate_prefix(b"SS:validators:"):
            h = struct.unpack(">q", key[len(b"SS:validators:"):])[0]
            if h < retain_height:
                deletes.append(key)
        for key, _ in self.db.iterate_prefix(b"SS:abci_responses:"):
            h = struct.unpack(">q", key[len(b"SS:abci_responses:"):])[0]
            if h < retain_height:
                deletes.append(key)
        self.db.write_batch([], deletes)
