"""Multi-chip sharded batch verification (the framework's scale-out axis).

Verification is embarrassingly parallel over the validator axis, so the
multi-chip design is: shard the trailing batch axis of every input tensor
across a `jax.sharding.Mesh`, run the single-device kernel per shard via
`shard_map`, and reduce cross-chip only for the O(1) aggregates (voting-power
tallies) with `psum` — which XLA lowers onto ICI.

Two mesh shapes are supported:
- 1D ("vals",): commit verification sharded across validators — replaces the
  reference's serial loop (reference: types/validator_set.go:680-702) at
  multi-chip scale.
- 2D ("blocks", "vals"): fast-sync historical replay sharded across blocks AND
  validators (reference: blockchain/v0/reactor.go VerifyCommitLight per block)
  — the batch axes of `verify_prepared` are arbitrary-rank, so a [32, NB, NV]
  tensor shards across both mesh axes with zero kernel changes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.ops.ed25519_jax import _verify_core, make_ctx, verify_prepared


def make_mesh(devices=None, shape=None, axis_names=("vals",)) -> Mesh:
    """Build a device mesh. Default: all devices on one 'vals' axis."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    arr = np.asarray(devices)
    if shape is not None:
        arr = arr.reshape(shape)
    return Mesh(arr, axis_names)


def sharded_verify(mesh: Mesh):
    """jit'd verify_prepared with the batch axis sharded across the mesh.

    Inputs [32,B]/[253,B] (or [..., NB, NV] for 2D meshes); batch axes map to
    mesh axes right-aligned: the last input axis onto the last mesh axis, etc.
    Returns the bool mask with the same sharded layout.
    """
    spec_in = P(None, *mesh.axis_names)
    spec_out = P(*mesh.axis_names)
    # ctx is replicated: every chip gets the same materialized constants
    # sized for ITS shard, so the fast (real-buffer) path runs per shard.
    spec_ctx = jax.tree.map(lambda _: P(), make_ctx(()))

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec_in, spec_in, spec_in, spec_in, spec_ctx),
        out_specs=spec_out,
        check_vma=False,
    )
    def _verify(a, r, s_bits, h_bits, ctx):
        return _verify_core(a, r, s_bits, h_bits, ctx)

    jitted = jax.jit(_verify)

    def run(a, r, s_bits, h_bits):
        shard_batch = tuple(
            d // m for d, m in zip(a.shape[1:], mesh.devices.shape)
        )
        return jitted(a, r, s_bits, h_bits, make_ctx(shard_batch))

    return run


def sharded_commit_step(mesh: Mesh):
    """The full 'training step' analog: batched commit verification.

    Per-shard signature verification + cross-chip psum of the voting power
    carried by valid signatures; accepts iff valid power > 2/3 of total
    (reference: types/validator_set.go:662 VerifyCommit tally semantics).
    Returns (mask, ok) with mask sharded and ok replicated.
    """
    spec_in = P(None, *mesh.axis_names)
    spec_p = P(*mesh.axis_names)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec_in, spec_in, spec_in, spec_in, spec_in,
                  jax.tree.map(lambda _: P(), make_ctx(()))),
        out_specs=(spec_p, P(), P()),
        check_vma=False,
    )
    def _step(a, r, s_bits, h_bits, power_planes, ctx):
        mask = _verify_core(a, r, s_bits, h_bits, ctx)
        # Exact int64 tallies without x64: powers arrive as four uint32 planes
        # of 16 bits each (see split_powers). Each plane sum is bounded by
        # N*2^16, safe in uint32 for N up to 2^15 validators per shard; psum
        # across the mesh and recombine host-side in Python ints (reference
        # tally semantics: types/validator_set.go:662 uses int64 power).
        valid_planes = jnp.where(mask[None], power_planes, 0)
        talled = jnp.sum(valid_planes, axis=tuple(range(1, valid_planes.ndim)))
        total = jnp.sum(power_planes, axis=tuple(range(1, power_planes.ndim)))
        for ax in mesh.axis_names:
            talled = jax.lax.psum(talled, ax)
            total = jax.lax.psum(total, ax)
        return mask, talled, total

    stepped = jax.jit(_step)

    def step(a, r, s_bits, h_bits, power_planes):
        import numpy as np

        shard_batch = tuple(
            d // m for d, m in zip(a.shape[1:], mesh.devices.shape)
        )
        mask, talled, total = stepped(
            a, r, s_bits, h_bits, power_planes, make_ctx(shard_batch)
        )

        def _join(planes) -> int:
            return sum(int(v) << (16 * k) for k, v in enumerate(np.asarray(planes)))

        ok = _join(talled) * 3 > _join(total) * 2
        return mask, ok

    return step


def split_powers(powers) -> "jnp.ndarray":
    """int64-range voting powers -> uint32[4, ...batch] planes of 16 bits
    each (exact for powers < 2^64; reference powers are int64)."""
    import numpy as np

    p = np.asarray(powers, dtype=np.uint64)
    planes = np.stack([(p >> np.uint64(16 * k)) & np.uint64(0xFFFF) for k in range(4)])
    return planes.astype(np.uint32)


def shard_batch_arrays(mesh: Mesh, *arrays):
    """Device-put host arrays with the trailing axes sharded over the mesh."""
    spec = P(None, *mesh.axis_names)
    sharding = NamedSharding(mesh, spec)
    return tuple(jax.device_put(a, sharding) for a in arrays)
