"""Chaos engine: schedule determinism, fault injectors, and the fast seeded
smoke net (tier-1). The long soak lives in test_chaos_soak.py (slow lane).

These run WITHOUT the `cryptography` wheel: the net tests use the plaintext
transport (p2p.plaintext=true), which is the point — chaos coverage must not
disappear in exactly the minimal containers where robustness regressions
hide."""

import asyncio
import os
import random

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.chaos import (
    ChaosEngine,
    ChaosSchedule,
    DeviceFaultError,
    DeviceFaultInjector,
    FaultEvent,
)
from tendermint_tpu.chaos.process import (
    corrupt_wal_tail,
    crash_wal,
    truncate_wal_tail,
)
from tendermint_tpu.config.config import test_config
from tendermint_tpu.consensus.wal import WAL, EndHeightMessage, iter_wal_messages
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.libs import metrics as M
from tendermint_tpu.node.node import Node
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

SEED = 20260803


# ---------------------------------------------------------------------------
# schedule determinism


def test_schedule_same_seed_reproduces_bit_for_bit():
    kw = dict(episodes=6, protected=(0,))
    s1 = ChaosSchedule.generate(SEED, 4, **kw)
    s2 = ChaosSchedule.generate(SEED, 4, **kw)
    assert s1 == s2
    assert s1.fingerprint() == s2.fingerprint()
    assert len(s1) > 0
    # a different seed must produce a different schedule
    s3 = ChaosSchedule.generate(SEED + 1, 4, **kw)
    assert s1 != s3
    assert s1.fingerprint() != s3.fingerprint()


def test_schedule_json_roundtrip_and_structure():
    s = ChaosSchedule.generate(SEED, 4, episodes=8)
    rt = ChaosSchedule.from_json(s.to_json())
    assert rt == s and rt.fingerprint() == s.fingerprint()
    # events are time-sorted, episodes paired
    times = [e.at for e in s]
    assert times == sorted(times)
    kinds = [e.kind for e in s]
    assert kinds.count("partition") == kinds.count("heal")
    assert kinds.count("crash") == kinds.count("restart")
    for e in s:
        if e.kind == "partition":
            groups = e.param_dict()["groups"]
            assert sorted(i for g in groups for i in g) == [0, 1, 2, 3]


def test_schedule_protected_nodes_never_crash():
    for seed in range(10):
        s = ChaosSchedule.generate(seed, 4, episodes=10, kinds=("crash",), protected=(0,))
        for e in s:
            if e.kind == "crash":
                assert e.param_dict()["target"] != 0


def test_schedule_rejects_unknown_kind():
    with pytest.raises(ValueError):
        FaultEvent.make(1.0, "meteor_strike")
    with pytest.raises(ValueError):
        ChaosSchedule.generate(1, 4, kinds=("meteor_strike",))


def test_schedule_rejects_all_protected_with_crash():
    """'protected means never crashed' must hold even when every node is
    protected — refuse loudly rather than crash a protected node."""
    with pytest.raises(ValueError):
        ChaosSchedule.generate(1, 2, kinds=("crash",), protected=(0, 1))


def test_schedule_catchup_kinds(tmp_path):
    """ISSUE 12: the catch-up fault kinds generate deterministically, carry
    well-formed params, round-trip through JSON, and the LocalChaosNet
    adapter arms a live node's ServeFaults for each of them."""
    kw = dict(episodes=9, kinds=("peer_stall", "peer_lie", "chunk_corrupt"))
    s = ChaosSchedule.generate(SEED, 3, **kw)
    assert s == ChaosSchedule.generate(SEED, 3, **kw)
    assert ChaosSchedule.from_json(s.to_json()) == s
    kinds = {e.kind for e in s}
    assert kinds <= {"peer_stall", "peer_lie", "chunk_corrupt"}
    for e in s:
        assert e.level == "catchup"
        p = e.param_dict()
        assert 0 <= p["target"] < 3
        if e.kind == "peer_stall":
            assert p["seconds"] > 0
        else:
            assert p["count"] >= 1

    # adapter methods install + arm ServeFaults on the target's reactors
    from tendermint_tpu.chaos.harness import LocalChaosNet

    class _Reactor:
        serve_faults = None

    node = type("N", (), {})()
    node.blocksync_reactor = _Reactor()
    node.statesync_reactor = _Reactor()
    net = LocalChaosNet(lambda i: None, 1)
    net.nodes[0] = node
    net.peer_stall(0, 2.0)
    sf = node.blocksync_reactor.serve_faults
    assert sf is not None and sf is node.statesync_reactor.serve_faults
    assert sf.block_stalled()
    net.peer_lie(0, 2)
    assert sf.take_block_lie()
    net.chunk_corrupt(0, 1)
    assert sf.take_chunk_corrupt()
    # arming a crashed node is a no-op, not an engine error
    net.nodes[0] = None
    net.peer_lie(0, 1)


# ---------------------------------------------------------------------------
# device fault injector


def test_device_injector_counts_and_heal():
    inj = DeviceFaultInjector()
    inj.arm_errors(2)
    with pytest.raises(DeviceFaultError):
        inj("rlc_submit")
    with pytest.raises(DeviceFaultError):
        inj("persig")
    inj("persig")  # armed count exhausted: passes
    assert inj.calls == 3
    assert [site for site, kind in inj.fired] == ["rlc_submit", "persig"]

    inj.set_persistent(True)
    for _ in range(3):
        with pytest.raises(DeviceFaultError):
            inj("probe")
    inj.heal()
    inj("probe")  # healed


def test_device_injector_hang_delays_call():
    import time

    inj = DeviceFaultInjector()
    inj.arm_hang(0.05)
    t0 = time.perf_counter()
    inj("rlc_submit")
    assert time.perf_counter() - t0 >= 0.045
    t0 = time.perf_counter()
    inj("rlc_submit")  # only the one call hangs
    assert time.perf_counter() - t0 < 0.04


# ---------------------------------------------------------------------------
# deterministic FuzzedConnection


class _RecordingStream:
    def __init__(self):
        self.writes = []

    async def read(self, n):
        return b"\x00" * n

    async def write(self, data):
        self.writes.append(bytes(data))

    def close(self):
        pass


async def _drive_fuzz(seed: int, n: int = 60):
    from tendermint_tpu.p2p.fuzz import FuzzConfig, FuzzedConnection

    inner = _RecordingStream()
    cfg = FuzzConfig(
        mode="drop", prob_drop_rw=0.5, start_after=0.0, max_delay=0.0, seed=seed
    )
    fc = FuzzedConnection(inner, cfg, clock=lambda: 100.0)
    for i in range(n):
        await fc.write(bytes([i]))
    return inner.writes


def test_fuzzed_connection_replay():
    """Same seed => byte-identical surviving-write sequence; different seed
    diverges (the satellite: fuzz runs must replay from their seed)."""
    a = asyncio.run(_drive_fuzz(7))
    b = asyncio.run(_drive_fuzz(7))
    c = asyncio.run(_drive_fuzz(8))
    assert a == b
    assert 0 < len(a) < 60  # some but not all writes survive p=0.5
    assert a != c


def test_fuzzed_connection_clock_injection():
    """start_after honors the injected clock, not wall time."""
    from tendermint_tpu.p2p.fuzz import FuzzConfig, FuzzedConnection

    now = [0.0]
    inner = _RecordingStream()
    cfg = FuzzConfig(mode="drop", prob_drop_rw=1.0, start_after=5.0, seed=3)
    fc = FuzzedConnection(inner, cfg, clock=lambda: now[0])

    async def run():
        for _ in range(10):
            await fc.write(b"x")  # inactive: all pass
        assert len(inner.writes) == 10
        now[0] = 6.0  # past start_after
        for _ in range(10):
            await fc.write(b"y")  # active, p=1: all dropped
        assert len(inner.writes) == 10

    asyncio.run(run())


def test_transport_derives_per_connection_rngs():
    """The i-th upgraded connection gets the same rng stream on every run
    (int-derived, not tuple/hash-derived — PYTHONHASHSEED must not matter)."""
    from tendermint_tpu.p2p.fuzz import FuzzConfig

    cfg = FuzzConfig(seed=99)
    streams = []
    for _run in range(2):
        run_streams = []
        for ordinal in (1, 2):
            rng = random.Random(cfg.seed * 1_000_003 + ordinal)
            run_streams.append([rng.random() for _ in range(5)])
        streams.append(run_streams)
    assert streams[0] == streams[1]
    assert streams[0][0] != streams[0][1]


# ---------------------------------------------------------------------------
# WAL process faults


def _fresh_wal(tmp_path, name, **kw):
    return WAL(str(tmp_path / name / "wal"), **kw)


def test_wal_truncate_and_corrupt_recover_prefix(tmp_path):
    wal = _fresh_wal(tmp_path, "a")
    for h in range(1, 6):
        wal.write_end_height(h)
    wal.close()
    path = wal.path
    full = list(iter_wal_messages(path))
    assert EndHeightMessage(5) in full

    truncate_wal_tail(path, drop_bytes=5)
    torn = list(iter_wal_messages(path))
    assert 0 < len(torn) < len(full)
    assert torn == full[: len(torn)]  # clean prefix, nothing reordered

    corrupt_wal_tail(path, rng=random.Random(1))
    rotten = list(iter_wal_messages(path))
    assert len(rotten) <= len(torn)
    assert rotten == full[: len(rotten)]


def test_crash_wal_drops_buffered_frames(tmp_path):
    """A hard kill loses the group-commit buffer — exactly the documented
    window — and the on-disk prefix stays replayable."""
    wal = _fresh_wal(
        tmp_path, "b", group_commit=True, group_commit_max_latency=10.0
    )
    wal.write_end_height(1)  # write_sync: durable
    wal.write(EndHeightMessage(2))  # buffered only
    crash_wal(wal)
    msgs = list(iter_wal_messages(wal.path))
    assert EndHeightMessage(1) in msgs
    assert EndHeightMessage(2) not in msgs
    # the dead object is inert, not EBADF-raising
    wal.close()


# ---------------------------------------------------------------------------
# engine dispatch


def test_engine_apply_dispatch_and_error_capture():
    class Adapter:
        def __init__(self):
            self.calls = []

        def device_error(self, count):
            self.calls.append(("device_error", count))

        async def partition(self, groups):
            self.calls.append(("partition", groups))

        def crash(self, target, wal_fault):
            raise RuntimeError("cannot crash")

    ad = Adapter()
    sched = ChaosSchedule(
        0,
        [
            FaultEvent.make(0.0, "device_error", count=2),
            FaultEvent.make(0.0, "partition", groups=[[0, 1], [2]]),
            FaultEvent.make(0.0, "crash", target=1, wal_fault=None),
            FaultEvent.make(0.0, "heal"),  # no adapter handler
        ],
    )
    eng = ChaosEngine(sched, ad)

    async def run():
        for ev in sched:
            await eng.apply(ev)

    before = dict(M.chaos_metrics().faults_injected._values)
    asyncio.run(run())
    assert ad.calls == [("device_error", 2), ("partition", [[0, 1], [2]])]
    assert len(eng.errors) == 2  # failing crash + missing heal handler
    assert len(eng.applied) == 2
    after = M.chaos_metrics().faults_injected._values
    injected = sum(after.values()) - sum(before.values())
    assert injected == 2  # only faults that actually APPLIED are counted


# ---------------------------------------------------------------------------
# switch reconnect tracking (satellite: task leak + attempts counter)


def test_switch_reconnect_tracked_counted_and_cancelled(monkeypatch):
    from tendermint_tpu.p2p import switch as switch_mod
    from tendermint_tpu.p2p.node_info import NodeInfo

    monkeypatch.setattr(switch_mod, "RECONNECT_BASE_DELAY", 0.01)

    class StubTransport:
        node_info = NodeInfo(
            node_id="ab" * 20, listen_addr="tcp://127.0.0.1:0",
            network="t", moniker="stub",
        )

        async def close(self):
            pass

    reg = M.Registry()
    pm = M.P2PMetrics(reg)

    async def run():
        sw = switch_mod.Switch(StubTransport(), metrics=pm)
        sw._running = True
        dials = []

        async def failing_dial(addr, persistent=False):
            dials.append(addr)
            raise ConnectionError("unreachable")

        sw.dial_peer = failing_dial
        sw._spawn_reconnect("pid@127.0.0.1:1", "pid")
        assert "pid" in sw._reconnect_tasks
        task = sw._reconnect_tasks["pid"]
        # spawning again while one is live must NOT stack a second loop
        sw._spawn_reconnect("pid@127.0.0.1:1", "pid")
        assert sw._reconnect_tasks["pid"] is task
        await asyncio.sleep(0.2)
        assert len(dials) >= 1
        assert pm.reconnect_attempts._values.get((), 0) >= 1
        await sw.stop()
        assert sw._reconnect_tasks == {}
        assert task.done()

    asyncio.run(run())


def test_switch_conn_filter_blocks_dial():
    from tendermint_tpu.p2p import switch as switch_mod
    from tendermint_tpu.p2p.node_info import NodeInfo

    class StubTransport:
        node_info = NodeInfo(
            node_id="cd" * 20, listen_addr="tcp://127.0.0.1:0",
            network="t", moniker="stub",
        )

        async def close(self):
            pass

    async def run():
        sw = switch_mod.Switch(StubTransport())
        sw.set_conn_filter(lambda pid: pid != "ef" * 20)
        with pytest.raises(ConnectionError):
            await sw.dial_peer(f"{'ef' * 20}@127.0.0.1:1")

    asyncio.run(run())


# ---------------------------------------------------------------------------
# the fast seeded chaos smoke: a 4-validator plaintext net survives a seeded
# partition/heal schedule with zero safety violations and keeps committing


def make_plain_net(n, tmp_path, chain="chaos-smoke", db_backend="memdb"):
    """Node factory for chaos nets: plaintext transport (runs in minimal
    containers without the `cryptography` wheel), explicit mesh (no pex)."""
    privs = [FilePV(gen_ed25519(bytes([20 + i]) * 32)) for i in range(n)]
    gen = GenesisDoc(
        chain_id=chain,
        validators=[GenesisValidator(p.get_pub_key(), 10) for p in privs],
    )

    def make_node(i):
        cfg = test_config()
        cfg.base.db_backend = db_backend
        # consensus-from-genesis: the blocksync wait_sync handoff can race at
        # height 0 on a tiny all-fresh net (everyone waits for someone to be
        # ahead); restarted nodes catch up via consensus catchup gossip
        # (block parts + commit votes for old heights) instead
        cfg.base.fast_sync = False
        cfg.rpc.laddr = ""
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.plaintext = True
        cfg.p2p.pex = False
        if db_backend == "memdb":
            cfg.root_dir = ""
            cfg.consensus.wal_path = str(tmp_path / f"wal{i}" / "wal")
        else:
            cfg.root_dir = str(tmp_path / f"node{i}")
            os.makedirs(cfg.root_dir, exist_ok=True)
        priv = FilePV(
            gen_ed25519(bytes([20 + i]) * 32),
            state_file=str(tmp_path / f"pv_state_{i}.json"),
        )
        return Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())

    return make_node


async def _wait_heights(net, pred, hard_timeout=300.0, poll=0.05):
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    while not pred():
        if loop.time() - t0 > hard_timeout:
            raise AssertionError(
                f"chaos net stalled: heights="
                f"{[n.block_store.height for n in net.live_nodes()]}"
            )
        await asyncio.sleep(poll)


def test_chaos_smoke_partition_heal(tmp_path):
    """Tier-1 smoke: seeded partition/heal schedule against a live 4-node
    net — progress through the fault, progress after heal, zero safety
    violations, and the schedule replays from its seed."""
    from tendermint_tpu.chaos.harness import LocalChaosNet

    kw = dict(
        episodes=2,
        kinds=("partition",),
        min_episode=1.0,
        max_episode=2.0,
        min_gap=0.3,
        max_gap=0.8,
        start_delay=0.8,
    )
    sched = ChaosSchedule.generate(SEED, 4, **kw)
    assert sched.fingerprint() == ChaosSchedule.generate(SEED, 4, **kw).fingerprint()

    async def run():
        net = LocalChaosNet(make_plain_net(4, tmp_path), 4)
        await net.start()
        try:
            engine = ChaosEngine(sched, net)
            task = engine.start()
            await task
            assert not engine.errors, engine.errors
            # liveness after heal: every node commits past the post-schedule top
            h0 = net.max_height()
            await _wait_heights(
                net,
                lambda: all(n.block_store.height >= h0 + 2 for n in net.live_nodes()),
            )
            net.assert_safety()
        finally:
            await net.stop()

    asyncio.run(run())
