"""Metrics registry + node instrumentation
(reference model: the per-service metrics.go files + prometheus endpoint)."""

import asyncio
import os

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.libs.metrics import Counter, Gauge, Histogram, NodeMetrics, Registry


def test_registry_exposition_format():
    reg = Registry()
    c = reg.counter("tm_test_total", "Things.", ("kind",))
    g = reg.gauge("tm_height", "Height.")
    h = reg.histogram("tm_lat", "Latency.", buckets=(0.1, 1.0))

    c.labels("a").inc()
    c.labels("a").inc(2)
    c.labels("b").inc()
    g.set(42)
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    text = reg.expose()
    assert 'tm_test_total{kind="a"} 3' in text
    assert 'tm_test_total{kind="b"} 1' in text
    assert "tm_height 42" in text
    assert 'tm_lat_bucket{le="0.1"} 1' in text
    assert 'tm_lat_bucket{le="1"} 2' in text
    assert 'tm_lat_bucket{le="+Inf"} 3' in text
    assert "tm_lat_count 3" in text
    assert "# TYPE tm_test_total counter" in text
    assert "# TYPE tm_height gauge" in text
    assert "# TYPE tm_lat histogram" in text


def test_gauge_replace_series_drops_departed_members():
    """replace_series (per-peer sampled gauges, e.g. clock skew): each pass
    replaces the whole labeled series set, so a departed member's series
    disappears instead of exposing a stale value forever."""
    import pytest

    reg = Registry()
    g = reg.gauge("tm_member_skew", "Skew.", ("peer",))
    g.replace_series({("a",): 0.5, ("b",): -0.25})
    text = reg.expose()
    assert 'tm_member_skew{peer="a"} 0.5' in text
    assert 'tm_member_skew{peer="b"} -0.25' in text
    # next sampling pass: b is gone
    g.replace_series({("a",): 0.75})
    text = reg.expose()
    assert 'tm_member_skew{peer="a"} 0.75' in text
    assert 'peer="b"' not in text
    with pytest.raises(ValueError):
        g.replace_series({("a", "extra"): 1.0})


def test_node_metrics_populated_and_served(tmp_path):
    """A running node populates consensus/mempool metrics and serves
    /metrics over HTTP when instrumentation is on."""
    import aiohttp

    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def run():
        import socket as s

        sock = s.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()

        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.root_dir = ""
        cfg.rpc.laddr = f"tcp://127.0.0.1:{port}"
        cfg.consensus.wal_path = str(tmp_path / "wal")
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        priv = FilePV(gen_ed25519(b"\x51" * 32))
        gen = GenesisDoc(chain_id="metrics-chain",
                         validators=[GenesisValidator(priv.get_pub_key(), 10)])
        node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
        await node.start()
        try:
            node.mempool.check_tx(b"m=1")
            await node.wait_for_height(3, timeout=60)

            # gauges track the chain
            text = node.metrics.expose()
            assert "tendermint_consensus_height" in text
            h = [l for l in text.splitlines() if l.startswith("tendermint_consensus_height ")]
            assert int(float(h[0].split()[-1])) >= 3
            assert "tendermint_consensus_validators 1" in text
            assert "tendermint_state_block_processing_time_count" in text

            # HTTP exposition via the RPC alias route
            async with aiohttp.ClientSession() as sess:
                async with sess.get(f"http://127.0.0.1:{port}/metrics") as resp:
                    assert resp.status == 200
                    body = await resp.text()
                    assert "tendermint_consensus_height" in body
                    assert "tendermint_mempool_size" in body

            # the DEDICATED prometheus listener (reference: node/node.go:1105
            # startPrometheusServer on instrumentation.prometheus_listen_addr)
            assert node.prometheus_server is not None
            pport = node.prometheus_server.port
            async with aiohttp.ClientSession() as sess:
                async with sess.get(f"http://127.0.0.1:{pport}/metrics") as resp:
                    assert resp.status == 200
                    body = await resp.text()
                    assert "tendermint_consensus_height" in body
        finally:
            await node.stop()

    asyncio.run(run())


def test_cpu_flush_populates_batch_verify_series():
    """Acceptance: ONE CPU-backend verify_batch flush produces non-zero
    tendermint_batch_verify_* series in the Prometheus exposition (the
    process-global registry every NodeMetrics exposition appends)."""
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto.keys import gen_ed25519
    from tendermint_tpu.libs.metrics import NodeMetrics

    priv = gen_ed25519(b"\x53" * 32)
    pk = priv.pub_key().bytes()
    msgs = [b"metrics-%d" % i for i in range(6)]
    sigs = [priv.sign(m) for m in msgs]
    assert B.verify_batch([pk] * 6, msgs, sigs, backend="cpu").all()

    text = NodeMetrics().expose()
    line = next(
        l for l in text.splitlines()
        if l.startswith("tendermint_batch_verify_flushes_total")
        and 'backend="cpu"' in l and 'path="cpu"' in l
    )
    assert float(line.split()[-1]) >= 1
    sigs_line = next(
        l for l in text.splitlines()
        if l.startswith("tendermint_batch_verify_sigs_total") and 'path="cpu"' in l
    )
    assert float(sigs_line.split()[-1]) >= 6
    assert "tendermint_batch_verify_batch_size_bucket" in text
    assert "tendermint_batch_verify_flush_seconds_count" in text
    # device-health gauges are part of the same exposition
    assert "tendermint_device_up" in text
    assert "tendermint_batch_verify_rlc_fallbacks_total" in text


def test_batch_verify_series_shared_across_nodes_registries():
    """Two NodeMetrics instances expose the SAME process-global batch
    series (the crypto pipeline is process-global), without duplicate
    registration errors."""
    from tendermint_tpu.libs.metrics import NodeMetrics, global_registry

    a, b = NodeMetrics(), NodeMetrics()
    assert global_registry() is global_registry()
    assert "tendermint_batch_verify_flushes_total" in a.expose()
    assert "tendermint_batch_verify_flushes_total" in b.expose()


def test_metrics_endpoint_404_when_disabled(tmp_path):
    import aiohttp

    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def run():
        import socket as s

        sock = s.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.root_dir = ""
        cfg.rpc.laddr = f"tcp://127.0.0.1:{port}"
        cfg.consensus.wal_path = str(tmp_path / "wal")
        priv = FilePV(gen_ed25519(b"\x52" * 32))
        gen = GenesisDoc(chain_id="m2", validators=[GenesisValidator(priv.get_pub_key(), 10)])
        node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
        await node.start()
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.get(f"http://127.0.0.1:{port}/metrics") as resp:
                    assert resp.status == 404
        finally:
            await node.stop()

    asyncio.run(run())
